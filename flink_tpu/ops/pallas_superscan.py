"""Pallas superscan: the whole T-step window dispatch as ONE TPU kernel.

The XLA superscan (`fused_window_pipeline._build_superscan`) expresses each
step as a chain of HLO ops inside `lax.scan`; on hardware that carries a
fixed cost per sequential op, its throughput is capped by per-step overhead
(~1 ms/step measured through the single-chip relay) plus the HBM round trip
of every intermediate (one-hot matrices, partial histograms). This kernel
removes both caps by fusing the full dispatch — ingest, fire, purge, T
steps — into a single `pallas_call`:

- the slice-ring count state lives in VMEM for the whole dispatch, laid out
  `[S * K/128, 128]` (slice-major blocks of 64x128 key tiles), so ingest
  and fire touch on-chip memory only;
- ingest is the same MXU one-hot trick as `ops/matmul_hist` (reference
  semantics: per-record HeapAggregatingState.add, WindowOperator.java:293),
  but the one-hot factors are built in VMEM per chunk and consumed by the
  MXU immediately — nothing spills to HBM;
- fire/purge control (slice positions, output rows, purge masks) is
  precomputed by the host planner and prefetched to SMEM
  (PrefetchScalarGridSpec), so the kernel's control flow is branch-cheap
  `@pl.when` predication, XLA-style static shapes throughout.

Measured on a v5e chip this runs the YSB sliding-count dispatch at ~1.0e9
records/s (T=64 steps x 1M records), ~15x the XLA superscan on the same
chip.

Segment encoding matches the host planner (`stage_superbatch`):
`idx = key_id * NSB + rel_slice`, negative = dropped. In-kernel it is
re-factored to `seg = rel_slice * K + key_id` so a segment's histogram
lands at rows `rel_slice * K/128 + key_id/128`, lane `key_id % 128` —
directly addressable as 64x128 blocks of the slice-ring state.

Supported aggregates: the count field, any number of add-combining VALUE
fields (sum/mean), and bounded-domain max fields
(`max_agg(domain_bits<=8)`). Weighted sums use the same three-term bf16
split-float trick as `matmul_hist.weighted_hist` (t0+t1+t2 == v bit-exactly
for |v| >= ~2**-110), so each record's f32 value enters the accumulator
unquantized. Bounded max runs on the MXU via two conditional nibble
histograms (pass 1 finds each segment's max high nibble, an MXU matvec
gathers it per record, pass 2 counts low nibbles among records matching it)
plus a dense elementwise maximum into the ring state — measured ~3x the
serial scatter unit at B=2^18. Unbounded min/max have no matmul form and
stay on the XLA superscan.
"""

from __future__ import annotations

import functools
from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from flink_tpu.ops.aggregators import VALUE

LANE = 128
# 1D int32 inputs are tiled T(1024) by XLA; chunk blocks must align to it
MIN_CHUNK = 1024


def _field_kind(f) -> str:
    """'add' | 'max8' | None (unsupported)."""
    if f.scatter == "add":
        return "add"
    if f.scatter == "max" and getattr(f, "domain_bits", None) is not None \
            and f.domain_bits <= 8:
        return "max8"
    return None


def supports(agg, K: int, R: int, S: int, NSB: int, chunk: int) -> bool:
    """Whether this aggregate/geometry can run on the pallas superscan."""
    if K % LANE != 0 or chunk % MIN_CHUNK != 0:
        return False
    value_fields = [f for f in agg.fields if f.source == VALUE]
    if any(_field_kind(f) is None for f in value_fields):
        return False
    # VMEM budget: persistent state + compact out buffers stay resident for
    # the whole dispatch; the per-chunk one-hot factors (oh_hiT [NSB*K/128,
    # CH] + oh_lo [CH, 128], bf16) are the dominant transient
    nf = len(value_fields)
    n_add = sum(1 for f in value_fields if _field_kind(f) == "add")
    n_max = nf - n_add
    state_bytes = S * K * 4 * (1 + nf) + R * K * 4 * (1 + nf)
    # count-only dispatches build int8 one-hot factors (1 byte), weighted
    # ones bf16 (2 bytes, needed for the split-float value terms)
    bytes_per = 1 if n_add == 0 else 2
    onehot_bytes = ((NSB * K // LANE) * chunk + chunk * LANE) * bytes_per
    if n_max:
        # nibble-pass transients: two [16*NSB*K/128, CH] int8 factor sets,
        # their [16*NSB*K/128, 128] int32 histograms, and the gather matmul
        # (the lane/row factors themselves are reused from the count path)
        hi16 = 16 * (NSB * K // LANE)
        onehot_bytes += 2 * hi16 * chunk + 2 * hi16 * LANE * 4 \
            + chunk * LANE * 4
    return state_bytes + onehot_bytes <= 15 * 1024 * 1024


@functools.lru_cache(maxsize=None)
def build_superscan(
    agg,
    K: int,
    S: int,
    NSB: int,
    F: int,
    SPW: int,
    R: int,
    T: int,
    B: int,
    CH: int,
    exact: bool,
    interpret: bool,
    fire_spws: Tuple[int, ...] = None,
):
    """Compile the fused T-step dispatch.

    `fire_spws` (shared partials): per-fire-slot window lengths in slices,
    length F — one gcd-granule ring serves several correlated window
    shapes, each slot combining its own slice-run length (Factor Windows);
    None keeps the uniform-SPW program unchanged.

    Returns run(smin, fire_pos, fire_valid, fire_row, purge_mask,
                count_in [S*KB,128] i32, field_states... , idx [T*B] i32,
                vals [T*B] f32 | None)
        -> (count_state, field_states..., count_out [R*KB,128],
            field_outs...)
    """
    assert B % CH == 0 and CH % MIN_CHUNK == 0
    spws = tuple(fire_spws) if fire_spws is not None else (SPW,) * F
    assert len(spws) == F, f"fire_spws has {len(spws)} slots, expected {F}"
    KB = K // LANE
    HI = NSB * KB
    C = B // CH
    vfields = [
        (f.name, jnp.dtype(f.dtype), _field_kind(f),
         getattr(f, "domain_bits", None))
        for f in agg.fields if f.source == VALUE
    ]
    nf = len(vfields)
    has_add = any(kind == "add" for _n, _d, kind, _b in vfields)
    has_max = any(kind == "max8" for _n, _d, kind, _b in vfields)

    def kernel(smin_ref, fpos_ref, fvalid_ref, frow_ref, purge_ref,
               count_in_ref, *rest):
        state_in = rest[:nf]
        idx_ref = rest[nf]
        off = nf + 1
        vals_ref = rest[off] if nf else None
        off += 1 if nf else 0
        count_ref = rest[off]
        states = rest[off + 1:off + 1 + nf]
        out_ref = rest[off + 1 + nf]
        outs = rest[off + 2 + nf:]

        t = pl.program_id(0)
        c = pl.program_id(1)

        @pl.when(jnp.logical_and(t == 0, c == 0))
        def _():
            count_ref[:] = count_in_ref[:]
            out_ref[:] = jnp.zeros_like(out_ref)
            for sref, sin in zip(states, state_in):
                sref[:] = sin[:]
            for o in outs:
                o[:] = jnp.zeros_like(o)

        # ---- ingest one chunk: one-hot factors in VMEM, MXU contraction ----
        # count-only dispatches use int8 factors with an int32 MXU
        # accumulator (exact, half the VMEM, measured ~1.7x the bf16 form);
        # weighted dispatches need bf16 for the split-float value terms
        oh_dt = jnp.int8 if not has_add else jnp.bfloat16
        acc_dt = jnp.int32 if not has_add else jnp.float32
        ii = idx_ref[:]                                   # [CH] i32
        kid = ii // NSB
        srel = ii % NSB
        seg = jnp.where(ii >= 0, srel * K + kid, -1)
        hi = seg // LANE
        lo = seg % LANE
        oh_hiT = (hi[None, :] == jax.lax.broadcasted_iota(
            jnp.int32, (HI, CH), 0)).astype(oh_dt)
        oh_lo = (lo[:, None] == jax.lax.broadcasted_iota(
            jnp.int32, (CH, LANE), 1)).astype(oh_dt)
        part = jax.lax.dot_general(
            oh_hiT, oh_lo, (((1,), (0,)), ((), ())),
            preferred_element_type=acc_dt).astype(jnp.int32)

        smin = smin_ref[t]
        for sr in range(NSB):
            col = (smin + sr) % S
            base = pl.multiple_of(col * KB, KB)
            count_ref[pl.ds(base, KB), :] += part[sr * KB:(sr + 1) * KB, :]

        if has_add:
            v = vals_ref[:].astype(jnp.float32)
            terms = []
            t0 = v.astype(jnp.bfloat16)
            terms.append(t0)
            if exact:
                r1 = v - t0.astype(jnp.float32)
                t1 = r1.astype(jnp.bfloat16)
                r2 = r1 - t1.astype(jnp.float32)
                terms.append(t1)
                terms.append(r2.astype(jnp.bfloat16))
            wacc = None
            for tm in terms:
                d = jax.lax.dot_general(
                    oh_hiT, oh_lo * tm[:, None], (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                wacc = d if wacc is None else wacc + d
            for sref, (_name, dt, kind, _b) in zip(states, vfields):
                if kind != "add":
                    continue
                w = wacc.astype(dt)
                for sr in range(NSB):
                    col = (smin + sr) % S
                    base = pl.multiple_of(col * KB, KB)
                    sref[pl.ds(base, KB), :] += w[sr * KB:(sr + 1) * KB, :]

        if has_max:
            # bounded-domain max on the MXU (no scatter): values are ints in
            # [0, 2^bits). Two conditional nibble histograms find each
            # segment's batch max; a dense elementwise maximum folds it into
            # the ring state. ~5x the TPU scatter unit at B=256K.
            #   pass 1: h1[v_hi, seg] = count  -> maxhi[seg]
            #   gather: g_r = maxhi[seg_r] via one MXU matvec (no scatter/
            #           gather unit: M = ohT @ maxhi, then lane-select)
            #   pass 2: h2[v_lo, seg | v_hi==maxhi] = count -> maxlo[seg]
            mv = jnp.clip(vals_ref[:].astype(jnp.int32), 0, 255)
            vhi = mv >> 4
            vlo = mv & 15
            valid = ii >= 0
            i8 = jnp.int8
            # reuse the count path's lane factor (already int8 unless an
            # add-field forced bf16 factors)
            oh_lo8 = oh_lo if oh_dt == i8 else oh_lo.astype(i8)
            row1 = jnp.where(valid, vhi * HI + hi, -1)
            ohm1 = (row1[None, :] == jax.lax.broadcasted_iota(
                jnp.int32, (16 * HI, CH), 0)).astype(i8)
            h1 = jax.lax.dot_general(
                ohm1, oh_lo8, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            maxhi = jnp.full((HI, LANE), -1, jnp.int32)
            for h in range(16):               # ascending: last hit wins
                maxhi = jnp.where(h1[h * HI:(h + 1) * HI, :] > 0, h, maxhi)
            # per-record gather of maxhi[seg_r] as an MXU matvec (reusing
            # the count path's row factor)
            M = jax.lax.dot_general(
                oh_hiT.astype(jnp.bfloat16), maxhi.astype(jnp.bfloat16),
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)           # [CH, LANE]
            g = jnp.sum(oh_lo8.astype(jnp.float32) * M, axis=1)
            cond = valid & (vhi == g.astype(jnp.int32))
            row2 = jnp.where(cond, vlo * HI + hi, -1)
            ohm2 = (row2[None, :] == jax.lax.broadcasted_iota(
                jnp.int32, (16 * HI, CH), 0)).astype(i8)
            h2 = jax.lax.dot_general(
                ohm2, oh_lo8, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            maxlo = jnp.full((HI, LANE), -1, jnp.int32)
            for h in range(16):
                maxlo = jnp.where(h2[h * HI:(h + 1) * HI, :] > 0, h, maxlo)
            chunkmax = jnp.where(maxhi >= 0, maxhi * 16 + maxlo, -1)
            for sref, (_name, _dt, kind, _b) in zip(states, vfields):
                if kind != "max8":
                    continue
                for sr in range(NSB):
                    col = (smin + sr) % S
                    base = pl.multiple_of(col * KB, KB)
                    sref[pl.ds(base, KB), :] = jnp.maximum(
                        sref[pl.ds(base, KB), :],
                        chunkmax[sr * KB:(sr + 1) * KB, :])

        # ---- fire + purge once the step's last chunk is ingested ----
        @pl.when(c == C - 1)
        def _():
            for f in range(F):
                @pl.when(fvalid_ref[t, f] > 0)
                def _(f=f):
                    fp = fpos_ref[t, f]
                    row = frow_ref[t, f]
                    acc = jnp.zeros((KB, LANE), jnp.int32)
                    for w in range(spws[f]):
                        col = (fp + w) % S
                        acc += count_ref[
                            pl.ds(pl.multiple_of(col * KB, KB), KB), :]
                    out_ref[pl.ds(row * KB, KB), :] = acc
                    for sref, oref, (_n, dt, kind, _b) in zip(
                            states, outs, vfields):
                        if kind == "max8":
                            sacc = jnp.full((KB, LANE), -1, dt)
                            for w in range(spws[f]):
                                col = (fp + w) % S
                                sacc = jnp.maximum(sacc, sref[
                                    pl.ds(pl.multiple_of(col * KB, KB), KB),
                                    :])
                        else:
                            sacc = jnp.zeros((KB, LANE), dt)
                            for w in range(spws[f]):
                                col = (fp + w) % S
                                sacc += sref[
                                    pl.ds(pl.multiple_of(col * KB, KB), KB),
                                    :]
                        oref[pl.ds(row * KB, KB), :] = sacc
            for s in range(S):
                @pl.when(purge_ref[t, s] == 0)
                def _(s=s):
                    base = pl.multiple_of(s * KB, KB)
                    count_ref[pl.ds(base, KB), :] = jnp.zeros(
                        (KB, LANE), jnp.int32)
                    for sref, (_n, dt, kind, _b) in zip(states, vfields):
                        ident = -1 if kind == "max8" else 0
                        sref[pl.ds(base, KB), :] = jnp.full(
                            (KB, LANE), ident, dt)

    state_spec = pl.BlockSpec((S * KB, LANE), lambda t, c, *_: (0, 0))
    out_spec = pl.BlockSpec((R * KB, LANE), lambda t, c, *_: (0, 0))
    chunk_spec = pl.BlockSpec((CH,), lambda t, c, *_: (t * C + c,))

    in_specs = [state_spec]                      # count_in
    in_specs += [state_spec] * nf                # field states in
    in_specs += [chunk_spec]                     # idx
    if nf:
        in_specs += [chunk_spec]                 # vals
    out_specs = [state_spec] + [state_spec] * nf + [out_spec] + [out_spec] * nf

    out_shape = [jax.ShapeDtypeStruct((S * KB, LANE), jnp.int32)]
    out_shape += [jax.ShapeDtypeStruct((S * KB, LANE), dt)
                  for _n, dt, _k, _b in vfields]
    out_shape += [jax.ShapeDtypeStruct((R * KB, LANE), jnp.int32)]
    out_shape += [jax.ShapeDtypeStruct((R * KB, LANE), dt)
                  for _n, dt, _k, _b in vfields]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(T, C),
        in_specs=in_specs,
        out_specs=out_specs,
    )
    fn = pl.pallas_call(
        kernel, grid_spec=grid_spec, out_shape=out_shape, interpret=interpret,
    )

    @jax.jit
    def run(smin, fpos, fvalid, frow, purge, count_in, states, idx, vals):
        args = [count_in, *states, idx]
        if nf:
            args.append(vals)
        res = fn(smin, fpos, fvalid, frow, purge, *args)
        count_state = res[0]
        field_states = tuple(res[1:1 + nf])
        count_out = res[1 + nf]
        field_outs = tuple(res[2 + nf:])
        return count_state, field_states, count_out, field_outs

    return run


# ------------------------------------------------------------------
# layout converters between the canonical [K, S] state (XLA superscan,
# snapshots) and the kernel's slice-major [S*KB, LANE] layout
# ------------------------------------------------------------------

def to_kernel_layout(arr, K: int, S: int):
    """[K, S] -> [S*K/128, 128] (numpy or jax array)."""
    xp = jnp if isinstance(arr, jax.Array) else np
    return xp.transpose(arr, (1, 0)).reshape(S * (K // LANE), LANE)


def from_kernel_layout(arr, K: int, S: int):
    """[S*K/128, 128] -> [K, S]."""
    xp = jnp if isinstance(arr, jax.Array) else np
    return xp.transpose(arr.reshape(S, K), (1, 0))


def rows_to_keys(out, R: int, K: int):
    """Compact fire buffer [R*K/128, 128] -> [R, K]."""
    return out.reshape(R, K)


# ------------------------------------------------------------------
# global-window superscan: keyed-partial -> cross-segment fold as ONE
# T-step kernel (the Nexmark-Q7 shape: per-window GLOBAL max/min/sum)
# ------------------------------------------------------------------

def supports_global(agg, S: int, R: int, NSB: int, chunk: int) -> bool:
    """Whether an aggregate/geometry can run on the fused global scan
    kernel: the [S] slice ring and the [R] out rows each live in one
    128-lane vector row, the purge mask unrolls over S scalar reads, and
    every field folds elementwise (any add/min/max, bounded or not — the
    fold needs no scatter unit and no one-hot matrices)."""
    from flink_tpu.ops.aggregators import VALUE

    if S > 32 or R > LANE or NSB > 8 or chunk % MIN_CHUNK != 0:
        return False
    return all(f.scatter in ("add", "min", "max")
               for f in agg.fields if f.source == VALUE)


@functools.lru_cache(maxsize=None)
def build_global_superscan(
    agg,
    S: int,
    NSB: int,
    F: int,
    SPW: int,
    R: int,
    T: int,
    B: int,
    CH: int,
    interpret: bool,
    fire_spws: Tuple[int, ...] = None,
):
    """Compile the fused T-step GLOBAL-window dispatch as one kernel.

    The XLA global scan (ops/superscan.make_global_scan_step) already
    removes the [K, S] ring; this kernel additionally removes the
    per-step lax.scan overhead: ingest partials, slice-ring folds, fires
    and purges for all T steps run as one pallas_call with the [S] ring
    resident in a single VMEM vector row. Each chunk costs NSB masked
    whole-chunk reductions — no scatter unit, no one-hot factors, no HBM
    round trips. Out rows are scalars packed into one [1, 128] row.

    Returns run(smin, fpos, fvalid, frow, purge,
                count_in [1,128] i32, states ([1,128] dt, ...),
                idx [T*B] i32, vals [T*B] f32 | None)
        -> (count_state, field_states, count_out [1,128], field_outs)"""
    assert B % CH == 0 and CH % MIN_CHUNK == 0
    assert S <= 32 and R <= LANE
    spws = tuple(fire_spws) if fire_spws is not None else (SPW,) * F
    assert len(spws) == F
    C = B // CH
    vfields = [
        (f.name, jnp.dtype(f.dtype), f.scatter, f.identity)
        for f in agg.fields if f.source == VALUE
    ]
    nf = len(vfields)

    def _ident(dt, scatter):
        from flink_tpu.ops.aggregators import scan_identity

        return scan_identity(dt, scatter)

    def kernel(smin_ref, fpos_ref, fvalid_ref, frow_ref, purge_ref,
               count_in_ref, *rest):
        state_in = rest[:nf]
        idx_ref = rest[nf]
        off = nf + 1
        vals_ref = rest[off] if nf else None
        off += 1 if nf else 0
        count_ref = rest[off]
        states = rest[off + 1:off + 1 + nf]
        out_ref = rest[off + 1 + nf]
        outs = rest[off + 2 + nf:]

        t = pl.program_id(0)
        c = pl.program_id(1)
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, LANE), 1)

        @pl.when(jnp.logical_and(t == 0, c == 0))
        def _():
            count_ref[:] = count_in_ref[:]
            out_ref[:] = jnp.zeros_like(out_ref)
            for sref, sin in zip(states, state_in):
                sref[:] = sin[:]
            for oref, (_n, dt, scatter, _i) in zip(outs, vfields):
                oref[:] = jnp.full_like(oref, _ident(dt, scatter))

        # ---- ingest one chunk: NSB masked whole-chunk folds ----
        ii = idx_ref[:]
        srel = jnp.where(ii >= 0, ii % NSB, -1)
        smin = smin_ref[t]
        for sr in range(NSB):
            col = (smin + sr) % S
            sel = lane == col
            cpart = jnp.sum((srel == sr).astype(jnp.int32))
            count_ref[:] = jnp.where(sel, count_ref[:] + cpart, count_ref[:])
            if nf:
                v = vals_ref[:]
                for sref, (_n, dt, scatter, _i) in zip(states, vfields):
                    ident = jnp.asarray(_ident(dt, scatter), dt)
                    lanev = jnp.where(srel == sr, v.astype(dt), ident)
                    if scatter == "add":
                        part = lanev.sum()
                        sref[:] = jnp.where(sel, sref[:] + part, sref[:])
                    elif scatter == "min":
                        part = lanev.min()
                        sref[:] = jnp.where(
                            sel, jnp.minimum(sref[:], part), sref[:])
                    else:
                        part = lanev.max()
                        sref[:] = jnp.where(
                            sel, jnp.maximum(sref[:], part), sref[:])

        # ---- fire + purge once the step's last chunk is ingested ----
        @pl.when(c == C - 1)
        def _():
            for f in range(F):
                @pl.when(fvalid_ref[t, f] > 0)
                def _(f=f):
                    fp = fpos_ref[t, f]
                    row = frow_ref[t, f]
                    inwin = (jnp.remainder(lane - fp, S) < spws[f]) & \
                        (lane < S)
                    rowsel = lane == row
                    cnt = jnp.sum(jnp.where(inwin, count_ref[:], 0))
                    out_ref[:] = jnp.where(rowsel, cnt, out_ref[:])
                    for sref, oref, (_n, dt, scatter, _i) in zip(
                            states, outs, vfields):
                        ident = jnp.asarray(_ident(dt, scatter), dt)
                        masked = jnp.where(inwin, sref[:], ident)
                        if scatter == "add":
                            folded = masked.sum()
                        elif scatter == "min":
                            folded = masked.min()
                        else:
                            folded = masked.max()
                        oref[:] = jnp.where(rowsel, folded, oref[:])
            # purge: S scalar reads build the expired-lane mask
            keep = jnp.ones((1, LANE), jnp.bool_)
            for s in range(S):
                keep = keep & ~((lane == s) & (purge_ref[t, s] == 0))
            count_ref[:] = jnp.where(keep, count_ref[:], 0)
            for sref, (_n, dt, scatter, _i) in zip(states, vfields):
                sref[:] = jnp.where(
                    keep, sref[:], jnp.asarray(_ident(dt, scatter), dt))

    row_spec = pl.BlockSpec((1, LANE), lambda t, c, *_: (0, 0))
    chunk_spec = pl.BlockSpec((CH,), lambda t, c, *_: (t * C + c,))

    in_specs = [row_spec] + [row_spec] * nf + [chunk_spec]
    if nf:
        in_specs += [chunk_spec]
    out_specs = [row_spec] * (1 + nf) + [row_spec] * (1 + nf)
    out_shape = [jax.ShapeDtypeStruct((1, LANE), jnp.int32)]
    out_shape += [jax.ShapeDtypeStruct((1, LANE), dt)
                  for _n, dt, _s, _i in vfields]
    out_shape += [jax.ShapeDtypeStruct((1, LANE), jnp.int32)]
    out_shape += [jax.ShapeDtypeStruct((1, LANE), dt)
                  for _n, dt, _s, _i in vfields]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(T, C),
        in_specs=in_specs,
        out_specs=out_specs,
    )
    fn = pl.pallas_call(
        kernel, grid_spec=grid_spec, out_shape=out_shape, interpret=interpret,
    )

    @jax.jit
    def run(smin, fpos, fvalid, frow, purge, count_in, states, idx, vals):
        args = [count_in, *states, idx]
        if nf:
            args.append(vals)
        res = fn(smin, fpos, fvalid, frow, purge, *args)
        count_state = res[0]
        field_states = tuple(res[1:1 + nf])
        count_out = res[1 + nf]
        field_outs = tuple(res[2 + nf:])
        return count_state, field_states, count_out, field_outs

    return run
