"""Pallas superscan: the whole T-step window dispatch as ONE TPU kernel.

The XLA superscan (`fused_window_pipeline._build_superscan`) expresses each
step as a chain of HLO ops inside `lax.scan`; on hardware that carries a
fixed cost per sequential op, its throughput is capped by per-step overhead
(~1 ms/step measured through the single-chip relay) plus the HBM round trip
of every intermediate (one-hot matrices, partial histograms). This kernel
removes both caps by fusing the full dispatch — ingest, fire, purge, T
steps — into a single `pallas_call`:

- the slice-ring count state lives in VMEM for the whole dispatch, laid out
  `[S * K/128, 128]` (slice-major blocks of 64x128 key tiles), so ingest
  and fire touch on-chip memory only;
- ingest is the same MXU one-hot trick as `ops/matmul_hist` (reference
  semantics: per-record HeapAggregatingState.add, WindowOperator.java:293),
  but the one-hot factors are built in VMEM per chunk and consumed by the
  MXU immediately — nothing spills to HBM;
- fire/purge control (slice positions, output rows, purge masks) is
  precomputed by the host planner and prefetched to SMEM
  (PrefetchScalarGridSpec), so the kernel's control flow is branch-cheap
  `@pl.when` predication, XLA-style static shapes throughout.

Measured on a v5e chip this runs the YSB sliding-count dispatch at ~1.0e9
records/s (T=64 steps x 1M records), ~15x the XLA superscan on the same
chip.

Segment encoding matches the host planner (`stage_superbatch`):
`idx = key_id * NSB + rel_slice`, negative = dropped. In-kernel it is
re-factored to `seg = rel_slice * K + key_id` so a segment's histogram
lands at rows `rel_slice * K/128 + key_id/128`, lane `key_id % 128` —
directly addressable as 64x128 blocks of the slice-ring state.

Supported aggregates: the count field plus any number of add-combining
VALUE fields (sum/mean). Weighted sums use the same three-term bf16
split-float trick as `matmul_hist.weighted_hist` (t0+t1+t2 == v bit-exactly
for |v| >= ~2**-110), so each record's f32 value enters the accumulator
unquantized. min/max fields have no matmul form; callers keep those on the
XLA superscan.
"""

from __future__ import annotations

import functools
from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from flink_tpu.ops.aggregators import VALUE

LANE = 128
# 1D int32 inputs are tiled T(1024) by XLA; chunk blocks must align to it
MIN_CHUNK = 1024


def supports(agg, K: int, R: int, S: int, NSB: int, chunk: int) -> bool:
    """Whether this aggregate/geometry can run on the pallas superscan."""
    if K % LANE != 0 or chunk % MIN_CHUNK != 0:
        return False
    value_fields = [f for f in agg.fields if f.source == VALUE]
    if any(f.scatter != "add" for f in value_fields):
        return False
    # VMEM budget: persistent state + compact out buffers stay resident for
    # the whole dispatch; the per-chunk one-hot factors (oh_hiT [NSB*K/128,
    # CH] + oh_lo [CH, 128], bf16) are the dominant transient
    nf = len(value_fields)
    state_bytes = S * K * 4 * (1 + nf) + R * K * 4 * (1 + nf)
    # count-only dispatches build int8 one-hot factors (1 byte), weighted
    # ones bf16 (2 bytes, needed for the split-float value terms)
    bytes_per = 1 if nf == 0 else 2
    onehot_bytes = ((NSB * K // LANE) * chunk + chunk * LANE) * bytes_per
    return state_bytes + onehot_bytes <= 15 * 1024 * 1024


@functools.lru_cache(maxsize=None)
def build_superscan(
    agg,
    K: int,
    S: int,
    NSB: int,
    F: int,
    SPW: int,
    R: int,
    T: int,
    B: int,
    CH: int,
    exact: bool,
    interpret: bool,
):
    """Compile the fused T-step dispatch.

    Returns run(smin, fire_pos, fire_valid, fire_row, purge_mask,
                count_in [S*KB,128] i32, field_states... , idx [T*B] i32,
                vals [T*B] f32 | None)
        -> (count_state, field_states..., count_out [R*KB,128],
            field_outs...)
    """
    assert B % CH == 0 and CH % MIN_CHUNK == 0
    KB = K // LANE
    HI = NSB * KB
    C = B // CH
    vfields = [
        (f.name, jnp.dtype(f.dtype)) for f in agg.fields if f.source == VALUE
    ]
    nf = len(vfields)

    def kernel(smin_ref, fpos_ref, fvalid_ref, frow_ref, purge_ref,
               count_in_ref, *rest):
        state_in = rest[:nf]
        idx_ref = rest[nf]
        off = nf + 1
        vals_ref = rest[off] if nf else None
        off += 1 if nf else 0
        count_ref = rest[off]
        states = rest[off + 1:off + 1 + nf]
        out_ref = rest[off + 1 + nf]
        outs = rest[off + 2 + nf:]

        t = pl.program_id(0)
        c = pl.program_id(1)

        @pl.when(jnp.logical_and(t == 0, c == 0))
        def _():
            count_ref[:] = count_in_ref[:]
            out_ref[:] = jnp.zeros_like(out_ref)
            for sref, sin in zip(states, state_in):
                sref[:] = sin[:]
            for o in outs:
                o[:] = jnp.zeros_like(o)

        # ---- ingest one chunk: one-hot factors in VMEM, MXU contraction ----
        # count-only dispatches use int8 factors with an int32 MXU
        # accumulator (exact, half the VMEM, measured ~1.7x the bf16 form);
        # weighted dispatches need bf16 for the split-float value terms
        oh_dt = jnp.int8 if nf == 0 else jnp.bfloat16
        acc_dt = jnp.int32 if nf == 0 else jnp.float32
        ii = idx_ref[:]                                   # [CH] i32
        kid = ii // NSB
        srel = ii % NSB
        seg = jnp.where(ii >= 0, srel * K + kid, -1)
        hi = seg // LANE
        lo = seg % LANE
        oh_hiT = (hi[None, :] == jax.lax.broadcasted_iota(
            jnp.int32, (HI, CH), 0)).astype(oh_dt)
        oh_lo = (lo[:, None] == jax.lax.broadcasted_iota(
            jnp.int32, (CH, LANE), 1)).astype(oh_dt)
        part = jax.lax.dot_general(
            oh_hiT, oh_lo, (((1,), (0,)), ((), ())),
            preferred_element_type=acc_dt).astype(jnp.int32)

        smin = smin_ref[t]
        for sr in range(NSB):
            col = (smin + sr) % S
            base = pl.multiple_of(col * KB, KB)
            count_ref[pl.ds(base, KB), :] += part[sr * KB:(sr + 1) * KB, :]

        if nf:
            v = vals_ref[:].astype(jnp.float32)
            terms = []
            t0 = v.astype(jnp.bfloat16)
            terms.append(t0)
            if exact:
                r1 = v - t0.astype(jnp.float32)
                t1 = r1.astype(jnp.bfloat16)
                r2 = r1 - t1.astype(jnp.float32)
                terms.append(t1)
                terms.append(r2.astype(jnp.bfloat16))
            wacc = None
            for tm in terms:
                d = jax.lax.dot_general(
                    oh_hiT, oh_lo * tm[:, None], (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                wacc = d if wacc is None else wacc + d
            for sref, (_name, dt) in zip(states, vfields):
                w = wacc.astype(dt)
                for sr in range(NSB):
                    col = (smin + sr) % S
                    base = pl.multiple_of(col * KB, KB)
                    sref[pl.ds(base, KB), :] += w[sr * KB:(sr + 1) * KB, :]

        # ---- fire + purge once the step's last chunk is ingested ----
        @pl.when(c == C - 1)
        def _():
            for f in range(F):
                @pl.when(fvalid_ref[t, f] > 0)
                def _(f=f):
                    fp = fpos_ref[t, f]
                    row = frow_ref[t, f]
                    acc = jnp.zeros((KB, LANE), jnp.int32)
                    for w in range(SPW):
                        col = (fp + w) % S
                        acc += count_ref[
                            pl.ds(pl.multiple_of(col * KB, KB), KB), :]
                    out_ref[pl.ds(row * KB, KB), :] = acc
                    for sref, oref, (_n, dt) in zip(states, outs, vfields):
                        sacc = jnp.zeros((KB, LANE), dt)
                        for w in range(SPW):
                            col = (fp + w) % S
                            sacc += sref[
                                pl.ds(pl.multiple_of(col * KB, KB), KB), :]
                        oref[pl.ds(row * KB, KB), :] = sacc
            for s in range(S):
                @pl.when(purge_ref[t, s] == 0)
                def _(s=s):
                    base = pl.multiple_of(s * KB, KB)
                    count_ref[pl.ds(base, KB), :] = jnp.zeros(
                        (KB, LANE), jnp.int32)
                    for sref, (_n, dt) in zip(states, vfields):
                        sref[pl.ds(base, KB), :] = jnp.zeros((KB, LANE), dt)

    state_spec = pl.BlockSpec((S * KB, LANE), lambda t, c, *_: (0, 0))
    out_spec = pl.BlockSpec((R * KB, LANE), lambda t, c, *_: (0, 0))
    chunk_spec = pl.BlockSpec((CH,), lambda t, c, *_: (t * C + c,))

    in_specs = [state_spec]                      # count_in
    in_specs += [state_spec] * nf                # field states in
    in_specs += [chunk_spec]                     # idx
    if nf:
        in_specs += [chunk_spec]                 # vals
    out_specs = [state_spec] + [state_spec] * nf + [out_spec] + [out_spec] * nf

    out_shape = [jax.ShapeDtypeStruct((S * KB, LANE), jnp.int32)]
    out_shape += [jax.ShapeDtypeStruct((S * KB, LANE), dt) for _, dt in vfields]
    out_shape += [jax.ShapeDtypeStruct((R * KB, LANE), jnp.int32)]
    out_shape += [jax.ShapeDtypeStruct((R * KB, LANE), dt) for _, dt in vfields]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(T, C),
        in_specs=in_specs,
        out_specs=out_specs,
    )
    fn = pl.pallas_call(
        kernel, grid_spec=grid_spec, out_shape=out_shape, interpret=interpret,
    )

    @jax.jit
    def run(smin, fpos, fvalid, frow, purge, count_in, states, idx, vals):
        args = [count_in, *states, idx]
        if nf:
            args.append(vals)
        res = fn(smin, fpos, fvalid, frow, purge, *args)
        count_state = res[0]
        field_states = tuple(res[1:1 + nf])
        count_out = res[1 + nf]
        field_outs = tuple(res[2 + nf:])
        return count_state, field_states, count_out, field_outs

    return run


# ------------------------------------------------------------------
# layout converters between the canonical [K, S] state (XLA superscan,
# snapshots) and the kernel's slice-major [S*KB, LANE] layout
# ------------------------------------------------------------------

def to_kernel_layout(arr, K: int, S: int):
    """[K, S] -> [S*K/128, 128] (numpy or jax array)."""
    xp = jnp if isinstance(arr, jax.Array) else np
    return xp.transpose(arr, (1, 0)).reshape(S * (K // LANE), LANE)


def from_kernel_layout(arr, K: int, S: int):
    """[S*K/128, 128] -> [K, S]."""
    xp = jnp if isinstance(arr, jax.Array) else np
    return xp.transpose(arr.reshape(S, K), (1, 0))


def rows_to_keys(out, R: int, K: int):
    """Compact fire buffer [R*K/128, 128] -> [R, K]."""
    return out.reshape(R, K)
