"""Device kernels for windowed aggregation: scatter-combine ingest, windowed
gather-reduce firing, slice purge.

This is the TPU replacement for the reference's per-record hot loop
(WindowOperator.processElement :293 → HeapAggregatingState.add :94 →
CopyOnWriteStateMap.transform): instead of one hash-map mutation per
(record × window), a whole batch of records is folded into HBM-resident
[keys, slices] accumulator columns with ONE fused XLA program, and window
firing is a gather + reduction over the window's slice range (the pane/slice
decomposition proven by the reference SQL runtime's tvf/slicing assigners).

Shapes are static everywhere (K = key capacity, S = slice-ring capacity,
B = padded batch size); invalid lanes carry the out-of-bounds sentinel
INVALID_INDEX and are dropped by scatter mode='drop' (negative indices would
wrap, NumPy-style, so the sentinel must be high, not -1). All functions are pure and jit-compiled once per
(shape, aggregator) combination.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flink_tpu.ops.aggregators import AccField, DeviceAggregator, ONE

# Out-of-bounds scatter sentinel for invalid lanes (dropped by mode='drop').
INVALID_INDEX = np.int32(2**31 - 1)


def _scatter(acc: jnp.ndarray, kid: jnp.ndarray, spos: jnp.ndarray, vals: jnp.ndarray, op: str) -> jnp.ndarray:
    ref = acc.at[kid, spos]
    if op == "add":
        return ref.add(vals, mode="drop")
    if op == "min":
        return ref.min(vals, mode="drop")
    if op == "max":
        return ref.max(vals, mode="drop")
    raise ValueError(op)


def _combine(vals: jnp.ndarray, op: str, axis: int) -> jnp.ndarray:
    if op == "add":
        return vals.sum(axis=axis)
    if op == "min":
        return vals.min(axis=axis)
    if op == "max":
        return vals.max(axis=axis)
    raise ValueError(op)


# ---------------------------------------------------------------------------
# ingest
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def make_ingest_fn(agg: DeviceAggregator, *, track_touch: bool, donate: bool = True):
    """Build the jitted ingest step.

    ingest(acc: {field: [K,S]}, count: i32[K,S], kid: i32[B], spos: i32[B],
           vals: f[B]) -> (acc', count', touch: bool[K,S]?)

    kid/spos carry INVALID_INDEX for invalid (padding / late-dropped) lanes.
    `touch` marks (key, slice) cells written by this batch — used for
    late-data re-fire masks (the per-record late FIRE of
    WindowOperator.processElement :419 becomes a masked batched re-fire).
    """

    def ingest(acc: Dict[str, jnp.ndarray], count: jnp.ndarray,
               kid: jnp.ndarray, spos: jnp.ndarray, vals: jnp.ndarray):
        new_acc = {}
        for f in agg.fields:
            src = jnp.ones(vals.shape, dtype=f.dtype) if f.source == ONE else vals.astype(f.dtype)
            new_acc[f.name] = _scatter(acc[f.name], kid, spos, src, f.scatter)
        new_count = count.at[kid, spos].add(
            jnp.ones(kid.shape, dtype=count.dtype), mode="drop"
        )
        if track_touch:
            touch = jnp.zeros(count.shape, dtype=jnp.bool_).at[kid, spos].set(
                True, mode="drop"
            )
            return new_acc, new_count, touch
        return new_acc, new_count

    donate_args = (0, 1) if donate else ()
    return jax.jit(ingest, donate_argnums=donate_args)


# ---------------------------------------------------------------------------
# fire
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def make_fire_fn(agg: DeviceAggregator, *, masked: bool):
    """Build the jitted window-fire step.

    fire(acc, count, positions: i32[spw], touch?: bool[K,S])
        -> (result: [K], counts: i32[K], mask: bool[K])

    Gathers the window's slice columns, combines them per key
    (segment-reduce along the slice axis), and computes the emission mask:
    keys with any data in the window — intersected with the batch-touch mask
    for late re-fires (only keys updated since the last fire re-emit,
    matching the per-record late-FIRE semantics key-for-key).
    """

    def fire(acc: Dict[str, jnp.ndarray], count: jnp.ndarray,
             positions: jnp.ndarray, touch: jnp.ndarray = None):
        combined = {}
        for f in agg.fields:
            cols = jnp.take(acc[f.name], positions, axis=1)  # [K, spw]
            combined[f.name] = _combine(cols, f.scatter, axis=1)
        cnt = jnp.take(count, positions, axis=1).sum(axis=1)
        mask = cnt > 0
        if masked:
            touched = jnp.take(touch, positions, axis=1).any(axis=1)
            mask = mask & touched
        result = agg.extract(combined)
        return result.astype(agg.result_dtype), cnt, mask

    return jax.jit(fire)


# ---------------------------------------------------------------------------
# purge
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def make_purge_fn(agg: DeviceAggregator, num_positions: int):
    """Reset expired slice columns to the aggregator identity.

    purge(acc, count, positions: i32[P]) — padded with INVALID_INDEX (dropped).
    The ring reuses purged columns for future slices (cleanup timers at
    window.maxTimestamp()+allowedLateness become a purge frontier).
    """

    def purge(acc: Dict[str, jnp.ndarray], count: jnp.ndarray, positions: jnp.ndarray):
        K = count.shape[0]
        rows = jnp.arange(K, dtype=jnp.int32)
        new_acc = {}
        for f in agg.fields:
            ident = jnp.full((K, num_positions), f.identity, dtype=f.dtype)
            new_acc[f.name] = _set_cols(acc[f.name], positions, ident)
        zeros = jnp.zeros((K, num_positions), dtype=count.dtype)
        new_count = _set_cols(count, positions, zeros)
        return new_acc, new_count

    def _set_cols(arr, positions, vals):
        # scatter whole columns; INVALID_INDEX positions dropped
        K = arr.shape[0]
        col_idx = jnp.broadcast_to(positions[None, :], (K, num_positions))
        row_idx = jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32)[:, None], (K, num_positions))
        return arr.at[row_idx, col_idx].set(vals, mode="drop")

    return jax.jit(purge, donate_argnums=(0, 1))


# ---------------------------------------------------------------------------
# bounded segment fold (global-window superscan ingest)
# ---------------------------------------------------------------------------

def bounded_segment_fold(vals, seg, nseg: int, op: str, identity):
    """Fold a value column into `nseg` per-segment partials WITHOUT any
    scatter or one-hot matrix: one masked whole-column reduction per
    segment, unrolled (nseg is tiny and static — the rel-slice count of a
    batch, never the key count). seg < 0 lanes are dropped.

    This is the keyed-partial half of the global-max superscan: each batch
    folds to [nseg] partials, the ring state folds partials across batches,
    and a window fire folds its slice range — a psum-style cross-segment
    fold instead of the dense per-key reduction (the single-chip analogue
    of the mesh's cross-shard pmax). Works under jit and inside pallas
    kernel bodies (pure jnp ops)."""
    import jax.numpy as jnp

    vals = jnp.asarray(vals)
    ident = jnp.asarray(identity, vals.dtype)
    parts = []
    for s in range(nseg):
        lane = jnp.where(seg == s, vals, ident)
        if op == "add":
            parts.append(lane.sum())
        elif op == "min":
            parts.append(lane.min())
        elif op == "max":
            parts.append(lane.max())
        else:
            raise ValueError(op)
    return jnp.stack(parts)


# ---------------------------------------------------------------------------
# top-k over fired results (Nexmark Q5-style hot items)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnums=(2,))
def masked_top_k(values: jnp.ndarray, mask: jnp.ndarray, k: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Top-k values among masked lanes; returns (values[k], indices[k]).
    Unmasked lanes rank below everything (−inf / int-min)."""
    if jnp.issubdtype(values.dtype, jnp.floating):
        neg = jnp.array(-jnp.inf, dtype=values.dtype)
    else:
        neg = jnp.array(jnp.iinfo(values.dtype).min, dtype=values.dtype)
    masked = jnp.where(mask, values, neg)
    return jax.lax.top_k(masked, k)


def init_state_arrays(agg: DeviceAggregator, K: int, S: int) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """Fresh accumulator columns + count, on the default device."""
    acc = {
        f.name: jnp.full((K, S), f.identity, dtype=f.dtype) for f in agg.fields
    }
    count = jnp.zeros((K, S), dtype=jnp.int32)
    return acc, count


def grow_keys(acc: Dict[str, jnp.ndarray], count: jnp.ndarray,
              agg: DeviceAggregator, new_k: int):
    """Double key capacity: pad with identities (host-triggered; subsequent
    steps compile for the new static shape)."""
    K, S = count.shape
    pad = new_k - K
    new_acc = {}
    for f in agg.fields:
        filler = jnp.full((pad, S), f.identity, dtype=f.dtype)
        new_acc[f.name] = jnp.concatenate([acc[f.name], filler], axis=0)
    new_count = jnp.concatenate([count, jnp.zeros((pad, S), dtype=count.dtype)], axis=0)
    return new_acc, new_count
