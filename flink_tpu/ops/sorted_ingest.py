"""Sort-based ingest: an alternative to scatter-combine for high-conflict
batches.

TPU scatters serialize on index conflicts; with few hot keys (skew) a batch
of B records may degrade to O(B) serial updates. The sort-based form runs in
O(B log B) *data-parallel* work regardless of skew:

  1. sort lanes by flat cell index (key*S + slice) — `lax.sort` maps to the
     TPU's fast bitonic sorter,
  2. segment-combine equal-index runs with a log-step prefix scan
     (associative_scan over the combine op, segmented by run boundaries),
  3. scatter only the last lane of each run (≤ one write per *distinct*
     cell, conflict-free).

This mirrors the skew-handling role of the reference's sort-based shuffle
(SortMergeResultPartition.java:66): when hash-style scatter degrades, sort
first. Selection between kernels is a per-operator config (autotuned on
device in bench; both are semantically identical — property-tested).
"""

from __future__ import annotations

import functools
from typing import Dict

import jax
import jax.numpy as jnp

from flink_tpu.ops.aggregators import DeviceAggregator, ONE
from flink_tpu.ops.segment_ops import INVALID_INDEX


def _segment_combine_sorted(values: jnp.ndarray, flat_idx: jnp.ndarray, op: str) -> jnp.ndarray:
    """Inclusive segmented scan over sorted segments: each lane ends up with
    the combine of all lanes of its segment up to and including itself."""

    def combine(a, b):
        ia, va = a
        ib, vb = b
        same = ia == ib
        if op == "add":
            merged = jnp.where(same, va + vb, vb)
        elif op == "min":
            merged = jnp.where(same, jnp.minimum(va, vb), vb)
        else:
            merged = jnp.where(same, jnp.maximum(va, vb), vb)
        return ib, merged

    _, scanned = jax.lax.associative_scan(combine, (flat_idx, values))
    return scanned


@functools.lru_cache(maxsize=None)
def make_sorted_ingest_fn(agg: DeviceAggregator, *, track_touch: bool):
    """Same contract as segment_ops.make_ingest_fn, sort-based internals."""

    def ingest(acc: Dict[str, jnp.ndarray], count: jnp.ndarray,
               kid: jnp.ndarray, spos: jnp.ndarray, vals: jnp.ndarray):
        K, S = count.shape
        B = kid.shape[0]
        # int32 flat index: K*S must stay < 2^31 - 1 (checked at state init;
        # 2^20 keys x 64 slices is well inside)
        valid = kid != INVALID_INDEX
        sentinel = jnp.int32(K * S)
        flat = jnp.where(valid, kid * jnp.int32(S) + spos, sentinel)
        order = jnp.argsort(flat)
        flat_s = flat[order]
        vals_s = vals[order]
        is_last = jnp.concatenate(
            [flat_s[1:] != flat_s[:-1], jnp.ones((1,), dtype=jnp.bool_)]
        )
        # per-segment combined value at segment-last lanes
        row = jnp.where(is_last & (flat_s < K * S), (flat_s // S).astype(jnp.int32),
                        jnp.int32(INVALID_INDEX))
        col = jnp.where(is_last & (flat_s < K * S), (flat_s % S).astype(jnp.int32),
                        jnp.int32(INVALID_INDEX))

        new_acc = {}
        for f in agg.fields:
            src = (
                jnp.ones(B, dtype=f.dtype) if f.source == ONE else vals_s.astype(f.dtype)
            )
            seg = _segment_combine_sorted(src, flat_s, f.scatter)
            ref = acc[f.name].at[row, col]
            op = {"add": ref.add, "min": ref.min, "max": ref.max}[f.scatter]
            new_acc[f.name] = op(seg, mode="drop")
        seg_cnt = _segment_combine_sorted(jnp.ones(B, dtype=count.dtype), flat_s, "add")
        new_count = count.at[row, col].add(seg_cnt, mode="drop")
        if track_touch:
            touch = jnp.zeros(count.shape, dtype=jnp.bool_).at[row, col].set(
                True, mode="drop"
            )
            return new_acc, new_count, touch
        return new_acc, new_count

    return jax.jit(ingest, donate_argnums=(0, 1))
