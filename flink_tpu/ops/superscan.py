"""The per-step superscan body: ingest/fire/purge over the [K, S] slice ring.

Shared by the single-chip fused superscan (runtime/fused_window_pipeline),
the chained whole-graph-fusion program, and the shard_map sharded superscan
(parallel/sharded_superscan — each shard runs this on its local key range).
It lives in `ops` because it is a pure device-kernel builder over a
DeviceAggregator: no runtime state, no host planning — exactly the layer
matmul_hist and pallas_superscan occupy, and the reason `parallel/` can
compose with it without importing the runtime (ARCH001).
"""

from __future__ import annotations

import functools as _functools


def default_ingest() -> str:
    """THE backend-dependent ingest choice, single-sourced: programs built
    fresh per job (the chained single-chip superscan and both sharded
    builds) use direct scatter-adds off-TPU — the [K, S] ring is
    cache-resident on a scalar core and the dense one-hot MXU contraction
    does K*NSB work per record there. On TPU the matmul-histogram form
    wins. (The classic single-chip `_build_superscan` keeps its historical
    explicit 'matmul' on every backend for executable-cache and bench
    continuity.) Identical math either way — both are pure adds into the
    same cells."""
    import jax

    return "matmul" if jax.default_backend() == "tpu" else "scatter"


def make_superscan_step(agg, K, S, NSB, F, R, SPW, chunk, exact,
                        ingest: str = "matmul", phase_counters: bool = False,
                        fire_spws=None):
    """The per-step ingest/fire/purge body, shared by the single-chip
    superscan and the shard_map sharded superscan (each shard runs this on
    its local key range).

    `ingest` selects how add-combining fields land in the [K, S] ring:
    'matmul' (default, unchanged) re-expresses the scatter as MXU one-hot
    histograms — the TPU form; 'scatter' uses direct scatter-adds, which is
    what wins on CPU backends (the [K, S] ring is cache-resident and the
    dense one-hot contraction does K*NSB work per record on a scalar
    core). Identical math either way: both are pure adds into the same
    cells, counts exact in int32.

    'partials' consumes PRE-REDUCED per-step partials instead of record
    lanes — the receive side of the mesh map-side combiner
    (parallel.mesh.local-combine): the idx slot of `args` carries the
    step's [K, NSB] count partial and the vals slot a tuple of [K, NSB]
    per-VALUE-field partials (aligned with the aggregator's VALUE fields,
    min/max cells holding their scan identity where untouched). Ingest
    becomes one dense column combine per field — the same
    add/min/max ops the lane scatter applies, so the ring state is exact;
    fire and purge are the identical shared body.

    `phase_counters` (device-plane observability) threads an int32[3]
    counter through the carry — [records ingested, fire slots executed,
    steps that purged] — so a dispatch's device time can be attributed to
    the ingest/fire/purge phases without any extra host sync (the counts
    ride the same async readback as the fire rows). The carry becomes a
    5-tuple; callers opt in, so the default executable shape is unchanged.

    `fire_spws` (shared-partials, graph/window_sharing.py): per-fire-slot
    window lengths in slices, length F, replacing the uniform SPW — one
    ring of gcd-granule partials serves several correlated window shapes
    (Factor Windows), each firing its own slice-run length from the shared
    state. None keeps the classic single-shape program byte-identical."""
    import jax
    import jax.numpy as jnp

    from flink_tpu.ops import matmul_hist
    from flink_tpu.ops.aggregators import VALUE, combine_reduce

    spws = tuple(fire_spws) if fire_spws is not None else (SPW,) * F
    if len(spws) != F:
        raise ValueError(f"fire_spws has {len(spws)} slots, expected F={F}")
    vfields = [
        (f.name, jnp.dtype(f.dtype), f.scatter, f.identity)
        for f in agg.fields
        if f.source == VALUE
    ]
    nseg = K * NSB

    def step(carry, args):
        if phase_counters:
            # `phase_c`, not `pc`: the ingest paths below use `pc` for
            # their partial-count histograms
            state, count, outs, count_out, phase_c = carry
        else:
            state, count, outs, count_out = carry
        idx, vals, smin_pos, fire_pos, fire_valid, fire_row, purge_mask = args
        cols = (smin_pos + jnp.arange(NSB, dtype=jnp.int32)) % S

        if ingest == "partials":
            # pre-reduced ingest (the map-side combiner's receive side):
            # idx is the step's [K, NSB] count partial, vals the tuple of
            # per-VALUE-field [K, NSB] partials — one dense column combine
            # per field, same add/min/max semantics as the lane scatter
            cpart = idx
            count = count.at[:, cols].add(cpart)
            new_state = {}
            for (name, dt, scatter, _ident), part in zip(vfields, vals):
                upd = getattr(state[name].at[:, cols], scatter)
                new_state[name] = upd(part.astype(dt))
            state = new_state if vfields else state
            return _fire_purge(
                state, count, outs, count_out, phase_c if phase_counters
                else None, cpart.sum(),
                (fire_pos, fire_valid, fire_row, purge_mask))

        # ingest: MXU histograms over (key, rel-slice) segments for
        # add-combining fields (or direct scatter-adds on CPU backends);
        # min/max fields always scatter-combine (no matmul form exists for
        # order statistics — the scatter unit is the cost of supporting
        # them on the fused path at all)
        kid = idx // NSB
        srel = idx % NSB
        col = (smin_pos + srel) % S
        safe_kid = jnp.where(idx >= 0, kid, K)  # OOB rows drop
        # CPU add-ingest form: XLA lowers a FLAT 1-D index scatter ~2x
        # faster than the 2-D (kid, col) scatter, so adds go through a
        # [K*NSB] staging histogram folded densely into the ring columns —
        # gated on the dense fold (nseg per step) staying small next to
        # the batch, so huge-K geometries keep the direct scatter
        flat_adds = ingest != "matmul" and nseg <= 16 * idx.shape[0]
        if ingest == "matmul":
            pc = matmul_hist.count_hist(idx, nseg, chunk=chunk).reshape(K, NSB)
            count = count.at[:, cols].add(pc)
        elif flat_adds:
            # dead rows carry idx -1, which jax would WRAP to the last
            # segment (numpy negative indexing; mode="drop" only drops
            # past-the-end) — remap them to nseg so the drop is real
            safe_idx = jnp.where(idx >= 0, idx, nseg)
            pc = jnp.zeros((nseg,), jnp.int32).at[safe_idx].add(
                jnp.int32(1), mode="drop").reshape(K, NSB)
            count = count.at[:, cols].add(pc)
        else:
            count = count.at[safe_kid, col].add(jnp.int32(1), mode="drop")
        new_state = {}
        for name, dt, scatter, ident in vfields:
            if scatter == "add":
                if ingest == "matmul":
                    ph = matmul_hist.weighted_hist(
                        idx, vals, nseg, chunk=chunk, exact=exact
                    ).reshape(K, NSB)
                    new_state[name] = state[name].at[:, cols].add(ph.astype(dt))
                elif flat_adds:
                    ph = jnp.zeros((nseg,), dt).at[
                        jnp.where(idx >= 0, idx, nseg)].add(
                        vals.astype(dt), mode="drop").reshape(K, NSB)
                    new_state[name] = state[name].at[:, cols].add(ph)
                else:
                    new_state[name] = state[name].at[safe_kid, col].add(
                        vals.astype(dt), mode="drop")
            else:
                upd = getattr(state[name].at[safe_kid, col], scatter)
                new_state[name] = upd(vals.astype(dt), mode="drop")
        state = new_state if vfields else state
        return _fire_purge(
            state, count, outs, count_out,
            phase_c if phase_counters else None,
            jnp.sum((idx >= 0).astype(jnp.int32)),
            (fire_pos, fire_valid, fire_row, purge_mask))

    def _fire_purge(state, count, outs, count_out, phase_c, ingested, plan):
        """Fire + purge, shared verbatim by the lane-scatter and
        pre-reduced ('partials') ingest forms — the combine path must be a
        different INGEST, never a different fire/purge."""
        fire_pos, fire_valid, fire_row, purge_mask = plan

        # fire: combine the window's slice columns, write compact rows.
        # The WHOLE fire body sits under the cond, gathers included: most
        # steps fire nothing, and the K*SPW column gather+combine per fire
        # slot is the dominant per-step fixed cost when computed eagerly
        # (at K=8192, SPW=10, F=2 that is 20x the ingest work of an 8k
        # batch) — identical results, the eager crow was discarded unless
        # fire_valid was set anyway
        def write_fire(f, bufs):
            pos = (fire_pos[f] + jnp.arange(spws[f], dtype=jnp.int32)) % S
            row = jnp.clip(fire_row[f], 0, R - 1)

            def do_fire(b):
                outs, count_out = b
                crow = count[:, pos].sum(axis=1)
                count_out = jax.lax.dynamic_update_index_in_dim(
                    count_out, crow, row, 0)
                new_outs = {}
                for name, _dt, scatter, _ident in vfields:
                    vrow = combine_reduce(scatter)(state[name][:, pos], 1)
                    new_outs[name] = jax.lax.dynamic_update_index_in_dim(
                        outs[name], vrow, row, 0)
                return (new_outs if vfields else outs), count_out

            return jax.lax.cond(fire_valid[f] > 0, do_fire, lambda b: b, bufs)

        bufs = (outs, count_out)
        for f in range(F):
            bufs = write_fire(f, bufs)
        outs, count_out = bufs

        # purge expired ring columns (reset to the field's identity); under
        # a cond for the same reason — the S*K multiply/where is pure
        # identity on the all-ones masks most steps carry
        def do_purge(sc):
            state, count = sc
            count = count * purge_mask[None, :]
            if vfields:
                state = {
                    name: jnp.where(
                        purge_mask[None, :] > 0,
                        state[name],
                        jnp.asarray(ident, dt),
                    )
                    for name, dt, _scatter, ident in vfields
                }
            return state, count

        purged = jnp.any(purge_mask == 0)
        state, count = jax.lax.cond(
            purged, do_purge, lambda sc: sc, (state, count))
        if phase_counters:
            phase_c = phase_c + jnp.stack([
                ingested.astype(jnp.int32),
                jnp.sum(fire_valid).astype(jnp.int32),
                purged.astype(jnp.int32),
            ])
            return (state, count, outs, count_out, phase_c), None
        return (state, count, outs, count_out), None

    return step


def make_segment_partials(agg, nseg, chunk, exact, ingest: str = "matmul"):
    """The map-side combiner's send side (parallel.mesh.local-combine):
    build fn(idx, vals) segment-reducing ONE step's record lanes into
    dense flat partials over `nseg` destination segments — count plus one
    partial per VALUE field, each pre-reduced by the field's own scatter
    combiner (add/min/max), untouched cells holding the scan identity so
    merging them downstream is a no-op. Lanes with idx < 0 drop.

    `ingest` mirrors the ring-ingest choice: 'matmul' builds add partials
    as MXU one-hot histograms (the TPU form; matmul_hist's exact bf16
    3-term split for float adds when `exact`), anything else uses direct
    flat scatters. Min/max partials always scatter — no matmul form
    exists for order statistics, exactly like the ring ingest.

    Returns (fn, vfields) where vfields is the (name, dtype, scatter,
    identity) tuple list the partials align with."""
    import jax.numpy as jnp

    from flink_tpu.ops import matmul_hist
    from flink_tpu.ops.aggregators import VALUE, scan_identity

    vfields = [
        (f.name, jnp.dtype(f.dtype), f.scatter, f.identity)
        for f in agg.fields
        if f.source == VALUE
    ]

    def partials(idx, vals):
        safe = jnp.where(idx >= 0, idx, nseg)   # OOB segment drops
        if ingest == "matmul":
            cpart = matmul_hist.count_hist(idx, nseg, chunk=chunk)
        else:
            cpart = jnp.zeros((nseg,), jnp.int32).at[safe].add(
                jnp.int32(1), mode="drop")
        parts = []
        for name, dt, scatter, _ident in vfields:
            if scatter == "add" and ingest == "matmul":
                p = matmul_hist.weighted_hist(
                    idx, vals, nseg, chunk=chunk, exact=exact).astype(dt)
            elif scatter == "add":
                p = jnp.zeros((nseg,), dt).at[safe].add(
                    vals.astype(dt), mode="drop")
            else:
                init = jnp.full((nseg,), scan_identity(dt, scatter), dt)
                p = getattr(init.at[safe], scatter)(
                    vals.astype(dt), mode="drop")
            parts.append(p)
        return cpart, tuple(parts)

    return partials, vfields


def make_global_scan_step(agg, S, NSB, F, R, SPW, fire_spws=None,
                          phase_counters: bool = False):
    """The per-step body of the GLOBAL-window superscan: keyed-partial →
    cross-segment fold, no [K, S] ring at all.

    Nexmark-Q7-shaped aggregates (a per-window GLOBAL max/min/sum with
    keyed pre-aggregation only as an implementation detail) do not need
    per-key state: each batch folds to [NSB] per-rel-slice partials with
    one masked whole-column reduction per slice (ops/segment_ops.
    bounded_segment_fold — no scatter unit, no one-hot matrices), the
    partials fold into a tiny [S] slice ring, and a window fire folds its
    SPW slice cells into ONE scalar. This replaces the dense per-batch
    keyed reduction (the [K, S] nibble-histogram path plus a [R, K]
    readback and a host-side max over keys) with the single-chip analogue
    of the mesh's psum/pmax cross-shard merge — and the readback shrinks
    from R*K rows to R scalars.

    Unbounded min/max get a device form here for free: the fold is
    elementwise, so no bounded-domain (max8) declaration is needed.

    idx lanes may carry either bare rel-slices or the keyed encoding
    `kid * NSB + srel` (the staged streams the keyed superscan consumes);
    both reduce to the same rel-slice via `idx % NSB`, negatives drop.
    """
    import jax
    import jax.numpy as jnp

    from flink_tpu.ops.aggregators import VALUE, combine_reduce, scan_identity
    from flink_tpu.ops.segment_ops import bounded_segment_fold

    spws = tuple(fire_spws) if fire_spws is not None else (SPW,) * F
    if len(spws) != F:
        raise ValueError(f"fire_spws has {len(spws)} slots, expected F={F}")
    vfields = [
        (f.name, jnp.dtype(f.dtype), f.scatter, f.identity)
        for f in agg.fields
        if f.source == VALUE
    ]

    def step(carry, args):
        if phase_counters:
            state, count, outs, count_out, phase_c = carry
        else:
            state, count, outs, count_out = carry
        idx, vals, smin_pos, fire_pos, fire_valid, fire_row, purge_mask = args

        # ingest: [NSB] partials per batch, folded into the [S] ring
        srel = jnp.where(idx >= 0, idx % NSB, -1)
        cols = (smin_pos + jnp.arange(NSB, dtype=jnp.int32)) % S
        cpart = bounded_segment_fold(
            jnp.ones(idx.shape, jnp.int32), srel, NSB, "add", 0)
        count = count.at[cols].add(cpart)
        new_state = {}
        for name, dt, scatter, _ident in vfields:
            part = bounded_segment_fold(
                vals.astype(dt), srel, NSB, scatter,
                scan_identity(dt, scatter))
            upd = getattr(state[name].at[cols], scatter)
            new_state[name] = upd(part)
        state = new_state if vfields else state

        # fire: fold the window's slice cells into one scalar per slot
        def write_fire(f, bufs):
            pos = (fire_pos[f] + jnp.arange(spws[f], dtype=jnp.int32)) % S
            row = jnp.clip(fire_row[f], 0, R - 1)

            def do_fire(b):
                outs, count_out = b
                count_out = count_out.at[row].set(count[pos].sum())
                new_outs = {}
                for name, _dt, scatter, _ident in vfields:
                    folded = combine_reduce(scatter)(state[name][pos], 0)
                    new_outs[name] = outs[name].at[row].set(folded)
                return (new_outs if vfields else outs), count_out

            return jax.lax.cond(fire_valid[f] > 0, do_fire, lambda b: b, bufs)

        bufs = (outs, count_out)
        for f in range(F):
            bufs = write_fire(f, bufs)
        outs, count_out = bufs

        # purge expired cells back to identity
        def do_purge(sc):
            state, count = sc
            count = count * purge_mask
            if vfields:
                state = {
                    name: jnp.where(
                        purge_mask > 0, state[name],
                        jnp.asarray(scan_identity(dt, scatter), dt))
                    for name, dt, scatter, _ident in vfields
                }
            return state, count

        purged = jnp.any(purge_mask == 0)
        state, count = jax.lax.cond(
            purged, do_purge, lambda sc: sc, (state, count))
        if phase_counters:
            phase_c = phase_c + jnp.stack([
                jnp.sum((idx >= 0).astype(jnp.int32)),
                jnp.sum(fire_valid).astype(jnp.int32),
                purged.astype(jnp.int32),
            ])
            return (state, count, outs, count_out, phase_c), None
        return (state, count, outs, count_out), None

    return step


@_functools.lru_cache(maxsize=None)
def build_global_superscan(agg, S, NSB, F, R, SPW, T, B,
                           fire_spws=None, phases: bool = False):
    """Compiled T-step global-window superscan (lax.scan over
    make_global_scan_step; module-level cache like _build_superscan).

    run(state {field: [S]}, count [S] i32, outs {field: [R]},
        count_out [R] i32, idx [T, B] i32, vals [T, B] f32,
        smin_pos, fire_pos, fire_valid, fire_row, purge_mask)
      -> (state, count, outs, count_out[, phase_counters])"""
    import jax
    import jax.numpy as jnp

    step = make_global_scan_step(agg, S, NSB, F, R, SPW,
                                 fire_spws=fire_spws, phase_counters=phases)

    @jax.jit
    def run(state, count, outs, count_out, idx, vals, smin_pos, fire_pos,
            fire_valid, fire_row, purge_mask):
        carry0 = (state, count, outs, count_out)
        if phases:
            carry0 = carry0 + (jnp.zeros((3,), jnp.int32),)
        carry, _ = jax.lax.scan(
            step, carry0,
            (idx, vals, smin_pos, fire_pos, fire_valid, fire_row,
             purge_mask),
        )
        return carry

    return run


# ---------------------------------------------------------------------------
# fused session superscan: T ingest steps + in-scan segmented gap-merges
# in ONE device program (runtime/tpu_session_operator.py drives this)
# ---------------------------------------------------------------------------

def session_gap_merge_scan(c, fmn, fmx, fl, vfields, idents, g, wm_rel, est):
    """The [K, n]-wide touching-fragment gap-merge scan — ONE copy of the
    join/break/close semantics shared by the per-watermark merge program
    (runtime/tpu_session_operator._build_merge_scan) and the fused
    superspan's in-carry merges (make_session_superscan below). The
    overflow-recovery contract ("placement never changes a result")
    requires the two paths to be bit-identical; single-sourcing the scan
    body makes a one-sided edit to the join condition (min - cmax <= g)
    or the close condition (cmax + g - 1 <= wm_rel) impossible.

    c/fmn/fmx [K, n] and fl ([K, n] per value field) are the gathered
    span: per-cell fragment counts and min/max rel-ms (columns with c == 0
    are gaps — callers zero invalid columns); `est` is the emission-slot
    carry (slots, e_start, e_end, e_cnt, e_s0, e_s1, e_flds, overflow)
    with [K, M] slot arrays — fresh for a standalone merge, carried across
    merges for a superspan. Returns the updated est: sessions closed by
    this scan (a following fragment breaks the gap, or end <= wm_rel)
    appended at each key's next slot, e_s0/e_s1 holding the session's
    column range in THIS scan's coordinates for the caller's purge."""
    import jax.numpy as jnp

    from flink_tpu.ops.aggregators import combine_binary

    combine = {sc: combine_binary(sc) for _n, _dt, sc in vfields}
    i32 = jnp.int32
    K, n = c.shape
    M = est[1].shape[1]
    mslots = jnp.arange(M, dtype=i32)[None, :]

    open_ = jnp.zeros((K,), bool)
    cmin = jnp.zeros((K,), i32)
    cmax = jnp.full((K,), -(1 << 30), i32)
    ccnt = jnp.zeros((K,), i32)
    cstart = jnp.zeros((K,), i32)
    clast = jnp.zeros((K,), i32)
    cflds = [jnp.full((K,), ident, f.dtype) for f, ident in zip(fl, idents)]

    def do_emit(mask, est):
        (slots, e_start, e_end, e_cnt, e_s0, e_s1, e_flds, overflow) = est
        can = mask & (slots < M)
        oh = (mslots == slots[:, None]) & can[:, None]        # [K, M]
        e_start = jnp.where(oh, cmin[:, None], e_start)
        e_end = jnp.where(oh, cmax[:, None], e_end)
        e_cnt = jnp.where(oh, ccnt[:, None], e_cnt)
        e_s0 = jnp.where(oh, cstart[:, None], e_s0)
        e_s1 = jnp.where(oh, clast[:, None], e_s1)
        e_flds = [jnp.where(oh, cf[:, None], ef)
                  for cf, ef in zip(cflds, e_flds)]
        overflow = overflow | jnp.any(mask & (slots >= M))
        slots = slots + can.astype(i32)
        return (slots, e_start, e_end, e_cnt, e_s0, e_s1, e_flds, overflow)

    for i in range(n):
        ci = c[:, i]
        frag = ci > 0
        mni = fmn[:, i]
        mxi = fmx[:, i]
        joins = open_ & frag & (mni - cmax <= g)
        breaks = open_ & frag & ~joins
        est = do_emit(breaks, est)
        starts = frag & ~joins
        cmin = jnp.where(starts, mni, cmin)
        ccnt = jnp.where(starts, 0, ccnt)
        cstart = jnp.where(starts, i, cstart)
        cflds = [jnp.where(starts, jnp.asarray(ident, cf.dtype), cf)
                 for cf, ident in zip(cflds, idents)]
        open_ = open_ | frag
        cmax = jnp.where(frag, mxi, cmax)
        ccnt = jnp.where(frag, ccnt + ci, ccnt)
        clast = jnp.where(frag, i, clast)
        cflds = [
            jnp.where(frag, combine[sc](cf, fi[:, i]), cf)
            for cf, fi, (_n, _dt, sc) in zip(cflds, fl, vfields)
        ]
    return do_emit(open_ & (cmax + g - 1 <= wm_rel), est)


def session_ingest_scatter(K, S, vfields):
    """The per-batch session ingest scatter — ONE copy of the [K, S] ring
    update (count/min-ts/max-ts/value fields, kid < 0 dropped via the
    sentinel row) shared by the per-step program
    (runtime/tpu_session_operator._build_ingest) and the fused superspan's
    in-scan ingest (make_session_superscan below). The overflow-recovery
    contract ("placement never changes a result") requires the two paths
    to be bit-identical; single-sourcing the body makes a one-sided edit
    to the scatter semantics impossible, like session_gap_merge_scan for
    the merge side."""
    import jax.numpy as jnp

    def ingest(cnt, mn, mx, fields, kid, spos, rel, vals):
        flat = jnp.where(kid >= 0, kid * S + spos, K * S)
        cnt = cnt.reshape(-1).at[flat].add(1, mode="drop").reshape(K, S)
        mn = mn.reshape(-1).at[flat].min(rel, mode="drop").reshape(K, S)
        mx = mx.reshape(-1).at[flat].max(rel, mode="drop").reshape(K, S)
        new_fields = []
        for (name, dt, scatter), f in zip(vfields, fields):
            upd = getattr(f.reshape(-1).at[flat], scatter)
            new_fields.append(
                upd(vals.astype(jnp.dtype(dt)), mode="drop").reshape(K, S))
        return cnt, mn, mx, tuple(new_fields)

    return ingest


@_functools.lru_cache(maxsize=None)
def make_session_superscan(K, S, M, g, vfields, idents, T, B):
    """Compile the fused session dispatch: T staged ingest steps with the
    gap-merge scan RUNNING INSIDE THE PROGRAM at watermark steps — sessions
    coalesce in the scan carry (the touching-session merge semantics of
    api/windowing/assigners.py EventTimeSessionWindows.merge_windows:
    fragments at consecutive slices join iff min_ts(frag) - max_ts(cur)
    <= gap) and never round-trip to host per merge. Closed sessions
    accumulate into M fixed emission slots per key across the whole
    dispatch; ONE packed int32 array comes back per dispatch, in the exact
    layout of the per-watermark `_build_merge_scan` (so the operator's
    `_resolve_entry` parses both).

    vfields: ((name, dtype_str, scatter), ...); idents aligned identities.

    run(cnt [K,S] i32, mn [K,S] i32, mx [K,S] i32, fields ([K,S] dt, ...),
        kid [T,B] i32, spos [T,B] i32, rel [T,B] i32, vals [T,B] f32,
        merge_flag [T] i32, lo_pos [T] i32, lo_rel [T] i32, wm_rel [T] i32)
      -> (cnt, mn, mx, fields, packed [K+1, (3+nf)*M + 1] i32)

    Coordinates: everything slice-relative to ONE dispatch base `lo0`
    (lo_rel[t] = merge-span base slice − lo0; rel-ms fit int32 — the
    caller guards (span + 2) * g < 2^31). The caller guarantees the whole
    dispatch's resident span stays inside the ring (< S slices), so every
    merge scans the full ring from lo_pos — empty columns are no-ops.
    Emission overflow (a key closing more than M sessions in one
    dispatch) sets the packed overflow flag; the caller discards the
    fused result and replays the dispatch on the exact per-watermark
    path from its retained pre-dispatch state."""
    import jax
    import jax.numpy as jnp

    nf = len(vfields)
    i32 = jnp.int32

    ingest = session_ingest_scatter(K, S, vfields)

    def merge(state, lo_pos, lo_rel, wm_rel):
        (cnt, mn, mx, fields, est) = state
        idx_p = jnp.arange(S, dtype=i32)
        pos = (lo_pos + idx_p) % S              # full-ring span, bijective
        abs_rel = lo_rel + idx_p                # absolute slice − lo0
        c = cnt[:, pos]                                        # [K, S]
        fmn = mn[:, pos] + abs_rel[None, :] * g
        fmx = mx[:, pos] + abs_rel[None, :] * g
        fl = [f[:, pos] for f in fields]
        mslots = jnp.arange(M, dtype=i32)[None, :]
        slots_in = est[0]

        est = session_gap_merge_scan(c, fmn, fmx, fl, vfields, idents, g,
                                     wm_rel, est)
        (slots, e_start, e_end, e_cnt, e_s0, e_s1, e_flds, overflow) = est

        # purge exactly the cells of sessions emitted by THIS merge (the
        # slot-range mask excludes entries from earlier merge steps of the
        # same dispatch, whose span coordinates were a different base)
        this = (mslots >= slots_in[:, None]) & (mslots < slots[:, None])
        cover = (idx_p[None, None, :] >= e_s0[:, :, None]) & \
                (idx_p[None, None, :] <= e_s1[:, :, None]) & \
                this[:, :, None]
        purge = jnp.any(cover, axis=1)                         # [K, S]
        c_new = jnp.where(purge, 0, c)
        # full-ring span: pos is a permutation, so column set-back is exact
        cnt = cnt.at[:, pos].set(c_new)
        mn = mn.at[:, pos].set(jnp.where(purge, g, mn[:, pos]))
        mx = mx.at[:, pos].set(jnp.where(purge, -1, mx[:, pos]))
        fields = tuple(
            f.at[:, pos].set(
                jnp.where(purge, jnp.asarray(ident, f.dtype), f[:, pos]))
            for f, ident in zip(fields, idents)
        )
        return (cnt, mn, mx, fields,
                (slots, e_start, e_end, e_cnt, e_s0, e_s1, e_flds, overflow))

    def step(carry, args):
        kid, spos, rel, vals, merge_flag, lo_pos, lo_rel, wm_rel = args
        (cnt, mn, mx, fields, est) = carry
        cnt, mn, mx, fields = ingest(cnt, mn, mx, fields, kid, spos, rel,
                                     vals)
        cnt, mn, mx, fields, est = jax.lax.cond(
            merge_flag > 0,
            lambda s: merge(s, lo_pos, lo_rel, wm_rel),
            lambda s: s,
            (cnt, mn, mx, fields, est))
        return (cnt, mn, mx, fields, est), None

    def run(cnt, mn, mx, fields, kid, spos, rel, vals,
            merge_flag, lo_pos, lo_rel, wm_rel):
        slots = jnp.zeros((K,), i32)
        e_start = jnp.zeros((K, M), i32)
        e_end = jnp.zeros((K, M), i32)
        e_cnt = jnp.zeros((K, M), i32)
        e_s0 = jnp.zeros((K, M), i32)
        e_s1 = jnp.full((K, M), -1, i32)
        e_flds = [jnp.full((K, M), ident, jnp.dtype(dt))
                  for (_n, dt, _s), ident in zip(vfields, idents)]
        overflow = jnp.zeros((), bool)
        est0 = (slots, e_start, e_end, e_cnt, e_s0, e_s1, e_flds, overflow)
        carry0 = (cnt, mn, mx, tuple(fields), est0)
        (cnt, mn, mx, fields, est), _ = jax.lax.scan(
            step, carry0,
            (kid, spos, rel, vals, merge_flag, lo_pos, lo_rel, wm_rel))
        (slots, e_start, e_end, e_cnt, _s0, _s1, e_flds, overflow) = est

        # live span of the surviving fragments, in dispatch-base slice
        # coordinates: the ring is bijective from position p -> slice
        # base_lo_rel + ((p - base_lo_pos) % S); the host passes the
        # dispatch-final base via the LAST step's lo_pos/lo_rel
        idx_p = jnp.arange(S, dtype=i32)
        pos = (lo_pos[-1] + idx_p) % S
        abs_rel = lo_rel[-1] + idx_p
        live = jnp.any(cnt[:, pos] > 0, axis=0)
        lo_live = jnp.min(jnp.where(live, abs_rel, 1 << 30))
        hi_live = jnp.max(jnp.where(live, abs_rel, -1))

        blocks = [e_start, e_end, e_cnt]
        for ef in e_flds:
            blocks.append(jax.lax.bitcast_convert_type(
                ef, i32) if ef.dtype != i32 else ef)
        packed = jnp.concatenate(blocks + [slots[:, None]], axis=1)
        scal = jnp.zeros((1, packed.shape[1]), i32)
        scal = scal.at[0, 0].set(
            jnp.where(hi_live >= 0, lo_live, 0).astype(i32))
        scal = scal.at[0, 1].set(hi_live.astype(i32))
        scal = scal.at[0, 2].set(overflow.astype(i32))
        packed = jnp.concatenate([packed, scal], axis=0)
        return cnt, mn, mx, fields, packed

    return jax.jit(run)
