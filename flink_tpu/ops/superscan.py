"""The per-step superscan body: ingest/fire/purge over the [K, S] slice ring.

Shared by the single-chip fused superscan (runtime/fused_window_pipeline),
the chained whole-graph-fusion program, and the shard_map sharded superscan
(parallel/sharded_superscan — each shard runs this on its local key range).
It lives in `ops` because it is a pure device-kernel builder over a
DeviceAggregator: no runtime state, no host planning — exactly the layer
matmul_hist and pallas_superscan occupy, and the reason `parallel/` can
compose with it without importing the runtime (ARCH001).
"""

from __future__ import annotations


def default_ingest() -> str:
    """THE backend-dependent ingest choice, single-sourced: programs built
    fresh per job (the chained single-chip superscan and both sharded
    builds) use direct scatter-adds off-TPU — the [K, S] ring is
    cache-resident on a scalar core and the dense one-hot MXU contraction
    does K*NSB work per record there. On TPU the matmul-histogram form
    wins. (The classic single-chip `_build_superscan` keeps its historical
    explicit 'matmul' on every backend for executable-cache and bench
    continuity.) Identical math either way — both are pure adds into the
    same cells."""
    import jax

    return "matmul" if jax.default_backend() == "tpu" else "scatter"


def make_superscan_step(agg, K, S, NSB, F, R, SPW, chunk, exact,
                        ingest: str = "matmul", phase_counters: bool = False):
    """The per-step ingest/fire/purge body, shared by the single-chip
    superscan and the shard_map sharded superscan (each shard runs this on
    its local key range).

    `ingest` selects how add-combining fields land in the [K, S] ring:
    'matmul' (default, unchanged) re-expresses the scatter as MXU one-hot
    histograms — the TPU form; 'scatter' uses direct scatter-adds, which is
    what wins on CPU backends (the [K, S] ring is cache-resident and the
    dense one-hot contraction does K*NSB work per record on a scalar
    core). Identical math either way: both are pure adds into the same
    cells, counts exact in int32.

    `phase_counters` (device-plane observability) threads an int32[3]
    counter through the carry — [records ingested, fire slots executed,
    steps that purged] — so a dispatch's device time can be attributed to
    the ingest/fire/purge phases without any extra host sync (the counts
    ride the same async readback as the fire rows). The carry becomes a
    5-tuple; callers opt in, so the default executable shape is unchanged."""
    import jax
    import jax.numpy as jnp

    from flink_tpu.ops import matmul_hist
    from flink_tpu.ops.aggregators import VALUE

    vfields = [
        (f.name, jnp.dtype(f.dtype), f.scatter, f.identity)
        for f in agg.fields
        if f.source == VALUE
    ]
    nseg = K * NSB

    def step(carry, args):
        if phase_counters:
            # `phase_c`, not `pc`: the ingest paths below use `pc` for
            # their partial-count histograms
            state, count, outs, count_out, phase_c = carry
        else:
            state, count, outs, count_out = carry
        idx, vals, smin_pos, fire_pos, fire_valid, fire_row, purge_mask = args

        # ingest: MXU histograms over (key, rel-slice) segments for
        # add-combining fields (or direct scatter-adds on CPU backends);
        # min/max fields always scatter-combine (no matmul form exists for
        # order statistics — the scatter unit is the cost of supporting
        # them on the fused path at all)
        kid = idx // NSB
        srel = idx % NSB
        col = (smin_pos + srel) % S
        safe_kid = jnp.where(idx >= 0, kid, K)  # OOB rows drop
        cols = (smin_pos + jnp.arange(NSB, dtype=jnp.int32)) % S
        # CPU add-ingest form: XLA lowers a FLAT 1-D index scatter ~2x
        # faster than the 2-D (kid, col) scatter, so adds go through a
        # [K*NSB] staging histogram folded densely into the ring columns —
        # gated on the dense fold (nseg per step) staying small next to
        # the batch, so huge-K geometries keep the direct scatter
        flat_adds = ingest != "matmul" and nseg <= 16 * idx.shape[0]
        if ingest == "matmul":
            pc = matmul_hist.count_hist(idx, nseg, chunk=chunk).reshape(K, NSB)
            count = count.at[:, cols].add(pc)
        elif flat_adds:
            # dead rows carry idx -1, which jax would WRAP to the last
            # segment (numpy negative indexing; mode="drop" only drops
            # past-the-end) — remap them to nseg so the drop is real
            safe_idx = jnp.where(idx >= 0, idx, nseg)
            pc = jnp.zeros((nseg,), jnp.int32).at[safe_idx].add(
                jnp.int32(1), mode="drop").reshape(K, NSB)
            count = count.at[:, cols].add(pc)
        else:
            count = count.at[safe_kid, col].add(jnp.int32(1), mode="drop")
        new_state = {}
        for name, dt, scatter, ident in vfields:
            if scatter == "add":
                if ingest == "matmul":
                    ph = matmul_hist.weighted_hist(
                        idx, vals, nseg, chunk=chunk, exact=exact
                    ).reshape(K, NSB)
                    new_state[name] = state[name].at[:, cols].add(ph.astype(dt))
                elif flat_adds:
                    ph = jnp.zeros((nseg,), dt).at[
                        jnp.where(idx >= 0, idx, nseg)].add(
                        vals.astype(dt), mode="drop").reshape(K, NSB)
                    new_state[name] = state[name].at[:, cols].add(ph)
                else:
                    new_state[name] = state[name].at[safe_kid, col].add(
                        vals.astype(dt), mode="drop")
            else:
                upd = getattr(state[name].at[safe_kid, col], scatter)
                new_state[name] = upd(vals.astype(dt), mode="drop")
        state = new_state if vfields else state

        # fire: combine the window's slice columns, write compact rows.
        # The WHOLE fire body sits under the cond, gathers included: most
        # steps fire nothing, and the K*SPW column gather+combine per fire
        # slot is the dominant per-step fixed cost when computed eagerly
        # (at K=8192, SPW=10, F=2 that is 20x the ingest work of an 8k
        # batch) — identical results, the eager crow was discarded unless
        # fire_valid was set anyway
        _COMBINE = {"add": lambda a: a.sum(axis=1),
                    "min": lambda a: a.min(axis=1),
                    "max": lambda a: a.max(axis=1)}

        def write_fire(f, bufs):
            pos = (fire_pos[f] + jnp.arange(SPW, dtype=jnp.int32)) % S
            row = jnp.clip(fire_row[f], 0, R - 1)

            def do_fire(b):
                outs, count_out = b
                crow = count[:, pos].sum(axis=1)
                count_out = jax.lax.dynamic_update_index_in_dim(
                    count_out, crow, row, 0)
                new_outs = {}
                for name, _dt, scatter, _ident in vfields:
                    vrow = _COMBINE[scatter](state[name][:, pos])
                    new_outs[name] = jax.lax.dynamic_update_index_in_dim(
                        outs[name], vrow, row, 0)
                return (new_outs if vfields else outs), count_out

            return jax.lax.cond(fire_valid[f] > 0, do_fire, lambda b: b, bufs)

        bufs = (outs, count_out)
        for f in range(F):
            bufs = write_fire(f, bufs)
        outs, count_out = bufs

        # purge expired ring columns (reset to the field's identity); under
        # a cond for the same reason — the S*K multiply/where is pure
        # identity on the all-ones masks most steps carry
        def do_purge(sc):
            state, count = sc
            count = count * purge_mask[None, :]
            if vfields:
                state = {
                    name: jnp.where(
                        purge_mask[None, :] > 0,
                        state[name],
                        jnp.asarray(ident, dt),
                    )
                    for name, dt, _scatter, ident in vfields
                }
            return state, count

        purged = jnp.any(purge_mask == 0)
        state, count = jax.lax.cond(
            purged, do_purge, lambda sc: sc, (state, count))
        if phase_counters:
            phase_c = phase_c + jnp.stack([
                jnp.sum((idx >= 0).astype(jnp.int32)),
                jnp.sum(fire_valid).astype(jnp.int32),
                purged.astype(jnp.int32),
            ])
            return (state, count, outs, count_out, phase_c), None
        return (state, count, outs, count_out), None

    return step
