"""Parallel execution over a device mesh: key-group sharding (the DP axis),
on-device keyBy all-to-all (the shuffle), psum merges (global windows)."""
