"""Device-mesh helpers.

The parallelism mapping (SURVEY.md §2.7): the reference's data parallelism —
N subtasks over disjoint key-group ranges (ExecutionVertex per subtask,
KeyGroupRangeAssignment.java:63) — becomes ONE mesh axis ("shards"); each
device owns a contiguous key-group range. keyBy shuffles
(KeyGroupStreamPartitioner + Netty N1/N2) become `all_to_all` collectives
over ICI inside shard_map programs (ops/exchange.py); global-window merges
(Nexmark Q7) become `psum`. Rescaling = remapping key-group ranges onto a
different mesh size at restore (state/columnar snapshots are keyed by
key group, not device).
"""

from __future__ import annotations

from typing import List, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from flink_tpu.core.keygroups import KeyGroupRange, key_group_range_for_operator

SHARD_AXIS = "shards"


def build_mesh(num_shards: Optional[int] = None, axis_name: str = SHARD_AXIS) -> Mesh:
    devices = jax.devices()
    n = num_shards or len(devices)
    if n > len(devices):
        raise ValueError(f"requested {n} shards but only {len(devices)} devices")
    return Mesh(np.array(devices[:n]), (axis_name,))


def usable_mesh_size(want: int, available: int, key_capacity: int) -> int:
    """THE mesh-size clamp, single-sourced: `want` devices (0 = all
    available) clamped to the visible device count, then rounded DOWN to
    the largest divisor of `key_capacity` so contiguous key ranges divide
    evenly across shards. 1 means no multi-device mesh applies. Every
    consumer of the clamp (runner construction, the autoscaler's
    reachability pre-check, the chaos scenario's expected-size math, the
    bench) must call this — a privately re-derived copy can silently
    diverge and turn accepted rescale targets into no-op churn."""
    n = max(1, min(int(want) or int(available), int(available)))
    while n > 1 and key_capacity % n != 0:
        n -= 1
    return n


def shard_ranges(mesh: Mesh, max_parallelism: int, axis_name: str = SHARD_AXIS) -> List[KeyGroupRange]:
    """Key-group range per shard (the reference's operator-index ranges)."""
    n = mesh.shape[axis_name]
    return [key_group_range_for_operator(max_parallelism, n, i) for i in range(n)]


def sharded(mesh: Mesh, *axes) -> NamedSharding:
    """NamedSharding partitioning the given leading axes."""
    return NamedSharding(mesh, P(*axes))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
