"""Ring collectives over ICI: bandwidth-optimal merges for large state.

The long-context scaling patterns of ML systems (ring attention: rotate
blocks around the ICI ring with `ppermute`, overlap compute with the
transfer) applied to this framework's big dimension — per-key window state.
`psum` is latency-optimal for small merges; for LARGE per-shard state
(wide accumulator panels, big top-k candidate sets) the bandwidth-optimal
form is the classic ring: reduce-scatter then all-gather, each step moving
1/n of the state to a neighbor, n-1 times — total bytes on the wire
2·(n-1)/n·|state| regardless of n.

Used for: global-window merges whose combined state is too wide for one
psum (Nexmark Q7-style global aggregates over huge key panels), and as the
ring-attention-shaped primitive for future sequence-sharded operators.
All functions run inside `shard_map`/`pmap` bodies with a named mesh axis.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _ring_perm(n: int, shift: int = 1):
    return [(i, (i + shift) % n) for i in range(n)]


def ring_reduce_scatter(x: jnp.ndarray, axis_name: str, combine=jnp.add) -> jnp.ndarray:
    """x: [n, chunk, ...] per shard (chunked along the shard axis). Returns
    this shard's fully-combined chunk [chunk, ...].

    n-1 ppermute steps; step k sends the partial for chunk (me - k - 1) to
    the right neighbor, which folds its own contribution in — the first
    half of a ring all-reduce.
    """
    n = jax.lax.psum(1, axis_name)
    me = jax.lax.axis_index(axis_name)

    def step(k, carry):
        x, send = carry
        recv = jax.lax.ppermute(send, axis_name, _ring_perm(n))
        # fold my contribution for the chunk now arriving:
        # after k+1 hops the travelling partial is for chunk (me - k - 2)
        idx = (me - k - 2) % n
        mine = jax.lax.dynamic_index_in_dim(x, idx, 0, keepdims=False)
        return x, combine(recv, mine)

    send0 = jax.lax.dynamic_index_in_dim(x, (me - 1) % n, 0, keepdims=False)
    _, out = jax.lax.fori_loop(0, n - 1, step, (x, send0))
    # after n-1 steps the accumulated partial sitting here is chunk (me - n) % n == me
    return out


def ring_all_gather(chunk: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """Inverse phase: every shard ends with all chunks stacked [n, ...]."""
    n = jax.lax.psum(1, axis_name)
    me = jax.lax.axis_index(axis_name)
    out0 = jnp.zeros((n,) + chunk.shape, chunk.dtype)
    out0 = jax.lax.dynamic_update_index_in_dim(out0, chunk, me, 0)

    def step(k, carry):
        out, send = carry
        recv = jax.lax.ppermute(send, axis_name, _ring_perm(n))
        idx = (me - k - 1) % n
        out = jax.lax.dynamic_update_index_in_dim(out, recv, idx, 0)
        return out, recv

    out, _ = jax.lax.fori_loop(0, n - 1, step, (out0, chunk))
    return out


def ring_all_reduce(x: jnp.ndarray, axis_name: str, combine=jnp.add) -> jnp.ndarray:
    """Bandwidth-optimal all-reduce of x (identical shape on every shard):
    chunk along dim 0 (padded to n), reduce-scatter, all-gather, unpad."""
    n = jax.lax.psum(1, axis_name)
    rows = x.shape[0]
    pad = (-rows) % n
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad,) + x.shape[1:], x.dtype)])
    chunks = x.reshape((n, x.shape[0] // n) + x.shape[1:])
    mine = ring_reduce_scatter(chunks, axis_name, combine)
    full = ring_all_gather(mine, axis_name)
    full = full.reshape((x.shape[0],) + x.shape[1:])
    return full[:rows]


def ring_global_topk(values: jnp.ndarray, k: int, axis_name: str):
    """Global top-k across shards by rotating candidate sets around the ring
    and re-selecting at each hop (k values travel, not the whole panel) —
    the Nexmark-Q5-style hot-items merge at ring cost O(n·k).

    values: this shard's scores [m]. Returns (topk_values[k], topk_shard[k])
    replicated on every shard.
    """
    n = jax.lax.psum(1, axis_name)
    me = jax.lax.axis_index(axis_name)
    v, _ = jax.lax.top_k(values, min(k, values.shape[0]))
    if v.shape[0] < k:
        v = jnp.concatenate([v, jnp.full(k - v.shape[0], -jnp.inf, v.dtype)])
    src = jnp.full((k,), me, jnp.int32)

    def step(_, carry):
        best_v, best_s, trav_v, trav_s = carry
        # rotate each shard's ORIGINAL candidate set around the ring (merged
        # sets would double-count values already folded in)
        trav_v = jax.lax.ppermute(trav_v, axis_name, _ring_perm(n))
        trav_s = jax.lax.ppermute(trav_s, axis_name, _ring_perm(n))
        allv = jnp.concatenate([best_v, trav_v])
        alls = jnp.concatenate([best_s, trav_s])
        nv, idx = jax.lax.top_k(allv, k)
        return nv, alls[idx], trav_v, trav_s

    best_v, best_s, _, _ = jax.lax.fori_loop(0, n - 1, step, (v, src, v, src))
    return best_v, best_s
