"""Key-group routing for the sharded superscan (parallel.mesh.skew-rebalance).

The static mesh owner function — ``dst = kid // K_local``, contiguous key
ranges per device — is what makes zipf-skewed traffic slow: whichever
device owns the hot key range absorbs the hot keys' full mass while the
rest of the mesh idles. This module replaces it with a ROUTING TABLE over
key-groups (the same contiguous ``kid * G // K`` ranges the key-stats fold
and the reference's KeyGroupRangeAssignment partition by, here exact
``kid // Kg`` because G divides K): ``assign[g]`` names the device that
owns group ``g``, and each device lays the groups it owns out in its local
row space in group-id order. The identity assignment reproduces the static
contiguous layout EXACTLY (device d owns groups d*G/n .. (d+1)*G/n - 1, so
local row = kid - d*K_local) — routing is placement, never semantics.

Hard invariant: every device owns exactly G/n groups. Device state is a
fixed [n, K_local, S] allocation; an assignment that gave one device more
groups than its row space holds would have nowhere to put them. The
balanced LPT planner in ``plan_balanced_assignment`` respects this by
construction, and ``KeyGroupRouting.with_assignment`` validates it.

Snapshots stay canonical [K, S] in global key order: ``to_device_layout``
/ ``to_canonical`` convert between the canonical order and the routed
device-major layout with one host permutation, so checkpoints restore
across any mesh size AND any routing table.

Layering: pure numpy over plain arrays (ARCH001 parallel layer — no
runtime, no scheduler; the rebalance POLICY that decides new assignments
lives in scheduler/rebalancer.py and hands plain arrays back).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np


def choose_key_groups(key_capacity: int, n_shards: int, want: int = 0) -> int:
    """The routing granularity: the largest group count <= `want`
    (0 = auto 128) that is a multiple of the mesh size AND divides the
    key capacity — both required so every device owns exactly G/n groups
    of exactly K/G keys. Floor n_shards (one group per device = the
    static layout, nothing to rebalance but still well-formed)."""
    key_capacity = int(key_capacity)
    n_shards = max(int(n_shards), 1)
    want = int(want) or 128
    want = max(min(want, key_capacity), n_shards)
    g = (want // n_shards) * n_shards
    while g > n_shards and key_capacity % g != 0:
        g -= n_shards
    if g < n_shards or key_capacity % g != 0:
        g = n_shards
    return g


def plan_balanced_assignment(group_loads: np.ndarray, n_shards: int,
                             current: Optional[np.ndarray] = None
                             ) -> np.ndarray:
    """Sticky balanced LPT: sort groups by load descending; each STAYS
    with its current owner while that keeps the owner within ~5% of the
    perfectly even per-device load (and within the G/n slot cap — every
    device must end with exactly G/n groups, the fixed row-space
    invariant), and otherwise moves to the least-loaded open device.
    Stickiness makes an already-balanced placement a fixpoint (uniform
    traffic replans to itself, zero moves) and a skewed one move only
    the groups the imbalance pays for."""
    loads = np.asarray(group_loads, np.float64)
    g = loads.shape[0]
    n = int(n_shards)
    if g % n != 0:
        raise ValueError(f"{g} groups do not divide over {n} shards")
    cap = g // n
    cur = (np.asarray(current, np.int64) if current is not None
           else (np.arange(g, dtype=np.int64) * n) // g)
    target = loads.sum() / n
    order = np.argsort(-loads, kind="stable")
    dev_load = np.zeros(n, np.float64)
    dev_count = np.zeros(n, np.int64)
    assign = np.empty(g, np.int32)
    for gi in order:
        open_devs = np.flatnonzero(dev_count < cap)
        best = open_devs[np.argmin(dev_load[open_devs])]
        owner = int(cur[gi])
        if dev_count[owner] < cap and (
                owner == best
                or dev_load[owner] + loads[gi] <= target * 1.05 + 1e-9):
            best = owner
        assign[gi] = best
        dev_load[best] += loads[gi]
        dev_count[best] += 1
    return assign


def predicted_skew(group_loads: np.ndarray, assign: np.ndarray,
                   n_shards: int) -> float:
    """max/mean per-device load under an assignment (the meshLoadSkew
    this placement would produce if traffic held its shape)."""
    loads = np.asarray(group_loads, np.float64)
    total = float(loads.sum())
    if total <= 0:
        return 1.0
    dev = np.zeros(int(n_shards), np.float64)
    np.add.at(dev, np.asarray(assign, np.int64), loads)
    return float(dev.max() / (total / int(n_shards)))


class KeyGroupRouting:
    """One routing table: assignment + the derived layout permutations.

    ``perm[kid]`` = kid's position in the device-major flat layout
    (device * K_local + slot(group) * Kg + kid % Kg), where slot(group)
    is the group's rank among the groups its device owns, in group-id
    order. ``g_dst``/``g_slot`` are the [G] tables the compiled per-shard
    program gathers from (passed as ARGUMENTS — remapping never
    recompiles)."""

    def __init__(self, key_capacity: int, n_shards: int,
                 num_groups: int = 0, *,
                 assign: Optional[Sequence[int]] = None, version: int = 0):
        self.K = int(key_capacity)
        self.n = max(int(n_shards), 1)
        if self.K % self.n != 0:
            raise ValueError(
                f"key capacity {self.K} must divide over {self.n} shards")
        self.G = choose_key_groups(self.K, self.n, num_groups)
        self.Kg = self.K // self.G
        self.version = int(version)
        if assign is None:
            assign = (np.arange(self.G, dtype=np.int64) * self.n) // self.G
        self._set(np.asarray(assign, np.int32))

    # -- construction / mutation ---------------------------------------
    def _set(self, assign: np.ndarray) -> None:
        if assign.shape != (self.G,):
            raise ValueError(
                f"assignment has {assign.shape} entries, expected {self.G}")
        counts = np.bincount(assign, minlength=self.n)
        if assign.min() < 0 or assign.max() >= self.n or \
                not np.all(counts == self.G // self.n):
            raise ValueError(
                "invalid assignment: every device must own exactly "
                f"G/n = {self.G // self.n} groups (got {counts.tolist()})")
        self.assign = assign.astype(np.int32)
        # slot of group g = rank of g among its owner's groups (stable in
        # group-id order); identity assignment => slot = g % (G/n)
        slot = np.empty(self.G, np.int64)
        for d in range(self.n):
            mine = np.flatnonzero(self.assign == d)
            slot[mine] = np.arange(mine.size)
        self.slot = slot.astype(np.int32)
        kid = np.arange(self.K, dtype=np.int64)
        g = kid // self.Kg
        kl = self.K // self.n
        self.perm = (self.assign[g].astype(np.int64) * kl
                     + self.slot[g].astype(np.int64) * self.Kg
                     + kid % self.Kg)

    def with_assignment(self, assign: Sequence[int]) -> "KeyGroupRouting":
        """A new table (version + 1) with the given group->device map."""
        return KeyGroupRouting(self.K, self.n, self.G,
                               assign=assign, version=self.version + 1)

    @property
    def is_identity(self) -> bool:
        return bool(np.array_equal(
            self.assign, (np.arange(self.G, dtype=np.int64) * self.n)
            // self.G))

    # -- layout conversion (host, off the dispatch hot path) -----------
    def to_device_layout(self, canonical: np.ndarray) -> np.ndarray:
        """Canonical [K, ...] rows -> device-major flat [K, ...] rows
        (caller reshapes to [n, K_local, ...])."""
        flat = np.empty_like(canonical)
        flat[self.perm] = canonical
        return flat

    def to_canonical(self, flat: np.ndarray) -> np.ndarray:
        """Device-major flat [K, ...] rows -> canonical key order."""
        return flat[self.perm]

    # -- decision inputs ------------------------------------------------
    def group_loads(self, key_loads: np.ndarray) -> np.ndarray:
        """Fold canonical per-key loads into per-group loads [G]."""
        loads = np.asarray(key_loads, np.int64)
        gid = np.arange(self.K, dtype=np.int64) // self.Kg
        out = np.zeros(self.G, np.int64)
        np.add.at(out, gid, loads)
        return out

    def device_of_groups(self) -> List[List[int]]:
        """Groups per device, for the observability payload."""
        return [np.flatnonzero(self.assign == d).tolist()
                for d in range(self.n)]

    def payload(self) -> dict:
        """JSON-safe routing block for /jobs/:id/device."""
        moved = int(np.sum(self.assign != (
            np.arange(self.G, dtype=np.int64) * self.n) // self.G))
        return {
            "version": self.version,
            "numKeyGroups": self.G,
            "groupsPerDevice": self.G // self.n,
            "movedGroups": moved,
            "assignment": self.assign.tolist(),
        }
