"""Sharded fused superscan: the flagship kernel composed with the ICI shuffle.

`FusedWindowPipeline` runs the whole T-step window dispatch as one compiled
program on one chip; `ShardedTpuWindowOperator` scales keyed window state
across a mesh but dispatches per step. This module composes the two: ONE
`shard_map` program per dispatch in which every step (a) routes its records
to their key-range owners with a `lax.all_to_all` over ICI — the in-scan
analogue of the reference's network shuffle (KeyGroupStreamPartitioner →
RecordWriter.emit:105) — and (b) runs the shared superscan ingest/fire/purge
body (`fused_window_pipeline.make_superscan_step`) on the shard's local key
range. Data parallelism over sources, key parallelism over state, zero host
involvement between steps.

Keys partition into contiguous ranges: shard = kid // K_local, and since the
segment encoding is `idx = kid * NSB + srel`, localizing is one subtract
(`idx - base * NSB`). Routing uses the positional lane protocol of
`ops/exchange.py`: the send buffer is [n, B] with non-destination lanes
INVALID, so the all-to-all needs no data-dependent compaction; each shard
then ingests n*B lanes per step (mostly INVALID, dropped for free by the
one-hot/scatter semantics).

Fire/purge control is replicated (all shards fire the same window rows);
each shard writes its own [R, K_local] slab and the host concatenates along
the key axis at resolve. Snapshots are canonical [K, S] global arrays,
interchangeable with single-chip `FusedWindowPipeline` snapshots — which
makes n -> m shard rescaling a restore.

Validated on the virtual 8-device CPU mesh (tests/test_sharded_superscan.py)
and dry-run by the driver via __graft_entry__.dryrun_multichip; on real
hardware the same program rides ICI.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from flink_tpu.utils.jax_compat import shard_map

from flink_tpu.api.windowing.assigners import WindowAssigner
from flink_tpu.ops.aggregators import VALUE, resolve
from flink_tpu.runtime.fused_window_pipeline import (
    DeferredEmissions,
    FusedWindowPipeline,
    make_superscan_step,
)


class ShardedFusedPipeline:
    """Keyed window aggregation over a device mesh, T steps per dispatch."""

    def __init__(
        self,
        mesh: Mesh,
        assigner: WindowAssigner,
        aggregate,
        *,
        key_capacity: int,
        num_slices: int = 32,
        nsb: int = 4,
        fires_per_step: int = 2,
        out_rows: int = 64,
        chunk: int = 1024,
        exact_sums: bool = True,
        axis: str = "shards",
    ):
        self.mesh = mesh
        self.axis = axis
        self.n = mesh.shape[axis]
        if key_capacity % self.n != 0:
            raise ValueError(
                f"key_capacity {key_capacity} must divide over {self.n} shards"
            )
        # the planner (and the canonical geometry/cursor state) is a
        # plan-only single-chip pipeline over the GLOBAL key space; its
        # device arrays are never dispatched
        self._planner = FusedWindowPipeline(
            assigner, aggregate,
            key_capacity=key_capacity, num_slices=num_slices, nsb=nsb,
            fires_per_step=fires_per_step, out_rows=out_rows, chunk=chunk,
            exact_sums=exact_sums, backend="xla", plan_only=True,
        )
        self.agg = self._planner.agg
        self.K = key_capacity
        self.K_local = key_capacity // self.n
        self.S = self._planner.S
        self.NSB = nsb
        self.F = fires_per_step
        self.R = out_rows
        self.chunk = chunk
        self.exact = exact_sums
        self._value_fields = [f for f in self.agg.fields if f.source == VALUE]
        self._needs_vals = bool(self._value_fields)
        self._init_state()
        self._fn_cache: Dict[Tuple, Any] = {}
        # device-plane observability: an attached CompileTracker wraps the
        # sharded dispatch; phase counters thread through the shared
        # superscan step body (summed over shards at resolve, accumulated
        # into the planner's phase_totals)
        self.compile_tracker = None
        self.phase_counters = False

    # ------------------------------------------------------------------
    def attach_device_stats(self, tracker, phase_counters: bool = True) -> None:
        """Wire a CompileTracker (metrics/device_stats.py) around the
        sharded dispatch. Call before the first dispatch: the phase flag
        is part of the executable cache key."""
        self.compile_tracker = tracker
        self.phase_counters = bool(phase_counters)

    @property
    def phase_totals(self):
        return self._planner.phase_totals

    def key_loads(self):
        """Global per-key record counts ([K]) for the key-stats fold —
        one reshape + segment-sum over the sharded count ring."""
        count = getattr(self, "_count", None)
        if count is None:
            return None
        return count.reshape(self.K, self.S).sum(axis=1)

    def key_stats_ready(self) -> bool:
        return self._planner.max_seen_slice is not None

    def state_row_bytes(self) -> int:
        return self._planner.state_row_bytes()

    # ------------------------------------------------------------------
    def _shard_spec(self, *tail):
        return NamedSharding(self.mesh, P(self.axis, *tail))

    def _init_state(self) -> None:
        n, Kl, S = self.n, self.K_local, self.S
        self._count = jax.device_put(
            jnp.zeros((n, Kl, S), jnp.int32), self._shard_spec(None, None))
        self._state = {
            f.name: jax.device_put(
                jnp.full((n, Kl, S), f.identity, jnp.dtype(f.dtype)),
                self._shard_spec(None, None))
            for f in self._value_fields
        }

    @property
    def num_late_records_dropped(self) -> int:
        return self._planner.num_late_records_dropped

    # ------------------------------------------------------------------
    def _build(self, T: int, B: int):
        phases = self.phase_counters
        key = (T, B, phases)
        if key in self._fn_cache:
            return self._fn_cache[key]

        n, Kl, S, axis = self.n, self.K_local, self.S, self.axis
        NSB, R = self.NSB, self.R
        lanes = n * B
        # the per-shard superscan body runs on K_local keys over n*B lanes
        chunk = self.chunk
        while lanes % chunk != 0:
            chunk //= 2
        step = make_superscan_step(
            self.agg, Kl, S, NSB, self.F, R, self._planner.spw, chunk,
            self.exact, phase_counters=phases,
        )
        nf = len(self._value_fields)

        def per_shard(count, state_t, idx, vals, smin_pos, fire_pos,
                      fire_valid, fire_row, purge_mask):
            # leading mesh dim is 1 inside the shard
            count = count[0]
            idx = idx[0]
            if nf:
                vals = vals[0]
            state = {
                f.name: state_t[i][0]
                for i, f in enumerate(self._value_fields)
            }
            base = jax.lax.axis_index(axis).astype(jnp.int32) * Kl

            def routed_step(carry, args):
                idx_row, vals_row, *plan_row = args
                # destination = owner of the record's key range
                valid = idx_row >= 0
                kid = idx_row // NSB
                dst = jnp.where(valid, kid // Kl, -1)
                rows = jnp.arange(n, dtype=jnp.int32)[:, None]
                route = rows == dst[None, :]                       # [n, B]
                send_idx = jnp.where(route, idx_row[None, :], -1)
                recv_idx = jax.lax.all_to_all(
                    send_idx, axis, split_axis=0, concat_axis=0, tiled=False
                ).reshape(-1)                                      # [n*B]
                # localize: idx - base*NSB keeps srel intact
                local_idx = jnp.where(recv_idx >= 0, recv_idx - base * NSB, -1)
                if nf:
                    send_v = jnp.where(route, vals_row[None, :], 0.0)
                    recv_v = jax.lax.all_to_all(
                        send_v, axis, split_axis=0, concat_axis=0, tiled=False
                    ).reshape(-1)
                else:
                    recv_v = vals_row  # [1] placeholder
                return step(carry, (local_idx, recv_v, *plan_row))

            outs0 = {
                f.name: jnp.zeros((R, Kl), jnp.dtype(f.dtype))
                for f in self._value_fields
            }
            count_out0 = jnp.zeros((R, Kl), jnp.int32)
            carry0 = (state, count, outs0, count_out0)
            if phases:
                carry0 = carry0 + (jnp.zeros((3,), jnp.int32),)
            carry, _ = jax.lax.scan(
                routed_step,
                carry0,
                (idx, vals, smin_pos, fire_pos, fire_valid, fire_row,
                 purge_mask),
            )
            if phases:
                state, count, outs, count_out, pc = carry
            else:
                state, count, outs, count_out = carry
            names = [f.name for f in self._value_fields]
            out = (
                count[None], tuple(state[nm][None] for nm in names),
                count_out[None], tuple(outs[nm][None] for nm in names),
            )
            if phases:
                out = out + (pc[None],)   # [1, 3] per shard
            return out

        out_specs = (
            P(axis, None, None),
            (P(axis, None, None),) * nf,
            P(axis, None, None),                      # count_out [n,R,Kl]
            (P(axis, None, None),) * nf,
        )
        if phases:
            out_specs = out_specs + (P(axis, None),)  # phase counters [n,3]
        sharded = shard_map(
            per_shard,
            mesh=self.mesh,
            in_specs=(
                P(axis, None, None),                      # count [n,Kl,S]
                (P(axis, None, None),) * nf,              # field states
                P(axis, None, None),                      # idx [n,T,B]
                P(axis, None, None) if nf else P(None, None),  # vals
                P(None), P(None, None), P(None, None), P(None, None),
                P(None, None),                            # plan (replicated)
            ),
            out_specs=out_specs,
            check_vma=False,
        )
        fn = jax.jit(sharded)
        self._fn_cache[key] = fn
        return fn

    # ------------------------------------------------------------------
    def stage_superbatch(self, batches: Sequence, watermarks: Sequence[int]):
        """Host planning + staging. `batches[t] = (keys, vals|None, ts)` is
        the step's GLOBAL record set; lanes are dealt round-robin across the
        n source shards (any split works — the in-scan all-to-all re-routes
        by key ownership)."""
        plan_idx, plan_vals, plan = self._planner.stage_superbatch(
            batches, watermarks)
        idx_h = np.asarray(plan_idx)          # [T, B_padded] int32
        T, B = idx_h.shape
        # pad B so every shard gets an equal lane count
        Bs = -(-B // self.n)
        if Bs * self.n != B:
            pad = Bs * self.n - B
            idx_h = np.concatenate(
                [idx_h, np.full((T, pad), -1, np.int32)], axis=1)
        idx_sh = idx_h.reshape(T, self.n, Bs).transpose(1, 0, 2)
        idx_d = jax.device_put(
            jnp.asarray(idx_sh), self._shard_spec(None, None))
        if self._needs_vals:
            vals_h = np.asarray(plan_vals)
            if Bs * self.n != B:
                vals_h = np.concatenate(
                    [vals_h, np.zeros((T, Bs * self.n - B), np.float32)],
                    axis=1)
            vals_d = jax.device_put(
                jnp.asarray(vals_h.reshape(T, self.n, Bs).transpose(1, 0, 2)),
                self._shard_spec(None, None))
        else:
            vals_d = jnp.zeros((T, 1), jnp.float32)
        return idx_d, vals_d, plan

    def process_superbatch(self, batches, watermarks, *, staged=None,
                           defer: bool = False):
        if staged is None:
            staged = self.stage_superbatch(batches, watermarks)
        idx_d, vals_d, plan = staged
        smin_pos, fire_pos, fire_valid, fire_row, purge_mask, fires = plan
        T = int(smin_pos.shape[0])
        B = int(idx_d.shape[2])
        run = self._build(T, B)
        names = [f.name for f in self._value_fields]
        args = (self._count, tuple(self._state[nm] for nm in names),
                idx_d, vals_d, smin_pos, fire_pos, fire_valid, fire_row,
                purge_mask)
        if self.compile_tracker is not None:
            out = self.compile_tracker.call(
                "sharded_superscan", run, args,
                {"T": T, "B": B, "K": self.K, "S": self.S, "n": self.n,
                 "dtype": "+".join(str(np.dtype(f.dtype))
                                   for f in self._value_fields) or "count"})
        else:
            out = run(*args)
        pc_total = None
        if self.phase_counters:
            count, states, count_out, field_outs, pc = out
            pc_total = pc.sum(axis=0)   # fold the shard axis on device
        else:
            count, states, count_out, field_outs = out
        self._count = count
        self._state = dict(zip(names, states))
        # [n, R, K_local] -> [R, K] (contiguous key ranges)
        count_rows = jnp.transpose(count_out, (1, 0, 2)).reshape(self.R, self.K)
        out_rows = {
            nm: jnp.transpose(o, (1, 0, 2)).reshape(self.R, self.K)
            for nm, o in zip(names, field_outs)
        }
        deferred = DeferredEmissions(self._planner, fires, count_rows,
                                     out_rows, phase_counts=pc_total)
        return deferred if defer else deferred.resolve()

    # ------------------------------------------------------------------
    def snapshot(self) -> dict:
        """Canonical [K, S] global arrays — interchangeable with single-chip
        FusedWindowPipeline snapshots (restore re-shards, so n -> m shard
        rescaling is just snapshot + restore)."""
        snap = {
            "state": {
                k: np.asarray(v).reshape(self.K, self.S)
                for k, v in self._state.items()
            },
            "count": np.asarray(self._count).reshape(self.K, self.S),
            "watermark": self._planner.watermark,
            "fire_cursor": self._planner.fire_cursor,
            "purged_to": self._planner.purged_to,
            "min_used_slice": self._planner.min_used_slice,
            "max_seen_slice": self._planner.max_seen_slice,
            "num_late_dropped": self._planner.num_late_records_dropped,
        }
        return snap

    def restore(self, snap: dict) -> None:
        if snap["count"].shape[0] != self.K:
            raise ValueError(
                f"snapshot key capacity {snap['count'].shape[0]} != {self.K}"
            )
        n, Kl, S = self.n, self.K_local, self.S
        self._count = jax.device_put(
            jnp.asarray(snap["count"].reshape(n, Kl, S)),
            self._shard_spec(None, None))
        self._state = {
            k: jax.device_put(
                jnp.asarray(v.reshape(n, Kl, S)), self._shard_spec(None, None))
            for k, v in snap["state"].items()
        }
        self._planner.watermark = snap["watermark"]
        self._planner.fire_cursor = snap["fire_cursor"]
        self._planner.purged_to = snap["purged_to"]
        self._planner.min_used_slice = snap["min_used_slice"]
        self._planner.max_seen_slice = snap["max_seen_slice"]
        self._planner.num_late_records_dropped = snap["num_late_dropped"]
