"""Sharded fused superscan: the flagship kernel composed with the ICI shuffle.

`FusedWindowPipeline` runs the whole T-step window dispatch as one compiled
program on one chip; `ShardedTpuWindowOperator` scales keyed window state
across a mesh but dispatches per step. This module composes the two: ONE
`shard_map` program per dispatch in which every step (a) routes its records
to their key-range owners with a `lax.all_to_all` over ICI — the in-scan
analogue of the reference's network shuffle (KeyGroupStreamPartitioner →
RecordWriter.emit:105) — and (b) runs the shared superscan ingest/fire/purge
body (`ops/superscan.make_superscan_step`) on the shard's local key range.
Data parallelism over sources, key parallelism over state, zero host
involvement between steps.

Keys partition into contiguous ranges: shard = kid // K_local, and since the
segment encoding is `idx = kid * NSB + srel`, localizing is one subtract
(`idx - base * NSB`). Routing uses the positional lane protocol of
`ops/exchange.py`: the send buffer is [n, B] with non-destination lanes
INVALID, so the all-to-all needs no data-dependent compaction; each shard
then ingests n*B lanes per step (mostly INVALID, dropped for free by the
one-hot/scatter semantics).

Two skew-adaptive layers compose on top (both pure perf switches —
docs/multichip.md "Pre-exchange local combine" / "Skew-aware key-group
routing"): `local_combine` segment-reduces each shard's lanes by
(destination, key, rel-slice) BEFORE the all-to-all, so only dense
partials cross ICI (exact for decomposable aggregates; others route raw
transparently), and `skew_routing` replaces the static owner function
with a KeyGroupRouting table (parallel/routing.py) whose remaps are a
replicated-table swap plus one canonical host round trip — never a
recompile, never a semantics change (snapshots stay canonical [K, S]).

With a `TracedPrologue` (whole-graph fusion, PR 7) the pipeline additionally
runs the user's traceable map/filter/map_ts chain + key/value extraction
INSIDE the per-shard program, BEFORE the shuffle: each device transforms its
slice of the raw source columns, bins the surviving records by owning
key-group, and one all-to-all replaces what used to be a host dataplane hop.
This is what lets `DeviceChainRunner` point a fused user job — not just the
bench kernel — at the mesh.

Fire/purge control is replicated (all shards fire the same window rows);
each shard writes its own [R, K_local] slab and the host concatenates along
the key axis at resolve. Snapshots are canonical [K, S] global arrays,
interchangeable with single-chip `FusedWindowPipeline` snapshots — which
makes n -> m shard rescaling a restore.

Layering: `parallel` sits below the runtime (ARCH001 — it may import
core/ops/state/config, never runtime/api/table). The single-chip planner it
drives (`FusedWindowPipeline`, plan-only: pure host cursor state, no device
arrays) is imported lazily at construction, the sanctioned function-scoped
escape hatch.

Validated on the virtual 8-device CPU mesh (tests/test_sharded_superscan.py,
tests/test_multichip_runtime.py) and dry-run by the driver via
__graft_entry__.dryrun_multichip; on real hardware the same program rides
ICI.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from flink_tpu.utils.jax_compat import shard_map

from flink_tpu.ops.aggregators import VALUE, combine_reduce, decomposable
from flink_tpu.ops.superscan import (
    default_ingest,
    make_segment_partials,
    make_superscan_step,
)
from flink_tpu.parallel.routing import KeyGroupRouting


class ShardedFusedPipeline:
    """Keyed window aggregation over a device mesh, T steps per dispatch.

    Presents the same pipeline surface `FusedWindowOperator` drives on one
    chip (process_superbatch / process_superbatch_raw / ensure_key_capacity
    / snapshot / restore plus the planner-geometry delegates), so the
    operator adapter — and through it DeviceChainRunner — is mesh-agnostic.
    """

    def __init__(
        self,
        mesh: Mesh,
        assigner,
        aggregate,
        *,
        key_capacity: int,
        num_slices: Optional[int] = None,
        nsb: int = 4,
        fires_per_step: int = 2,
        out_rows: int = 64,
        chunk: int = 1024,
        exact_sums: bool = True,
        axis: str = "shards",
        prologue=None,
        assigners=None,
        local_combine: bool = False,
        skew_routing: bool = False,
        num_key_groups: int = 0,
    ):
        # runtime import is function-scoped: parallel/ sits below runtime in
        # the layer DAG (ARCH001), and the planner is pure host state
        from flink_tpu.runtime.fused_window_pipeline import (
            FusedWindowPipeline,
            SharedWindowPipeline,
        )

        self.mesh = mesh
        self.axis = axis
        self.n = mesh.shape[axis]
        if key_capacity % self.n != 0:
            raise ValueError(
                f"key_capacity {key_capacity} must divide over {self.n} shards"
            )
        # the planner (and the canonical geometry/cursor state) is a
        # plan-only single-chip pipeline over the GLOBAL key space; its
        # device arrays are never dispatched. With `assigners` (shared
        # partials) it is the multi-spec planner: the per-shard program
        # below picks up its per-slot fire_spws, so correlated windows
        # share one scan ON THE MESH exactly like single-chip.
        if assigners is not None:
            self._planner = SharedWindowPipeline(
                assigners, aggregate,
                key_capacity=key_capacity, num_slices=num_slices, nsb=nsb,
                fires_per_step=fires_per_step, out_rows=out_rows, chunk=chunk,
                exact_sums=exact_sums, backend="xla", plan_only=True,
                prologue=prologue,
            )
        else:
            self._planner = FusedWindowPipeline(
                assigner, aggregate,
                key_capacity=key_capacity, num_slices=num_slices, nsb=nsb,
                fires_per_step=fires_per_step, out_rows=out_rows, chunk=chunk,
                exact_sums=exact_sums, backend="xla", plan_only=True,
                prologue=prologue,
            )
        self.agg = self._planner.agg
        self.prologue = prologue
        self.K = key_capacity
        self.K_local = key_capacity // self.n
        self.S = self._planner.S
        self.NSB = nsb
        self.F = self._planner.F   # total fire slots (N*F when shared)
        self.R = out_rows
        self.chunk = chunk
        self.exact = exact_sums
        self._value_fields = [f for f in self.agg.fields if f.source == VALUE]
        self._needs_vals = bool(self._value_fields)
        # pre-exchange local combine (parallel.mesh.local-combine): shards
        # segment-reduce their lanes by (dst, key, rel-slice) BEFORE the
        # all-to-all, so a hot key crosses ICI as at most n partials per
        # slice. Exact only for decomposable aggregates — a
        # non-decomposable spec transparently keeps the route-raw exchange
        self.local_combine = bool(local_combine) and decomposable(self.agg)
        # skew-aware key-group routing (parallel.mesh.skew-rebalance): the
        # static `dst = kid // K_local` owner function becomes a
        # device-resident [G] routing table; remapping groups is a table
        # swap + host state re-layout, never a recompile. None = static.
        self._num_key_groups = int(num_key_groups)
        self.routing: Optional[KeyGroupRouting] = (
            KeyGroupRouting(key_capacity, self.n, num_key_groups)
            if skew_routing else None)
        self._g_dst = self._g_slot = self._perm_dev = None
        if self.routing is not None:
            self._refresh_route_tables()
        self._init_state()
        self._fn_cache: Dict[tuple, Any] = {}
        # device-plane observability: an attached CompileTracker wraps the
        # sharded dispatch; phase counters thread through the shared
        # superscan step body (summed over shards at resolve, accumulated
        # into the planner's phase_totals)
        self.compile_tracker = None
        self.phase_counters = False
        # latency mode (scheduler/latency_controller.py): donate the
        # sharded [n, Kl, S] scan carry to the executable. Streaming fire
        # readback (readback_steps) stays single-chip only — splitting the
        # mesh dispatch would multiply the per-step all-to-all count, so
        # the mesh path keeps span-granular readback by design. Set here
        # explicitly: __getattr__ would otherwise forward the read to the
        # plan-only planner and a write would shadow it confusingly.
        self.donate_carry = False

    # ------------------------------------------------------------------
    # planner-geometry delegation: StepNormalizer, DeferredEmissions, and
    # the operator adapter read the frontier/geometry surface of a
    # single-chip pipeline (g/sl/spw/offset/size_ms/slide_ms, the
    # watermark/fire/purge cursors, _j_*/_slice_of/_window_of,
    # phase_totals, num_late_records_dropped). On the mesh that state
    # lives in the plan-only planner — one source of truth for the window
    # math — so every attribute this class does not define itself
    # forwards there wholesale: a per-member delegate list would drift
    # (a forgotten entry surfaces only as a mesh-path AttributeError).
    # ------------------------------------------------------------------
    @property
    def planner(self):
        return self._planner

    def __getattr__(self, name):
        if name == "_planner":   # guard: no recursion before __init__ set it
            raise AttributeError(name)
        return getattr(self._planner, name)

    # ------------------------------------------------------------------
    def attach_device_stats(self, tracker, phase_counters: bool = True) -> None:
        """Wire a CompileTracker (metrics/device_stats.py) around the
        sharded dispatch. Call before the first dispatch: the phase flag
        is part of the executable cache key."""
        self.compile_tracker = tracker
        self.phase_counters = bool(phase_counters)

    @property
    def phase_totals(self):
        return self._planner.phase_totals

    def key_loads(self):
        """Global per-key record counts ([K], canonical key order) for the
        key-stats fold — one reshape + segment-sum over the sharded count
        ring (+ one gather when a routing table permutes the layout)."""
        count = getattr(self, "_count", None)
        if count is None:
            return None
        loads = count.reshape(self.K, self.S).sum(axis=1)
        if self.routing is not None:
            loads = jnp.take(loads, self._perm_dev, axis=0)
        return loads

    # ------------------------------------------------------------------
    # skew-aware key-group routing (parallel/routing.py): the table is a
    # pair of replicated [G] device arrays the compiled program gathers
    # from — remapping is a table swap plus ONE host round trip of the
    # canonical state, never a recompile. All mutators run off the
    # dispatch hot path (callers resolve in-flight dispatches first).
    # ------------------------------------------------------------------
    def _refresh_route_tables(self) -> None:
        r = self.routing
        self._g_dst = jnp.asarray(r.assign, jnp.int32)
        self._g_slot = jnp.asarray(r.slot, jnp.int32)
        self._perm_dev = jnp.asarray(r.perm, jnp.int32)

    def routing_version(self) -> Optional[int]:
        return None if self.routing is None else self.routing.version

    def routing_payload(self) -> Optional[dict]:
        return None if self.routing is None else self.routing.payload()

    def mesh_group_loads(self):
        """Per-key-group resident record loads [G] (canonical groups) —
        the skew rebalancer's decision input. None without a table."""
        if self.routing is None:
            return None
        loads = self.key_loads()
        if loads is None:
            return None
        return self.routing.group_loads(np.asarray(loads))

    def set_routing_assignment(self, assign) -> int:
        """Swap in a new group->device map: pull the canonical [K, S]
        state under the OLD table, bump the table, re-lay rows under the
        new one. Exact by construction — canonical state never changes,
        only its placement. Returns the new table version."""
        if self.routing is None:
            raise RuntimeError(
                "skew routing is disabled (parallel.mesh.skew-rebalance)")
        count, state = self._canonical_arrays()
        self.routing = self.routing.with_assignment(assign)
        self._refresh_route_tables()
        self._put_canonical(count, state)
        return self.routing.version

    def _canonical_arrays(self):
        """(count [K, S], {field: [K, S]}) in canonical key order."""
        count = np.asarray(self._count).reshape(self.K, self.S)
        state = {
            name: np.asarray(a).reshape(self.K, self.S)
            for name, a in self._state.items()
        }
        if self.routing is not None:
            count = self.routing.to_canonical(count)
            state = {k: self.routing.to_canonical(v)
                     for k, v in state.items()}
        return count, state

    def per_device_key_loads(self):
        """Per-device local per-key record counts ([n, K_local]): the
        input of the per-device skew fold — an even GLOBAL histogram can
        still leave one device owning every hot key-group, and the mesh
        telemetry must see that device, not device 0's view."""
        count = getattr(self, "_count", None)
        if count is None:
            return None
        return count.sum(axis=2)

    def key_stats_ready(self) -> bool:
        return self._planner.max_seen_slice is not None

    def state_row_bytes(self) -> int:
        return self._planner.state_row_bytes()

    # ------------------------------------------------------------------
    def _shard_spec(self, *tail):
        return NamedSharding(self.mesh, P(self.axis, *tail))

    def _init_state(self) -> None:
        n, Kl, S = self.n, self.K_local, self.S
        self._count = jax.device_put(
            jnp.zeros((n, Kl, S), jnp.int32), self._shard_spec(None, None))
        self._state = {
            f.name: jax.device_put(
                jnp.full((n, Kl, S), f.identity, jnp.dtype(f.dtype)),
                self._shard_spec(None, None))
            for f in self._value_fields
        }

    @property
    def num_late_records_dropped(self) -> int:
        return self._planner.num_late_records_dropped

    def ensure_key_capacity(self, required: int) -> None:
        """Grow the GLOBAL key dimension when the host dictionary outgrows
        K (classic keyed path only — traced chains fix capacity up front).
        Growth is to the next power of two rounded up to a multiple of the
        mesh size, so the contiguous key ranges keep dividing evenly; the
        canonical [K, S] grow-then-reshard costs one host round trip and
        one recompile, amortized by doubling exactly like the single-chip
        pipeline."""
        if required <= self.K:
            return
        new_k = 1 << (required - 1).bit_length()
        if new_k % self.n != 0:
            new_k = -(-new_k // self.n) * self.n
        n, S = self.n, self.S
        pad = new_k - self.K
        count, state = self._canonical_arrays()
        count = np.concatenate(
            [count, np.zeros((pad, S), np.int32)])
        idents = {f.name: (f.identity, np.dtype(f.dtype))
                  for f in self._value_fields}
        state = {
            k: np.concatenate([v, np.full((pad, S), *idents[k])])
            for k, v in state.items()
        }
        self.K = new_k
        self.K_local = new_k // n
        self._planner.K = new_k
        if self.routing is not None:
            # the table is sized to K: rebuild at identity over the grown
            # capacity (the rebalancer re-fires from fresh skew telemetry;
            # carrying an old-K assignment forward would be shape-invalid)
            self.routing = KeyGroupRouting(
                new_k, n, self._num_key_groups,
                version=self.routing.version + 1)
            self._refresh_route_tables()
        self._put_canonical(count, state)
        self._fn_cache.clear()   # executables captured the old K_local

    # ------------------------------------------------------------------
    # ------------------------------------------------------------------
    # exchange variants: the shared pieces the classic and raw builds
    # compose. `_dst_and_local` is THE owner function — static contiguous
    # ranges, or the routing table's group lookup; `_exchange_partials`
    # is the map-side combiner's exchange (dense per-destination partials
    # over ICI, folded per scatter kind on the receive side).
    # ------------------------------------------------------------------
    def _dst_and_local(self, g_tables):
        """fn(valid, kid, srel) -> (dst, local segment idx), -1 invalid."""
        n, Kl, NSB = self.n, self.K_local, self.NSB
        if g_tables is None:
            def fn(valid, kid, srel):
                dst = jnp.where(valid, kid // Kl, -1)
                lidx = jnp.where(valid, (kid % Kl) * NSB + srel, -1)
                return dst, lidx
            return fn
        g_dst, g_slot = g_tables
        Kg = self.routing.Kg

        def fn(valid, kid, srel):
            g = jnp.where(valid, kid // Kg, 0)
            dst = jnp.where(valid, g_dst[g], -1)
            lidx = jnp.where(
                valid, (g_slot[g] * Kg + kid % Kg) * NSB + srel, -1)
            return dst, lidx
        return fn

    def _exchange_partials(self, partials_fn, step, scatters):
        """fn(carry, pidx, vals, plan_row): segment-reduce this shard's
        lanes into flat [n*Kl*NSB] per-destination partials, ONE
        all-to-all per channel (count + each value field), fold across
        source shards by the field's own combiner, ingest pre-reduced."""
        n, Kl, NSB, axis = self.n, self.K_local, self.NSB, self.axis

        def fn(carry, pidx, vals, plan_row):
            cpart, parts = partials_fn(pidx, vals)
            rc = jax.lax.all_to_all(
                cpart.reshape(n, Kl * NSB), axis, split_axis=0,
                concat_axis=0, tiled=False)
            cpart_l = rc.sum(axis=0).reshape(Kl, NSB)
            parts_l = []
            for p, sc in zip(parts, scatters):
                rp = jax.lax.all_to_all(
                    p.reshape(n, Kl * NSB), axis, split_axis=0,
                    concat_axis=0, tiled=False)
                parts_l.append(
                    combine_reduce(sc)(rp, 0).reshape(Kl, NSB))
            return step(carry, (cpart_l, tuple(parts_l)) + tuple(plan_row))
        return fn

    def _make_step(self, lanes: int, phases: bool):
        chunk = self.chunk
        while lanes % chunk != 0:
            chunk //= 2
        return make_superscan_step(
            self.agg, self.K_local, self.S, self.NSB, self.F, self.R,
            self._planner.spw, chunk,
            self.exact,
            ingest="partials" if self.local_combine else default_ingest(),
            phase_counters=phases, fire_spws=self._planner._fire_spws,
        )

    def _make_partials_fn(self, B: int):
        pchunk = self.chunk
        while B % pchunk != 0:
            pchunk //= 2
        fn, _vf = make_segment_partials(
            self.agg, self.n * self.K_local * self.NSB, pchunk, self.exact,
            ingest=default_ingest())
        return fn

    def _build(self, T: int, B: int):
        phases = self.phase_counters
        combine = self.local_combine
        routed = self.routing is not None
        key = ("classic", T, B, phases, combine,
               None if not routed else self.routing.G, self.donate_carry)
        if key in self._fn_cache:
            return self._fn_cache[key]

        n, Kl, S, axis = self.n, self.K_local, self.S, self.axis
        NSB, R = self.NSB, self.R
        # the per-shard superscan body runs on K_local keys over n*B lanes
        step = self._make_step(n * B, phases)
        nf = len(self._value_fields)
        partials_fn = self._make_partials_fn(B) if combine else None
        scatters = [f.scatter for f in self._value_fields]

        def per_shard(count, state_t, idx, vals, *rest):
            if routed:
                *rest, g_dst, g_slot = rest
                owner = self._dst_and_local((g_dst, g_slot))
            else:
                owner = self._dst_and_local(None)
            smin_pos, fire_pos, fire_valid, fire_row, purge_mask = rest
            # leading mesh dim is 1 inside the shard
            count = count[0]
            idx = idx[0]
            if nf:
                vals = vals[0]
            state = {
                f.name: state_t[i][0]
                for i, f in enumerate(self._value_fields)
            }
            base = jax.lax.axis_index(axis).astype(jnp.int32) * Kl
            if combine:
                exchange = self._exchange_partials(
                    partials_fn, step, scatters)

            def routed_step(carry, args):
                idx_row, vals_row, *plan_row = args
                valid = idx_row >= 0
                kid = idx_row // NSB
                if combine:
                    dst, lidx = owner(valid, kid, idx_row % NSB)
                    pidx = jnp.where(valid, dst * (Kl * NSB) + lidx, -1)
                    return exchange(carry, pidx, vals_row, plan_row)
                if routed:
                    # route-raw under a table: the sender localizes (the
                    # receiver cannot invert an arbitrary table from a
                    # global idx without a second lookup)
                    dst, lidx = owner(valid, kid, idx_row % NSB)
                    send_payload, localize = lidx, (lambda r: r)
                else:
                    # destination = owner of the record's key range
                    dst = jnp.where(valid, kid // Kl, -1)
                    # localize: idx - base*NSB keeps srel intact
                    send_payload = idx_row
                    localize = lambda r: jnp.where(      # noqa: E731
                        r >= 0, r - base * NSB, -1)
                rows = jnp.arange(n, dtype=jnp.int32)[:, None]
                route = rows == dst[None, :]                       # [n, B]
                send_idx = jnp.where(route, send_payload[None, :], -1)
                recv_idx = jax.lax.all_to_all(
                    send_idx, axis, split_axis=0, concat_axis=0, tiled=False
                ).reshape(-1)                                      # [n*B]
                local_idx = localize(recv_idx)
                if nf:
                    send_v = jnp.where(route, vals_row[None, :], 0.0)
                    recv_v = jax.lax.all_to_all(
                        send_v, axis, split_axis=0, concat_axis=0, tiled=False
                    ).reshape(-1)
                else:
                    recv_v = vals_row  # [1] placeholder
                return step(carry, (local_idx, recv_v, *plan_row))

            outs0 = {
                f.name: jnp.zeros((R, Kl), jnp.dtype(f.dtype))
                for f in self._value_fields
            }
            count_out0 = jnp.zeros((R, Kl), jnp.int32)
            carry0 = (state, count, outs0, count_out0)
            if phases:
                carry0 = carry0 + (jnp.zeros((3,), jnp.int32),)
            carry, _ = jax.lax.scan(
                routed_step,
                carry0,
                (idx, vals, smin_pos, fire_pos, fire_valid, fire_row,
                 purge_mask),
            )
            if phases:
                state, count, outs, count_out, pc = carry
            else:
                state, count, outs, count_out = carry
            names = [f.name for f in self._value_fields]
            out = (
                count[None], tuple(state[nm][None] for nm in names),
                count_out[None], tuple(outs[nm][None] for nm in names),
            )
            if phases:
                out = out + (pc[None],)   # [1, 3] per shard
            return out

        out_specs = (
            P(axis, None, None),
            (P(axis, None, None),) * nf,
            P(axis, None, None),                      # count_out [n,R,Kl]
            (P(axis, None, None),) * nf,
        )
        if phases:
            out_specs = out_specs + (P(axis, None),)  # phase counters [n,3]
        in_specs = (
            P(axis, None, None),                      # count [n,Kl,S]
            (P(axis, None, None),) * nf,              # field states
            P(axis, None, None),                      # idx [n,T,B]
            P(axis, None, None) if nf else P(None, None),  # vals
            P(None), P(None, None), P(None, None), P(None, None),
            P(None, None),                            # plan (replicated)
        )
        if routed:
            in_specs = in_specs + (P(None), P(None))  # routing tables [G]
        sharded = shard_map(
            per_shard,
            mesh=self.mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=False,
        )
        # latency mode donates the carry (args 0/1: count + field states);
        # dispatch rebinds to the outputs, so the inputs die at enqueue
        fn = (jax.jit(sharded, donate_argnums=(0, 1)) if self.donate_carry
              else jax.jit(sharded))
        self._fn_cache[key] = fn
        return fn

    # ------------------------------------------------------------------
    def stage_superbatch(self, batches: Sequence, watermarks: Sequence[int]):
        """Host planning + staging. `batches[t] = (keys, vals|None, ts)` is
        the step's GLOBAL record set; lanes are dealt round-robin across the
        n source shards (any split works — the in-scan all-to-all re-routes
        by key ownership)."""
        plan_idx, plan_vals, plan = self._planner.stage_superbatch(
            batches, watermarks)
        idx_h = np.asarray(plan_idx)          # [T, B_padded] int32
        T, B = idx_h.shape
        # pad B so every shard gets an equal lane count
        Bs = -(-B // self.n)
        if Bs * self.n != B:
            pad = Bs * self.n - B
            idx_h = np.concatenate(
                [idx_h, np.full((T, pad), -1, np.int32)], axis=1)
        idx_sh = idx_h.reshape(T, self.n, Bs).transpose(1, 0, 2)
        idx_d = jax.device_put(
            jnp.asarray(idx_sh), self._shard_spec(None, None))
        if self._needs_vals:
            vals_h = np.asarray(plan_vals)
            if Bs * self.n != B:
                vals_h = np.concatenate(
                    [vals_h, np.zeros((T, Bs * self.n - B), np.float32)],
                    axis=1)
            vals_d = jax.device_put(
                jnp.asarray(vals_h.reshape(T, self.n, Bs).transpose(1, 0, 2)),
                self._shard_spec(None, None))
        else:
            vals_d = jnp.zeros((T, 1), jnp.float32)
        return idx_d, vals_d, plan

    def process_superbatch(self, batches, watermarks, *, staged=None,
                           defer: bool = False):
        from flink_tpu.runtime.fused_window_pipeline import DeferredEmissions

        if staged is None:
            staged = self.stage_superbatch(batches, watermarks)
        idx_d, vals_d, plan = staged
        smin_pos, fire_pos, fire_valid, fire_row, purge_mask, fires = plan
        T = int(smin_pos.shape[0])
        B = int(idx_d.shape[2])
        run = self._build(T, B)
        names = [f.name for f in self._value_fields]
        args = (self._count, tuple(self._state[nm] for nm in names),
                idx_d, vals_d, smin_pos, fire_pos, fire_valid, fire_row,
                purge_mask)
        if self.routing is not None:
            args = args + (self._g_dst, self._g_slot)
        if self.compile_tracker is not None:
            out = self.compile_tracker.call(
                "sharded_superscan", run, args,
                {"T": T, "B": B, "K": self.K, "S": self.S, "n": self.n,
                 "dtype": "+".join(str(np.dtype(f.dtype))
                                   for f in self._value_fields) or "count"})
        else:
            out = run(*args)
        pc_total = None
        if self.phase_counters:
            count, states, count_out, field_outs, pc = out
            pc_total = pc.sum(axis=0)   # fold the shard axis on device
        else:
            count, states, count_out, field_outs = out
        self._count = count
        self._state = dict(zip(names, states))
        count_rows, out_rows = self._canonical_fire_rows(
            count_out, field_outs, names)
        deferred = DeferredEmissions(self._planner, fires, count_rows,
                                     out_rows, phase_counts=pc_total)
        return deferred if defer else deferred.resolve()

    def _canonical_fire_rows(self, count_out, field_outs, names):
        """[n, R, K_local] per-shard fire slabs -> [R, K] canonical key
        order: contiguous ranges concatenate; a routing table additionally
        permutes columns (one deferred device gather — the rows ride the
        same async readback either way)."""
        count_rows = jnp.transpose(count_out, (1, 0, 2)).reshape(
            self.R, self.K)
        out_rows = {
            nm: jnp.transpose(o, (1, 0, 2)).reshape(self.R, self.K)
            for nm, o in zip(names, field_outs)
        }
        if self.routing is not None:
            count_rows = jnp.take(count_rows, self._perm_dev, axis=1)
            out_rows = {nm: jnp.take(o, self._perm_dev, axis=1)
                        for nm, o in out_rows.items()}
        return count_rows, out_rows

    # ------------------------------------------------------------------
    # traced-chain path (whole-graph fusion over the mesh): every shard
    # runs the user's traceable chain + key extraction on ITS slice of the
    # raw source columns, then ONE all-to-all per step routes each record
    # to its key-range owner — the keyBy shuffle as an ICI collective
    # inside the compiled scan, replacing the host dataplane hop
    # ------------------------------------------------------------------
    def _build_raw(self, T: int, B: int):
        phases = self.phase_counters
        combine = self.local_combine
        routed = self.routing is not None
        key = ("raw", T, B, phases, combine,
               None if not routed else self.routing.G, self.donate_carry)
        if key in self._fn_cache:
            return self._fn_cache[key]

        n, Kl, K, S, axis = self.n, self.K_local, self.K, self.S, self.axis
        NSB, R = self.NSB, self.R
        # the per-shard superscan body ingests n*B post-shuffle lanes
        step = self._make_step(n * B, phases)
        nf = len(self._value_fields)
        partials_fn = self._make_partials_fn(B) if combine else None
        scatters = [f.scatter for f in self._value_fields]
        pro = self.prologue
        needs_ts = pro.needs_ts
        transforms = tuple(pro.transforms)
        key_fn, value_fn = pro.key_fn, pro.value_fn

        def per_shard(count, state_t, raw, srel, *rest):
            if routed:
                *rest, g_dst, g_slot = rest
                owner = self._dst_and_local((g_dst, g_slot))
            else:
                owner = self._dst_and_local(None)
            count = count[0]
            raw = raw[0]
            srel = srel[0]
            if needs_ts:
                ts, rest = rest[0][0], rest[1:]
            else:
                ts = None
            smin_pos, fire_pos, fire_valid, fire_row, purge_mask = rest
            state = {
                f.name: state_t[i][0]
                for i, f in enumerate(self._value_fields)
            }
            base = jax.lax.axis_index(axis).astype(jnp.int32) * Kl
            if combine:
                exchange = self._exchange_partials(
                    partials_fn, step, scatters)

            def routed_step(carry, args):
                inner, key_bounds = carry
                if needs_ts:
                    raw_row, srel_row, ts_row = args[0], args[1], args[2]
                    plan_row = args[3:]
                else:
                    raw_row, srel_row = args[0], args[1]
                    ts_row = None
                    plan_row = args[2:]
                # the traced chain runs on THIS shard's raw lanes, before
                # any routing: filter/projection/keying happen where the
                # data landed, only survivors cross the interconnect
                col = raw_row
                mask = srel_row >= 0
                for kind, fn in transforms:
                    if kind == "map":
                        col = fn(col)
                    elif kind == "map_ts":
                        col = fn(col, ts_row)
                    else:  # filter
                        mask = mask & jnp.asarray(fn(col)).astype(bool)
                keys = jnp.asarray(key_fn(col)).astype(jnp.int32)
                live = mask & (keys >= 0) & (keys < K)
                idx = jnp.where(live, keys * NSB + srel_row,
                                jnp.int32(-1)).astype(jnp.int32)
                # key range observed over every SURVIVING record (pre range
                # clamp), exactly like the single-chip chained program: an
                # out-of-range key is a hard error at resolve, never a
                # silent drop or a silent alias of another shard's row
                key_bounds = jnp.stack([
                    jnp.maximum(key_bounds[0],
                                jnp.max(jnp.where(mask, keys, jnp.int32(-1)))),
                    jnp.minimum(key_bounds[1],
                                jnp.min(jnp.where(mask, keys, jnp.int32(0)))),
                ])
                if nf:
                    vcol = value_fn(col) if value_fn is not None else col
                    # dead/pad rows hold uninitialized staging bytes; zero
                    # them BEFORE the shuffle so 0 * NaN can never poison
                    # an owner shard's sums (combine path: a NaN times a
                    # zero one-hot in the partial histogram, same hazard)
                    vals = jnp.where(
                        live, jnp.asarray(vcol).astype(jnp.float32), 0.0)
                else:
                    vals = jnp.zeros((1,), jnp.float32)
                if combine:
                    # the map-side combiner: this shard's survivors
                    # segment-reduce by (owner, key, rel-slice) and ONLY
                    # the dense partials cross the interconnect — a hot
                    # key costs n partials per slice, not its tuple mass
                    dst, lidx = owner(live, keys, srel_row)
                    pidx = jnp.where(live, dst * (Kl * NSB) + lidx, -1)
                    inner, _ = exchange(inner, pidx, vals, plan_row)
                    return (inner, key_bounds), None
                # the keyBy exchange: bin by owning key range, one
                # all-to-all over the mesh interconnect per step
                if routed:
                    # route-raw under a table: sender-side localization
                    dst, send_payload = owner(live, keys, srel_row)
                    localize = lambda r: r                 # noqa: E731
                else:
                    dst = jnp.where(live, keys // Kl, -1)
                    send_payload = idx
                    localize = lambda r: jnp.where(        # noqa: E731
                        r >= 0, r - base * NSB, -1)
                rows = jnp.arange(n, dtype=jnp.int32)[:, None]
                route = rows == dst[None, :]                     # [n, B]
                send_idx = jnp.where(route, send_payload[None, :], -1)
                recv_idx = jax.lax.all_to_all(
                    send_idx, axis, split_axis=0, concat_axis=0, tiled=False
                ).reshape(-1)                                    # [n*B]
                local_idx = localize(recv_idx)
                if nf:
                    send_v = jnp.where(route, vals[None, :], 0.0)
                    recv_v = jax.lax.all_to_all(
                        send_v, axis, split_axis=0, concat_axis=0,
                        tiled=False,
                    ).reshape(-1)
                else:
                    recv_v = jnp.zeros((1,), jnp.float32)
                inner, _ = step(inner, (local_idx, recv_v) + plan_row)
                return (inner, key_bounds), None

            outs0 = {
                f.name: jnp.zeros((R, Kl), jnp.dtype(f.dtype))
                for f in self._value_fields
            }
            count_out0 = jnp.zeros((R, Kl), jnp.int32)
            inner0 = (state, count, outs0, count_out0)
            if phases:
                inner0 = inner0 + (jnp.zeros((3,), jnp.int32),)
            kb0 = jnp.asarray([-1, 0], jnp.int32)
            xs = (raw, srel)
            if needs_ts:
                xs = xs + (ts,)
            xs = xs + (smin_pos, fire_pos, fire_valid, fire_row, purge_mask)
            (inner, key_bounds), _ = jax.lax.scan(
                routed_step, (inner0, kb0), xs)
            if phases:
                state, count, outs, count_out, pc = inner
            else:
                state, count, outs, count_out = inner
            names = [f.name for f in self._value_fields]
            out = (
                count[None], tuple(state[nm][None] for nm in names),
                count_out[None], tuple(outs[nm][None] for nm in names),
                key_bounds[None],                         # [1, 2] per shard
            )
            if phases:
                out = out + (pc[None],)
            return out

        raw_ndim = 3 + len(self._planner._raw_shape or ())
        out_specs = (
            P(axis, None, None),
            (P(axis, None, None),) * nf,
            P(axis, None, None),
            (P(axis, None, None),) * nf,
            P(axis, None),                                # key bounds [n,2]
        )
        if phases:
            out_specs = out_specs + (P(axis, None),)
        in_specs = (
            P(axis, None, None),                          # count [n,Kl,S]
            (P(axis, None, None),) * nf,                  # field states
            P(axis, *([None] * (raw_ndim - 1))),          # raw [n,T,Bs,...]
            P(axis, None, None),                          # srel [n,T,Bs]
        )
        if needs_ts:
            in_specs = in_specs + (P(axis, None, None),)  # ts [n,T,Bs]
        in_specs = in_specs + (
            P(None), P(None, None), P(None, None), P(None, None),
            P(None, None),                                # plan (replicated)
        )
        if routed:
            in_specs = in_specs + (P(None), P(None))      # routing tables
        sharded = shard_map(
            per_shard, mesh=self.mesh,
            in_specs=in_specs, out_specs=out_specs, check_vma=False,
        )

        def run(*args):
            out = sharded(*args)
            if phases:
                count, states, count_out, outs, kb, pc = out
            else:
                count, states, count_out, outs, kb = out
                pc = None
            # global key bounds: worst over shards (each shard saw only
            # its own pre-shuffle lanes)
            kb_g = jnp.stack([kb[:, 0].max(), kb[:, 1].min()])
            if phases:
                return count, states, count_out, outs, kb_g, pc
            return count, states, count_out, outs, kb_g

        fn = (jax.jit(run, donate_argnums=(0, 1)) if self.donate_carry
              else jax.jit(run))
        self._fn_cache[key] = fn
        return fn

    def stage_superbatch_raw(self, steps, watermarks):
        """Host planning + mesh staging for one traced-chain dispatch:
        the planner fills the same flat [T, B] staging buffers the
        single-chip path uses, then lanes are dealt contiguously across
        the n source shards (any split works — the in-scan all-to-all
        re-routes every record to its key owner)."""
        raw_h, srel_h, ts_h, plan_np, fires = self._planner._stage_raw_host(
            steps, watermarks)
        T, B = srel_h.shape
        n = self.n
        Bs = -(-B // n)
        if Bs * n != B:
            pad = Bs * n - B
            srel_h = np.concatenate(
                [srel_h, np.full((T, pad), -1, np.int32)], axis=1)
            raw_h = np.concatenate(
                [raw_h, np.zeros((T, pad) + raw_h.shape[2:], raw_h.dtype)],
                axis=1)
            if ts_h is not None:
                ts_h = np.concatenate(
                    [ts_h, np.zeros((T, pad), ts_h.dtype)], axis=1)
        trail = raw_h.shape[2:]
        raw_d = jax.device_put(
            jnp.asarray(
                raw_h.reshape((T, n, Bs) + trail)
                .transpose((1, 0, 2) + tuple(range(3, 3 + len(trail))))),
            self._shard_spec(*([None] * (2 + len(trail)))))
        srel_d = jax.device_put(
            jnp.asarray(srel_h.reshape(T, n, Bs).transpose(1, 0, 2)),
            self._shard_spec(None, None))
        ts_d = None
        if ts_h is not None:
            ts_d = jax.device_put(
                jnp.asarray(ts_h.reshape(T, n, Bs).transpose(1, 0, 2)),
                self._shard_spec(None, None))
        plan = tuple(jax.device_put(a) for a in plan_np) + (fires,)
        return raw_d, srel_d, ts_d, plan

    def process_superbatch_raw(self, steps, watermarks, *,
                               staged: Optional[tuple] = None,
                               defer: bool = False):
        """Run T traced-chain steps in one sharded dispatch (the
        prologue-bearing sibling of process_superbatch; same defer
        contract as the single-chip pipeline)."""
        from flink_tpu.runtime.fused_window_pipeline import DeferredEmissions

        if staged is None and all(len(step[1]) == 0 for step in steps):
            # watermark-only dispatch: with zero rows the prologue is
            # irrelevant — run the classic fire/purge program over the
            # same sharded state (mirrors the single-chip fallback, and
            # covers restore-then-watermark before geometry is known)
            empty = [(np.empty(0, np.int32), None, np.empty(0, np.int64))
                     for _ in steps]
            return self.process_superbatch(empty, watermarks, defer=defer)
        if staged is None:
            staged = self.stage_superbatch_raw(steps, watermarks)
        raw_d, srel_d, ts_d, plan = staged
        smin_pos, fire_pos, fire_valid, fire_row, purge_mask, fires = plan
        T = int(srel_d.shape[1])
        B = int(srel_d.shape[2])
        run = self._build_raw(T, B)
        names = [f.name for f in self._value_fields]
        args = (self._count, tuple(self._state[nm] for nm in names),
                raw_d, srel_d)
        if ts_d is not None:
            args = args + (ts_d,)
        args = args + (smin_pos, fire_pos, fire_valid, fire_row, purge_mask)
        if self.routing is not None:
            args = args + (self._g_dst, self._g_slot)
        if self.compile_tracker is not None:
            out = self.compile_tracker.call(
                "sharded_chained_superscan", run, args,
                {"T": T, "B": B, "K": self.K, "S": self.S, "n": self.n,
                 "raw_dtype": str(raw_d.dtype),
                 "dtype": "+".join(str(np.dtype(f.dtype))
                                   for f in self._value_fields) or "count"})
        else:
            out = run(*args)
        pc_total = None
        if self.phase_counters:
            count, states, count_out, field_outs, kb, pc = out
            pc_total = pc.sum(axis=0)
        else:
            count, states, count_out, field_outs, kb = out
        self._count = count
        self._state = dict(zip(names, states))
        count_rows, out_rows = self._canonical_fire_rows(
            count_out, field_outs, names)
        deferred = DeferredEmissions(self._planner, fires, count_rows,
                                     out_rows, key_bounds=kb,
                                     key_capacity=self.K,
                                     phase_counts=pc_total)
        return deferred if defer else deferred.resolve()

    # ------------------------------------------------------------------
    # tiered-state row surface (state/tier_manager.py): same contract as
    # the single-chip pipeline's accessors — these MUST shadow the planner
    # delegation (the plan-only planner has no device state). All run off
    # the dispatch hot path (demotion/promotion between superbatches,
    # cell gathers at checkpoint), so the simple canonical round trip —
    # pull [K, S], mutate on host, re-shard — is the whole implementation;
    # note_external_slices needs no shadow (it mutates the planner's host
    # cursors, which ARE the mesh pipeline's canonical cursor state).
    # ------------------------------------------------------------------
    def gather_key_rows(self, kids):
        k = np.asarray(kids, np.int64)
        count, state = self._canonical_arrays()
        return count[k], {name: v[k] for name, v in state.items()}

    def _put_canonical(self, count: np.ndarray,
                       state: "Dict[str, np.ndarray]") -> None:
        n, Kl, S = self.n, self.K_local, self.S
        if self.routing is not None:
            count = self.routing.to_device_layout(np.asarray(count))
            state = {k: self.routing.to_device_layout(np.asarray(v))
                     for k, v in state.items()}
        self._count = jax.device_put(
            jnp.asarray(count.reshape(n, Kl, S)),
            self._shard_spec(None, None))
        self._state = {
            name: jax.device_put(
                jnp.asarray(v.reshape(n, Kl, S)),
                self._shard_spec(None, None))
            for name, v in state.items()
        }

    def clear_key_rows(self, kids) -> None:
        k = np.asarray(kids, np.int64)
        count, state = self._canonical_arrays()
        count = count.copy()
        count[k] = 0
        idents = {f.name: f.identity for f in self._value_fields}
        new_state = {}
        for name, arr in state.items():
            arr = arr.copy()
            arr[k] = idents[name]
            new_state[name] = arr
        self._put_canonical(count, new_state)

    def write_cells(self, kids, spos, counts, fields) -> None:
        k = np.asarray(kids, np.int64)
        s = np.asarray(spos, np.int64)
        count, state = self._canonical_arrays()
        count = count.copy()
        count[k, s] = np.asarray(counts)
        new_state = {}
        for name, arr in state.items():
            arr = arr.copy()
            arr[k, s] = np.asarray(fields[name], arr.dtype)
            new_state[name] = arr
        self._put_canonical(count, new_state)

    def gather_cells(self, kids, spos):
        k = np.asarray(kids, np.int64)
        s = np.asarray(spos, np.int64)
        count, state = self._canonical_arrays()
        return (count[k, s],
                {name: v[k, s] for name, v in state.items()})

    # ------------------------------------------------------------------
    def snapshot(self) -> dict:
        """Canonical [K, S] global arrays — interchangeable with single-chip
        FusedWindowPipeline snapshots (restore re-shards, so n -> m shard
        rescaling is just snapshot + restore). A routing table un-permutes
        before writing, so checkpoints are routing-independent too: any
        mesh size with any table restores the same snapshot."""
        count, state = self._canonical_arrays()
        snap = {
            "state": state,
            "count": count,
            "watermark": self._planner.watermark,
            "fire_cursor": self._planner.fire_cursor,
            "purged_to": self._planner.purged_to,
            "min_used_slice": self._planner.min_used_slice,
            "max_seen_slice": self._planner.max_seen_slice,
            "num_late_dropped": self._planner.num_late_records_dropped,
        }
        # shared-partials planner: per-spec fire cursors are part of the
        # canonical form (SharedWindowPipeline.snapshot writes them too —
        # a mesh checkpoint must restore into a single-chip shared
        # operator and vice versa)
        cursors = getattr(self._planner, "fire_cursors", None)
        if cursors is not None:
            snap["fire_cursors"] = list(cursors)
        return snap

    def restore(self, snap: dict) -> None:
        count = snap["count"]
        state = dict(snap["state"])
        snap_k = int(count.shape[0])
        if snap_k % self.n != 0:
            # a grown snapshot K (classic keyed path: pow2 rounded to the
            # OLD mesh's multiple) need not divide the NEW mesh — e.g. a
            # K=1024 checkpoint rescaled onto 6 devices. Identity-pad up
            # to the next multiple: rows beyond the key dictionary are
            # never addressed (dense ids < len(keydict) <= snap_k), so
            # padding is exact — and failing here instead would wedge the
            # job in a restart loop against the same checkpoint
            pad = -(-snap_k // self.n) * self.n - snap_k
            count = np.concatenate(
                [count, np.zeros((pad, self.S), count.dtype)])
            idents = {f.name: (f.identity, np.dtype(f.dtype))
                      for f in self._value_fields}
            state = {
                k: np.concatenate(
                    [v, np.full((pad, self.S), *idents[k])])
                for k, v in state.items()
            }
            snap_k += pad
        if snap_k != self.K:
            # capacity may have grown pre-snapshot (classic keyed path):
            # adopt the snapshot's K, exactly like the single-chip restore
            self.K = snap_k
            self.K_local = snap_k // self.n
            self._planner.K = snap_k
            if self.routing is not None:
                # table is sized to K: rebuild at identity for the adopted
                # capacity (the snapshot is canonical — any table is a
                # valid placement of it)
                self.routing = KeyGroupRouting(
                    snap_k, self.n, self._num_key_groups,
                    version=self.routing.version + 1)
                self._refresh_route_tables()
            self._fn_cache.clear()
        self._put_canonical(count, state)
        self._planner.watermark = snap["watermark"]
        self._planner.fire_cursor = snap["fire_cursor"]
        self._planner.purged_to = snap["purged_to"]
        self._planner.min_used_slice = snap["min_used_slice"]
        self._planner.max_seen_slice = snap["max_seen_slice"]
        self._planner.num_late_records_dropped = snap["num_late_dropped"]
        if getattr(self._planner, "fire_cursors", None) is not None:
            self._planner.fire_cursors = list(snap["fire_cursors"])
