"""Sharded window operator: key-group data parallelism over a device mesh.

The multi-device form of runtime/tpu_window_operator.py: accumulator columns
get a leading shard axis ([n_shards, K, S], sharded over the mesh's
"shards" axis), records are routed to the shard owning their key group
(KeyGroupRangeAssignment semantics — shard = key_group * n // max_parallelism,
matching computeOperatorIndexForKeyGroup), and every device step runs as a
shard_map program so ingest/fire/purge execute on all shards simultaneously
with zero host round-trips between shards.

Routing happens host-side here (records enter through one host in the local
runtime); the pure-device all-to-all route (ops/exchange.py) is the
multi-host ingest path where each host feeds its local devices and the
shuffle rides ICI.

Snapshot/rescale: state is keyed by (key → key group), not by device, so a
snapshot taken at n shards restores onto m shards by re-routing every key to
its new owner (the reference's key-group re-sharding on restore,
StateAssignmentOperation).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import List

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from flink_tpu.utils.jax_compat import shard_map

from flink_tpu.ops import segment_ops
from flink_tpu.ops.aggregators import DeviceAggregator, ONE
from flink_tpu.parallel.mesh import SHARD_AXIS
from flink_tpu.state.columnar import KeyDictionary, RingFrontiers


@functools.lru_cache(maxsize=None)
def _make_sharded_ingest(agg: DeviceAggregator, mesh: Mesh, axis: str):
    def body(acc, count, kid, spos, vals):
        # per-shard views [1, ...]: strip and restore the leading axis
        acc1 = {k: v[0] for k, v in acc.items()}
        new_acc = {}
        for f in agg.fields:
            src = (
                jnp.ones(vals[0].shape, dtype=f.dtype)
                if f.source == ONE
                else vals[0].astype(f.dtype)
            )
            ref = acc1[f.name].at[kid[0], spos[0]]
            op = {"add": ref.add, "min": ref.min, "max": ref.max}[f.scatter]
            new_acc[f.name] = op(src, mode="drop")[None]
        new_count = count[0].at[kid[0], spos[0]].add(
            jnp.ones(kid[0].shape, dtype=count.dtype), mode="drop"
        )[None]
        touch = (
            jnp.zeros(count[0].shape, dtype=jnp.bool_)
            .at[kid[0], spos[0]]
            .set(True, mode="drop")[None]
        )
        return new_acc, new_count, touch

    s3 = P(axis, None, None)
    s2 = P(axis, None)
    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=({f.name: s3 for f in agg.fields}, s3, s2, s2, s2),
        out_specs=({f.name: s3 for f in agg.fields}, s3, s3),
    )
    return jax.jit(fn, donate_argnums=(0, 1))


@functools.lru_cache(maxsize=None)
def _make_sharded_fire(agg: DeviceAggregator, mesh: Mesh, axis: str, masked: bool):
    def body(acc, count, positions, touch=None):
        combined = {}
        for f in agg.fields:
            cols = jnp.take(acc[f.name][0], positions, axis=1)  # [K, spw]
            red = {"add": cols.sum, "min": cols.min, "max": cols.max}[f.scatter]
            combined[f.name] = red(axis=1)
        cnt = jnp.take(count[0], positions, axis=1).sum(axis=1)
        mask = cnt > 0
        if masked:
            mask = mask & jnp.take(touch[0], positions, axis=1).any(axis=1)
        result = agg.extract(combined).astype(agg.result_dtype)
        return result[None], cnt[None], mask[None]

    s3 = P(axis, None, None)
    s2 = P(axis, None)
    in_specs = ({f.name: s3 for f in agg.fields}, s3, P())
    if masked:
        in_specs = in_specs + (s3,)
    fn = shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=(s2, s2, s2)
    )
    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _make_sharded_purge(agg: DeviceAggregator, mesh: Mesh, axis: str, num_positions: int):
    def body(acc, count, positions):
        K = count.shape[1]
        col_idx = jnp.broadcast_to(positions[None, :], (K, num_positions))
        row_idx = jnp.broadcast_to(
            jnp.arange(K, dtype=jnp.int32)[:, None], (K, num_positions)
        )
        new_acc = {}
        for f in agg.fields:
            ident = jnp.full((K, num_positions), f.identity, dtype=f.dtype)
            new_acc[f.name] = acc[f.name][0].at[row_idx, col_idx].set(ident, mode="drop")[None]
        zeros = jnp.zeros((K, num_positions), dtype=count.dtype)
        new_count = count[0].at[row_idx, col_idx].set(zeros, mode="drop")[None]
        return new_acc, new_count

    s3 = P(axis, None, None)
    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=({f.name: s3 for f in agg.fields}, s3, P()),
        out_specs=({f.name: s3 for f in agg.fields}, s3),
    )
    return jax.jit(fn, donate_argnums=(0, 1))


class ShardedColumnarState:
    """[n_shards, K, S] accumulator columns sharded over the mesh, with one
    host key dictionary per shard (keys are disjoint across shards by
    key-group ownership)."""

    PURGE_CHUNK = 8

    def __init__(
        self,
        agg: DeviceAggregator,
        mesh: Mesh,
        *,
        key_capacity: int = 1 << 12,
        num_slices: int = 64,
        dense_int_keys: bool = False,
        axis: str = SHARD_AXIS,
    ):
        self.agg = agg
        self.mesh = mesh
        self.axis = axis
        self.n = mesh.shape[axis]
        self.K = key_capacity
        self.S = num_slices
        self.keydicts = [KeyDictionary(dense_int_keys) for _ in range(self.n)]
        self.frontiers = RingFrontiers()
        self._sharding3 = NamedSharding(mesh, P(axis, None, None))
        self._sharding2 = NamedSharding(mesh, P(axis, None))
        self._init_arrays()
        self._ingest = _make_sharded_ingest(agg, mesh, axis)
        self._fire = _make_sharded_fire(agg, mesh, axis, False)
        self._fire_masked = _make_sharded_fire(agg, mesh, axis, True)
        self._purge = _make_sharded_purge(agg, mesh, axis, self.PURGE_CHUNK)
        self.last_touch = None

    def _init_arrays(self):
        self.acc = {
            f.name: jax.device_put(
                np.full((self.n, self.K, self.S), f.identity, dtype=f.dtype), self._sharding3
            )
            for f in self.agg.fields
        }
        self.count = jax.device_put(
            np.zeros((self.n, self.K, self.S), dtype=np.int32), self._sharding3
        )

    def ensure_key_capacity(self, required: int) -> None:
        if required <= self.K:
            return
        new_k = self.K
        while new_k < required:
            new_k *= 2
        pad_n = new_k - self.K
        acc_h = {k: np.asarray(v) for k, v in self.acc.items()}
        cnt_h = np.asarray(self.count)
        for f in self.agg.fields:
            filler = np.full((self.n, pad_n, self.S), f.identity, dtype=f.dtype)
            acc_h[f.name] = np.concatenate([acc_h[f.name], filler], axis=1)
        cnt_h = np.concatenate(
            [cnt_h, np.zeros((self.n, pad_n, self.S), dtype=np.int32)], axis=1
        )
        self.acc = {k: jax.device_put(v, self._sharding3) for k, v in acc_h.items()}
        self.count = jax.device_put(cnt_h, self._sharding3)
        self.K = new_k
        self.last_touch = None

    def ingest(self, kid: np.ndarray, slices_abs: np.ndarray, vals: np.ndarray) -> None:
        """kid/slices/vals are [n, B] routed arrays (INVALID-padded)."""
        f = self.frontiers
        valid = kid != segment_ops.INVALID_INDEX
        live = slices_abs[valid]
        if live.size:
            lo, hi = int(live.min()), int(live.max())
            f.min_used = lo if f.min_used is None else min(f.min_used, lo)
            f.max_used = hi if f.max_used is None else max(f.max_used, hi)
        spos = np.where(valid, slices_abs % self.S, segment_ops.INVALID_INDEX).astype(np.int32)
        kid_d = jax.device_put(kid.astype(np.int32), self._sharding2)
        spos_d = jax.device_put(spos, self._sharding2)
        vals_d = jax.device_put(vals, self._sharding2)
        self.acc, self.count, self.last_touch = self._ingest(
            self.acc, self.count, kid_d, spos_d, vals_d
        )

    def fire(self, slice_range: range, *, touch_mask: bool = False):
        positions = np.asarray([s % self.S for s in slice_range], dtype=np.int32)
        if touch_mask:
            if self.last_touch is None:
                return None  # nothing ingested since restore: no refire
            return self._fire_masked(self.acc, self.count, positions, self.last_touch)
        return self._fire(self.acc, self.count, positions)

    def purge_slices(self, slices_abs: List[int]) -> None:
        for i in range(0, len(slices_abs), self.PURGE_CHUNK):
            chunk = slices_abs[i : i + self.PURGE_CHUNK]
            positions = np.full(self.PURGE_CHUNK, segment_ops.INVALID_INDEX, dtype=np.int32)
            positions[: len(chunk)] = [s % self.S for s in chunk]
            self.acc, self.count = self._purge(self.acc, self.count, positions)

    def reset_all(self) -> None:
        self._init_arrays()
        self.last_touch = None

    def snapshot(self) -> dict:
        return {
            "acc": {k: np.asarray(v) for k, v in self.acc.items()},
            "count": np.asarray(self.count),
            "keydicts": [d.snapshot() for d in self.keydicts],
            "frontiers": dataclasses.asdict(self.frontiers),
            "n": self.n,
            "K": self.K,
            "S": self.S,
        }


def __getattr__(name):
    """Back-compat: ShardedTpuWindowOperator subclasses the runtime's
    TpuWindowOperator and therefore moved to
    runtime/sharded_window_operator.py when `parallel` became an ARCH001
    layer (may import core/ops/state/config, never runtime). The lazy
    module attribute keeps the historical import path working without a
    module-level runtime edge."""
    if name == "ShardedTpuWindowOperator":
        from flink_tpu.runtime.sharded_window_operator import (
            ShardedTpuWindowOperator,
        )

        return ShardedTpuWindowOperator
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
