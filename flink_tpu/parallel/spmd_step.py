"""Fully-fused SPMD window step: keyBy all-to-all → scatter ingest → window
fire → psum global merge, as ONE shard_map program.

This is the pure-device hot path for multi-chip deployments: each shard
feeds its locally-ingested lanes, the keyBy shuffle rides ICI inside the
compiled program (no host round-trip between shuffle and state update —
compare the reference's record path §3.3, which crosses the Netty network
boundary between RecordWriter.emit and the downstream WindowOperator), and
the global-window merge (Nexmark Q7-style global max/count) is a `psum`/
`pmax` collective instead of a singleton downstream operator.

Key ids here are *globally dense* (source-assigned), so owner shards index
state rows directly after the exchange; the host-routed operator
(parallel/sharded_window.py) is the general path for arbitrary keys.
"""

from __future__ import annotations

import functools
from typing import Dict

import jax
import jax.numpy as jnp
from flink_tpu.utils.jax_compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from flink_tpu.ops.aggregators import DeviceAggregator, ONE
from flink_tpu.ops.exchange import keyby_exchange_fn
from flink_tpu.ops.segment_ops import INVALID_INDEX


@functools.lru_cache(maxsize=None)
def make_spmd_step(mesh: Mesh, max_parallelism: int, agg: DeviceAggregator,
                   axis: str = "shards"):
    """Build the jitted fused step.

    step(acc {f:[n,K,S]}, count [n,K,S],
         key_groups [n,B] i32, kid [n,B] i32 (global dense), spos [n,B] i32,
         vals [n,B] f32, fire_positions [spw] i32)
      -> (acc', count', result [n,K], mask [n,K], global_count scalar-per-shard [n])
    """
    n = mesh.shape[axis]
    exchange = keyby_exchange_fn(n, max_parallelism, axis)

    def body(acc, count, key_groups, kid, spos, vals, fire_positions):
        acc1 = {k: v[0] for k, v in acc.items()}
        count1 = count[0]

        # 1. keyBy shuffle over ICI
        kg_r, cols = exchange(
            key_groups[0], {"kid": kid[0], "spos": spos[0], "vals": vals[0]}
        )
        kid_r, spos_r, vals_r = cols["kid"], cols["spos"], cols["vals"]

        # 2. scatter-combine ingest into this shard's columns
        new_acc = {}
        for f in agg.fields:
            src = (
                jnp.ones(vals_r.shape, dtype=f.dtype)
                if f.source == ONE
                else vals_r.astype(f.dtype)
            )
            ref = acc1[f.name].at[kid_r, spos_r]
            op = {"add": ref.add, "min": ref.min, "max": ref.max}[f.scatter]
            new_acc[f.name] = op(src, mode="drop")
        new_count = count1.at[kid_r, spos_r].add(
            jnp.ones(kid_r.shape, dtype=count1.dtype), mode="drop"
        )

        # 3. window fire: segment-reduce over the window's slice columns
        combined = {}
        for f in agg.fields:
            cols_f = jnp.take(new_acc[f.name], fire_positions, axis=1)
            red = {"add": cols_f.sum, "min": cols_f.min, "max": cols_f.max}[f.scatter]
            combined[f.name] = red(axis=1)
        cnt = jnp.take(new_count, fire_positions, axis=1).sum(axis=1)
        mask = cnt > 0
        result = agg.extract(combined).astype(agg.result_dtype)

        # 4. global merge across shards (the psum that replaces a singleton
        #    downstream global-window operator)
        global_count = jax.lax.psum(cnt.sum(), axis)

        return (
            {k: v[None] for k, v in new_acc.items()},
            new_count[None],
            result[None],
            mask[None],
            global_count[None],
        )

    s3 = P(axis, None, None)
    s2 = P(axis, None)
    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=({f.name: s3 for f in agg.fields}, s3, s2, s2, s2, s2, P()),
        out_specs=({f.name: s3 for f in agg.fields}, s3, s2, s2, P(axis)),
    )
    return jax.jit(fn, donate_argnums=(0, 1))
