"""SQL planner: table plans → StepGraph, onto the fused device path.

The production front door for "millions of users" is SQL, not hand-built
operator chains. This package translates parsed `Query` objects
(table/sql.py) into logical relational plans (planner/logical.py),
optimizes them (planner/rules.py: predicate pushdown below the window,
projection pruning, window-spec normalization onto the sliceable
assigners, agg-call → DeviceAggregator mapping), and lowers them
(planner/lowering.py) into the same transformation chain the DataStream
API records — so `graph.plan()` + `graph/fusion.py` classify SQL windowed
aggregates as device-fusable and `DeviceChainRunner` (plus the sharded
mesh path and the tiered state plane) run them as one compiled superscan.

Statements outside the fused core fall back to the interpreted
TableEnvironment path with a catalogued reason (`FALLBACK_CATALOG`),
never an error. `TableEnvironment.execute_sql*` routes through here
behind `table.device-fusion` (default on); `explain_sql` returns the
report this module produces.

Layering (ARCH001): may import table/graph/core/config — never runtime,
api, or scheduler; assigner construction is a function-scoped lazy import.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

from flink_tpu.planner.logical import (  # noqa: F401 — public surface
    FALLBACK_CATALOG,
    JoinLogicalPlan,
    LogicalPlan,
    TableInfo,
    Unsupported,
    build_logical_plan,
)
from flink_tpu.planner.lowering import LoweredQuery, lower
from flink_tpu.planner.rules import optimize
from flink_tpu.table.sql import Query


@dataclasses.dataclass
class SqlPlanReport:
    """Per-statement planning outcome: which path was selected and why.

    `path` is 'fused' or 'interpreted'; on fallback, `reason` is a
    FALLBACK_CATALOG code and `detail` the specific trigger. `plan` holds
    the optimized logical tree for fused statements (golden-test /
    EXPLAIN surface); `lowered` the emitted chain when a source
    transformation was provided."""

    path: str
    reason: Optional[str] = None
    detail: Optional[str] = None
    plan: Optional[LogicalPlan] = None
    lowered: Optional[LoweredQuery] = None

    @property
    def fused(self) -> bool:
        return self.path == "fused"

    def describe(self) -> str:
        if self.fused and self.plan is not None:
            return self.plan.describe()
        return f"interpreted[{self.reason}]: {self.detail}"


def plan_query(
    q: Query,
    catalog: Dict[str, TableInfo],
    sources: Optional[Dict[str, object]] = None,
) -> SqlPlanReport:
    """Plan one parsed statement against the catalog.

    `sources` maps table name -> source Transformation; when provided and
    the statement is fused-lowerable, the report carries the emitted
    LoweredQuery ready for execution. Without sources the report is
    plan-only (EXPLAIN / golden tests)."""
    try:
        plan = optimize(build_logical_plan(q, catalog))
    except Unsupported as u:
        return SqlPlanReport(path="interpreted", reason=u.reason,
                             detail=u.detail)
    lowered = None
    if sources is not None:
        if isinstance(plan, JoinLogicalPlan):
            # fused windowed join: the planner validated the shape; the
            # two-input stream construction happens in the table layer
            # (row streams are an api-layer concern), which stamps the
            # window_join transformation sql_origin so the runtime's
            # DeviceJoinRunner counts as the SQL-fused selection. The
            # report stays `lowered=None` by design.
            for name in (q.table, q.join.table2):
                if sources.get(name) is None:
                    return SqlPlanReport(
                        path="interpreted", reason="unknown-table",
                        detail=f"no source for {name!r}")
        else:
            src = sources.get(q.table)
            if src is None:
                return SqlPlanReport(
                    path="interpreted", reason="unknown-table",
                    detail=f"no source for {q.table!r}")
            lowered = lower(plan, src)
    return SqlPlanReport(path="fused", plan=plan, lowered=lowered)
