"""Logical relational plan IR for the SQL front door.

A parsed `Query` (table/sql.py) is first translated into a small
relational tree — Scan → [Filter] → WindowAggregate → Output — before any
physical decision is made. The tree is the planner's working surface: the
rewrite rules (planner/rules.py) annotate it (predicate pushdown below the
window, projection pruning, window-spec normalization, agg-call → device
aggregator field mapping) and the lowering (planner/lowering.py) reads the
annotations to emit transformations for the fused device path.

"On the Semantic Overlap of Operators in Stream Processing Engines"
(PAPERS.md) grounds the move: relational SELECT/WHERE/GROUP BY windows
reduce to the same operator core the DataStream API records, so one
classifier (graph/fusion.py) serves both front doors. Shapes outside that
core raise `Unsupported` with a catalogued reason, and the table layer
keeps them on the interpreted path — a fallback is attributed, never a
failure.

Layering: this package sits beside `graph` — it may import `table` (the
parsed Query shapes), `graph` (Transformation), `core`, and `config`;
never `runtime`, `api`, or `scheduler` (ARCH001). Assigner construction
happens through the sanctioned function-scoped lazy import in lowering.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Optional, Tuple

from flink_tpu.table.sql import BoolExpr, Operand, Query, SelectItem

#: fallback catalog: reason code -> what keeps the statement on the
#: interpreted path (docs/sql.md renders this table; the gateway reports
#: the code per statement)
FALLBACK_CATALOG: Dict[str, str] = {
    "disabled": "table.device-fusion is off; every statement interprets",
    "unknown-table": "the statement references an unregistered table",
    "join": "join shapes outside the fused core (aggregates or GROUP BY "
            "over a join) stay on the host join translation",
    "join-unwindowed": "regular (non-windowed) joins keep unbounded "
                       "two-sided state with retraction output; the device "
                       "join ring is windowed, so they run on the host "
                       "StreamingJoinRunner",
    "join-outer-windowed": "windowed LEFT/RIGHT OUTER joins need "
                           "end-of-window padding the device emission does "
                           "not produce; only windowed INNER joins fuse",
    "join-full-outer": "FULL OUTER JOIN is not supported on any path: "
                       "neither the host join operators nor the device "
                       "join ring implements two-sided padding retraction",
    "join-session-window": "SESSION windows are not sliceable, so a "
                           "session-windowed join has no bucket-ring form "
                           "(and the host windowed join refuses it too)",
    "union": "UNION ALL branches plan independently on the host",
    "no-window": "continuous (non-windowed) aggregates emit a retract "
                 "changelog; the device path is append-only windows",
    "no-aggregate": "pure projection / ML_PREDICT statements have no "
                    "windowed aggregate to fuse",
    "no-group-by": "a windowed aggregate without GROUP BY columns has no "
                   "key column for dense device keying",
    "composite-group-key": "multi-column GROUP BY keys need host tuple "
                           "keying; dense device keys are single ints",
    "multi-aggregate": "more than one aggregate call per SELECT keeps the "
                       "host composite accumulator",
    "session-window": "SESSION windows are not sliceable; the fused "
                      "superscan requires a sliceable assigner",
    "bad-window-geometry": "window size/slide must be positive; the "
                           "interpreted path raises the assigner's own "
                           "error for the statement",
    "window-not-on-rowtime": "the window's time column must be the "
                             "table's declared rowtime (the batch "
                             "timestamp column)",
    "untyped-schema": "row-mode tables without declared field_types "
                      "cannot prove numeric columns at plan time",
    "non-integer-group-key": "the GROUP BY column must be a declared "
                             "int field (dense device keys)",
    "non-numeric-field": "an aggregate or predicate references a "
                         "non-numeric field",
    "non-traceable-predicate": "the WHERE predicate compares against a "
                               "string literal or otherwise has no "
                               "columnar device form",
    "unknown-column": "the statement references a column the table's "
                      "schema does not declare; the interpreted path "
                      "raises its own error for the statement",
    "rowtime-in-expression": "the rowtime column rides the batch "
                             "timestamps; predicates/aggregates over it "
                             "have no value-column device form",
}


class Unsupported(Exception):
    """A statement shape outside the fused front door. Carries the
    catalogued reason code; the table layer turns this into an attributed
    interpreted-path fallback, never an error."""

    def __init__(self, reason: str, detail: str = ""):
        assert reason in FALLBACK_CATALOG, f"uncatalogued reason {reason!r}"
        self.reason = reason
        self.detail = detail or FALLBACK_CATALOG[reason]
        super().__init__(f"{reason}: {self.detail}")


@dataclasses.dataclass(frozen=True)
class TableInfo:
    """Catalog entry the planner sees per registered table."""

    name: str
    fields: Tuple[str, ...]
    rowtime: Optional[str] = None
    field_types: Optional[Tuple[str, ...]] = None   # 'int'|'float'|'str'
    columnar: bool = False

    def type_of(self, field: str) -> Optional[str]:
        """Declared type; columnar tables default to 'float' (their batch
        columns are numeric by construction), row tables to None."""
        if self.field_types is not None:
            try:
                return self.field_types[self.fields.index(field)]
            except ValueError:
                return None
        return "float" if self.columnar else None

    def is_numeric(self, field: str) -> bool:
        return self.type_of(field) in ("int", "float")


@dataclasses.dataclass
class AggCall:
    """One aggregate select item, mapped by rules.map_aggregates onto the
    builtin DeviceAggregator the runtime resolves by name."""

    func: str                     # COUNT/SUM/MIN/MAX/AVG
    arg: Optional[str]            # None for COUNT(*)
    output: str
    device_agg: Optional[str] = None   # 'count'/'sum'/'min'/'max'/'mean'

    def describe(self) -> str:
        call = f"{self.func.lower()}({self.arg or '*'})"
        dev = f" -> {self.device_agg}" if self.device_agg else ""
        return f"{call} AS {self.output}{dev}"


@dataclasses.dataclass
class NormalizedWindow:
    """A TUMBLE/HOP spec normalized onto the sliceable assigner form the
    device operators consume (rules.normalize_window fills slice_ms)."""

    kind: str                     # 'tumble' | 'hop'
    time_col: str
    size_ms: int
    slide_ms: int                 # == size_ms for tumble
    slice_ms: Optional[int] = None

    def describe(self) -> str:
        parts = [f"size={self.size_ms}ms"]
        if self.kind == "hop":
            parts.append(f"slide={self.slide_ms}ms")
        if self.slice_ms is not None:
            parts.append(f"slice={self.slice_ms}ms")
        return f"{self.kind}({' '.join(parts)})"


@dataclasses.dataclass
class Scan:
    table: TableInfo
    required: Optional[List[str]] = None   # rules.prune_projection fills

    def describe(self) -> str:
        read = (",".join(self.required)
                if self.required is not None else "*")
        return (f"Scan[{self.table.name}, "
                f"fields={','.join(self.table.fields)}, read={read}]")


@dataclasses.dataclass
class Filter:
    pred: Any                     # Comparison | BoolExpr
    text: str
    below_window: bool = False    # rules.push_predicate_below_window

    def describe(self) -> str:
        note = ", device-pushdown" if self.below_window else ""
        return f"Filter[{render_predicate(self.pred)}{note}]"


@dataclasses.dataclass
class WindowAggregate:
    group_col: str
    window: NormalizedWindow
    agg: AggCall

    def describe(self) -> str:
        return (f"WindowAggregate[key={self.group_col}, "
                f"{self.window.describe()}, {self.agg.describe()}]")


@dataclasses.dataclass
class Output:
    """The host-side output stage: row assembly + HAVING + per-window
    top-N. Downstream of the fused program, shared verbatim with the
    interpreted path (table_env's windowed output stage)."""

    columns: List[str]
    having_text: Optional[str] = None
    order_by: List[Tuple[str, bool]] = dataclasses.field(default_factory=list)
    limit: Optional[int] = None

    def describe(self) -> str:
        extra = []
        if self.having_text:
            extra.append(f"having={self.having_text}")
        if self.order_by:
            ob = ",".join(f"{c}{' DESC' if d else ''}"
                          for c, d in self.order_by)
            extra.append(f"order_by={ob}")
        if self.limit is not None:
            extra.append(f"limit={self.limit}")
        tail = f", {' '.join(extra)}" if extra else ""
        return f"Output[{','.join(self.columns)}{tail}]"


@dataclasses.dataclass
class LogicalPlan:
    scan: Scan
    filter: Optional[Filter]
    window_agg: WindowAggregate
    output: Output
    query: Query

    def describe(self) -> str:
        """Top-down indented tree — the golden-test surface."""
        nodes = [self.output.describe(), self.window_agg.describe()]
        if self.filter is not None:
            nodes.append(self.filter.describe())
        nodes.append(self.scan.describe())
        return "\n".join("  " * i + n for i, n in enumerate(nodes))


@dataclasses.dataclass
class JoinScan:
    """One input side of a fused windowed join."""

    table: TableInfo
    alias: str
    key_col: str                  # unqualified column on this side

    def describe(self) -> str:
        return (f"Scan[{self.table.name} AS {self.alias}, "
                f"key={self.key_col}]")


@dataclasses.dataclass
class JoinLogicalPlan:
    """A fused windowed equi-join: two scans under one shared window,
    matched on the device join ring (runtime's DeviceJoinRunner). The
    WHERE/projection stages run on the host DOWNSTREAM of the fused
    emission — the join itself (both sides' buffering and the per-window
    cross-match) is the device part."""

    left: JoinScan
    right: JoinScan
    window: NormalizedWindow
    output: Output
    query: Query
    filter_text: Optional[str] = None

    def describe(self) -> str:
        q = self.query
        j = q.join
        flt = f", where={self.filter_text}" if self.filter_text else ""
        nodes = [
            self.output.describe(),
            (f"WindowJoin[{j.left_col} = {j.right_col}, "
             f"{self.window.describe()}, device=join-ring{flt}]"),
        ]
        lines = ["  " * i + n for i, n in enumerate(nodes)]
        indent = "  " * len(nodes)
        lines.append(indent + self.left.describe())
        lines.append(indent + self.right.describe())
        return "\n".join(lines)


def render_predicate(node) -> str:
    """Stable text form of a predicate AST (parenthesized OR under AND)."""
    if isinstance(node, BoolExpr):
        left, right = render_predicate(node.left), render_predicate(node.right)
        if node.op == "and":
            if isinstance(node.left, BoolExpr) and node.left.op == "or":
                left = f"({left})"
            if isinstance(node.right, BoolExpr) and node.right.op == "or":
                right = f"({right})"
        return f"{left} {node.op.upper()} {right}"
    return (f"{_render_operand(node.left)} {node.op} "
            f"{_render_operand(node.right)}")


def _render_operand(op: Operand) -> str:
    if op.kind == "string":
        return f"'{op.value}'"
    return str(op.value)


def build_logical_plan(
    q: Query, catalog: Dict[str, TableInfo],
) -> "LogicalPlan | JoinLogicalPlan":
    """Translate a parsed Query into the relational tree, rejecting (with
    catalogued reasons) every shape outside the fused front door. The
    rewrite rules then annotate the tree; see planner/rules.py."""
    if q.union_all is not None:
        raise Unsupported("union")
    if q.join is not None:
        return _build_join_plan(q, catalog)
    table = catalog.get(q.table)
    if table is None:
        raise Unsupported("unknown-table", f"table {q.table!r}")

    aggs = [i for i in q.select if i.kind == "agg"]
    if any(i.kind == "ml_predict" for i in q.select):
        raise Unsupported("no-aggregate", "ML_PREDICT projection")
    if not aggs:
        raise Unsupported("no-aggregate")
    if q.window is None:
        raise Unsupported("no-window")
    if q.window.kind == "session":
        raise Unsupported("session-window")
    if not q.group_by:
        raise Unsupported("no-group-by")
    if len(q.group_by) > 1:
        raise Unsupported("composite-group-key",
                          f"GROUP BY {', '.join(q.group_by)}")
    if len(aggs) > 1:
        raise Unsupported("multi-aggregate",
                          f"{len(aggs)} aggregate calls")
    for item in q.select:
        if item.kind == "column" and item.name not in q.group_by:
            # invalid SQL, not a fallback shape: both paths refuse it with
            # the same error (the shared output stage raises identically),
            # so the planner must not classify it as fused either
            raise ValueError(
                f"SELECT column {item.name!r} must appear in GROUP BY "
                "(non-grouped columns are not defined for aggregates)")

    window = NormalizedWindow(
        kind=q.window.kind,
        time_col=q.window.time_col,
        size_ms=q.window.size_ms,
        slide_ms=(q.window.slide_ms if q.window.kind == "hop"
                  else q.window.size_ms),
    )
    agg_item: SelectItem = aggs[0]
    agg = AggCall(
        func=agg_item.func,
        arg=None if agg_item.name == "*" else agg_item.name,
        output=agg_item.output_name,
    )
    flt = (Filter(q.where_ast, q.where_text or "")
           if q.where_ast is not None else None)
    out = Output(
        columns=[i.output_name for i in q.select],
        having_text=q.having_text,
        order_by=list(q.order_by),
        limit=q.limit,
    )
    return LogicalPlan(
        scan=Scan(table=table),
        filter=flt,
        window_agg=WindowAggregate(
            group_col=q.group_by[0], window=window, agg=agg),
        output=out,
        query=q,
    )


def _build_join_plan(q: Query, catalog: Dict[str, TableInfo]
                     ) -> JoinLogicalPlan:
    """The join front door: windowed INNER equi-joins plan fused (the
    device join ring); every other join shape falls back with its OWN
    catalogued reason — single-sourced with the runtime's join fallback
    catalog (flink_tpu/joins/spec.py), so the SQL explain and the runner's
    joinFallbackReason gauge attribute the same way."""
    j = q.join
    if j.join_type == "full":
        raise Unsupported("join-full-outer",
                          f"{q.table} FULL OUTER JOIN {j.table2}")
    if j.window is None:
        raise Unsupported("join-unwindowed",
                          f"regular join on {j.left_col} = {j.right_col}")
    if j.join_type != "inner":
        raise Unsupported("join-outer-windowed",
                          f"windowed {j.join_type.upper()} OUTER join")
    if j.window.kind == "session":
        raise Unsupported("join-session-window",
                          f"session window on {j.left_col} = {j.right_col}")
    left = catalog.get(q.table)
    if left is None:
        raise Unsupported("unknown-table", f"table {q.table!r}")
    right = catalog.get(j.table2)
    if right is None:
        raise Unsupported("unknown-table", f"table {j.table2!r}")
    if q.group_by or any(i.kind in ("agg", "ml_predict") for i in q.select):
        raise Unsupported("join", "aggregate/GROUP BY over a join")
    window = NormalizedWindow(
        kind=j.window.kind,
        time_col=j.window.time_col or "<batch timestamps>",
        size_ms=j.window.size_ms,
        slide_ms=(j.window.slide_ms if j.window.kind == "hop"
                  else j.window.size_ms),
    )
    out = Output(columns=[i.output_name for i in q.select])
    return JoinLogicalPlan(
        left=JoinScan(table=left, alias=j.alias1,
                      key_col=j.left_col.split(".", 1)[1]),
        right=JoinScan(table=right, alias=j.alias2,
                       key_col=j.right_col.split(".", 1)[1]),
        window=window,
        output=out,
        query=q,
        filter_text=q.where_text,
    )


def predicate_is_columnar(
    node, table: TableInfo,
) -> Tuple[Optional[str], str]:
    """Can this predicate run as a traceable column mask? Returns
    (fallback_reason_code or None, detail) — a STRUCTURED code, never
    prose the caller has to grep. Requires every operand to be a numeric
    field of `table` or a numeric literal; string literals and rowtime
    references have no value-column form."""
    if isinstance(node, BoolExpr):
        for side in (node.left, node.right):
            code, why = predicate_is_columnar(side, table)
            if code is not None:
                return code, why
        return None, ""
    for side in (node.left, node.right):
        if side.kind == "string":
            return "non-traceable-predicate", f"string literal '{side.value}'"
        if side.kind == "column":
            name = side.value
            if name == table.rowtime:
                return "rowtime-in-expression", f"rowtime column {name!r}"
            if name not in table.fields:
                return "unknown-column", f"unknown column {name!r}"
            if not table.is_numeric(name):
                return ("non-traceable-predicate",
                        f"non-numeric column {name!r}")
    return None, ""


def window_slice_ms(size_ms: int, slide_ms: int) -> int:
    """Slice granule of a sliceable window: gcd(size, slide) — the same
    decomposition SlidingEventTimeWindows declares (tumbling is the
    slide == size special case)."""
    return math.gcd(int(size_ms), int(slide_ms))
