"""Lower an optimized logical plan onto the fused StepGraph path.

The output is the SAME transformation chain a hand-fused DataStream
program records —

    source -> [columnarize] -> filter(traceable) -> key_by(traceable)
           -> window_aggregate(builtin device agg, traceable value_fn)

— so `graph.plan()` + `graph.fusion.plan_device_chains()` classify the SQL
windowed aggregate exactly like a DataStream one, and the executor's
translation picks `DeviceChainRunner` (and the sharded mesh path, and the
tiered state plane) with no SQL-specific runtime code at all.

Two source shapes:

- **columnar tables** (numeric Batch columns; field i of the non-rowtime
  schema order = column i, rowtime rides the batch timestamps): the WHERE
  mask, key extraction, and value extraction are all emitted as traceable
  column functions — the whole prologue compiles INTO the superscan
  (full fusion; the filter chain step is absorbed).
- **typed row-mode tables** (dict rows with declared numeric
  field_types): the planner emits a host vectorized columnarizer over
  exactly the pruned field set (physical projection pushdown), and the
  window still fuses with traced key/value extraction over the pruned
  layout — device window, host prologue.

The generated callables use only array operators (comparisons, `&`/`|`,
indexing, `.astype`) so they trace under jax and run identically on numpy
for the fusion-off fallback — the planner itself never imports jax.

The window terminal carries `sql_origin: True`; the runtime registers the
`job.sqlFusedSelected` gauge off that marker (1 when every SQL window
step selected the fused runner) and /jobs/:id surfaces it.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.graph.transformation import Transformation
from flink_tpu.planner.logical import LogicalPlan, render_predicate
from flink_tpu.table.sql import CMP_OPS, BoolExpr, Operand


@dataclasses.dataclass
class LoweredQuery:
    """What the table layer wires up: the window terminal transformation
    (ready for DataStream wrapping + the shared windowed output stage)
    plus the plan facts the output stage needs."""

    terminal: Transformation
    group_col: str
    size_ms: int
    host_prologue: bool           # row-mode columnarizer in front
    device_agg: str

    @property
    def name(self) -> str:
        return self.terminal.name


def _column_layout(plan: LogicalPlan) -> List[str]:
    """Field -> column-index layout the traced extractors index into.

    Columnar tables keep their registration layout (every non-rowtime
    schema field, in order — the source's physical columns). Row-mode
    tables get the PRUNED layout: the columnarizer materializes only the
    fields the query reads (projection pushdown made physical)."""
    table = plan.scan.table
    if table.columnar:
        return [f for f in table.fields if f != table.rowtime]
    return [f for f in (plan.scan.required or [])
            if f != table.rowtime]


# Generated callables are memoized on their STRUCTURE (column index,
# predicate AST — frozen dataclasses, hashable): two plans of the same
# statement get the IDENTICAL function objects. That identity is what the
# compiled-superscan executable caches key on, so re-planning a statement
# (every job build, every bench sweep) reuses the compiled device program
# instead of tracing + compiling a fresh one per plan.

@functools.lru_cache(maxsize=None)
def _key_extractor(i: int) -> Callable:
    return lambda col, _i=i: col[:, _i].astype("int32")


@functools.lru_cache(maxsize=None)
def _value_extractor(i: int) -> Callable:
    return lambda col, _i=i: col[:, _i]


def _operand_fn(op: Operand, index: Dict[str, int]) -> Callable:
    if op.kind == "column":
        i = index[op.value]
        return lambda col, _i=i: col[:, _i]
    v = op.value
    return lambda col, _v=v: _v


@functools.lru_cache(maxsize=256)
def _mask_fn_for(node, layout: Tuple[str, ...],
                 null_aware: bool) -> Callable:
    index = {f: i for i, f in enumerate(layout)}
    return _mask_fn(node, index, null_aware)


def _mask_fn(node, index: Dict[str, int], null_aware: bool) -> Callable:
    """Predicate AST -> columnar mask function ([n, F] -> bool[n]).
    Elementwise `&`/`|` replace the row closure's and/or — identical
    semantics for pure comparisons over numeric columns.

    `null_aware` (row-mode tables, where the columnarizer encodes SQL
    NULL as NaN): every comparison is additionally masked by operand
    validity (`x == x` is False iff NaN), giving the interpreted path's
    three-valued semantics — NULL cmp anything is not-TRUE, including
    `!=`. Columnar sources have no NULL representation, so their masks
    stay plain (a genuine NaN float then compares exactly like the
    interpreted row view's NaN)."""
    if isinstance(node, BoolExpr):
        l = _mask_fn(node.left, index, null_aware)
        r = _mask_fn(node.right, index, null_aware)
        if node.op == "and":
            return lambda col, _l=l, _r=r: _l(col) & _r(col)
        return lambda col, _l=l, _r=r: _l(col) | _r(col)
    lhs = _operand_fn(node.left, index)
    rhs = _operand_fn(node.right, index)
    cmp = CMP_OPS[node.op]   # the dialect's one operator table, shared
    if not null_aware:
        return lambda col, _l=lhs, _r=rhs, _c=cmp: _c(_l(col), _r(col))

    def null_aware_cmp(col, _l=lhs, _r=rhs, _c=cmp):
        a, b = _l(col), _r(col)
        return _c(a, b) & (a == a) & (b == b)

    return null_aware_cmp


@functools.lru_cache(maxsize=256)
def _columnarizer(fields: Tuple[str, ...],
                  int_cols: Tuple[int, ...],
                  strict_cols: Tuple[int, ...]) -> Callable:
    """Dict rows -> [n, len(fields)] float32 (the record-mode bridge onto
    the device path; a loud KeyError/ValueError for malformed rows).

    NULL handling: predicate-only columns encode SQL NULL (None) as NaN
    — the null-aware masks then drop such rows exactly like the
    interpreted closures. `strict_cols` (the group key and the aggregate
    argument) REFUSE None loudly: a NULL group key has no dense device
    representation, and a NULL aggregate input is refused by the
    interpreted extraction too.

    Declared-int columns (`int_cols`) are round-trip checked: a value the
    float32 column cannot represent exactly (|v| >= 2**24) would silently
    alias another key/value on the device — the same never-silently-alias
    contract the traced key range check enforces, so it raises instead."""

    def columnarize(rows, _cols=fields, _ints=int_cols,
                    _strict=strict_cols):
        for i in _strict:
            f = _cols[i]
            if any(r[f] is None for r in rows):
                raise TypeError(
                    f"NULL in column {f!r}: the fused path's dense device "
                    "keying/aggregation has no NULL representation for "
                    "GROUP BY keys or aggregate arguments — clean the "
                    "column or set table.device-fusion false")
        arr = np.asarray(
            [[(np.nan if r[f] is None else float(r[f])) for f in _cols]
             for r in rows],
            dtype=np.float64,
        )
        out = arr.astype(np.float32)
        if _ints and len(out) and not np.array_equal(
                np.nan_to_num(out[:, _ints]).astype(np.int64),
                np.nan_to_num(arr[:, _ints]).astype(np.int64)):
            bad = [_cols[i] for i in _ints
                   if len(out) and not np.array_equal(
                       np.nan_to_num(out[:, i]).astype(np.int64),
                       np.nan_to_num(arr[:, i]).astype(np.int64))]
            raise TypeError(
                f"int column(s) {bad} hold values float32 cannot represent "
                "exactly (|v| >= 2**24): columnarizing would silently alias "
                "distinct keys/values on the device path — keep such "
                "columns out of fused statements or set "
                "table.device-fusion false")
        return out

    return columnarize


def lower(plan: LogicalPlan, source: Transformation) -> LoweredQuery:
    """Emit the fused-path transformation chain for an OPTIMIZED plan on
    top of the table's source transformation. Requires rules.optimize to
    have run (slice/aggregate/pushdown annotations present)."""
    table = plan.scan.table
    wa = plan.window_agg
    assert wa.agg.device_agg is not None and wa.window.slice_ms is not None, \
        "lower() needs an optimized plan (run planner.rules.optimize first)"

    layout = tuple(_column_layout(plan))
    index = {f: i for i, f in enumerate(layout)}
    prev = source
    host_prologue = not table.columnar
    if host_prologue:
        int_cols = tuple(i for i, f in enumerate(layout)
                         if table.type_of(f) == "int")
        strict = {wa.group_col}
        if wa.agg.arg is not None:
            strict.add(wa.agg.arg)
        strict_cols = tuple(i for i, f in enumerate(layout) if f in strict)
        prev = Transformation(
            "map", f"sql_columnarize[{','.join(layout)}]", [prev],
            {"fn": _columnarizer(layout, int_cols, strict_cols),
             "vectorized": True, "traceable": False, "sql_origin": True},
        )
    if plan.filter is not None:
        mask = _mask_fn_for(plan.filter.pred, layout,
                            null_aware=host_prologue)
        prev = Transformation(
            "filter", f"sql_where[{render_predicate(plan.filter.pred)}]",
            [prev],
            {"fn": mask, "vectorized": True, "traceable": True,
             "sql_origin": True},
        )

    key_fn = _key_extractor(index[wa.group_col])
    keyed = Transformation(
        "key_by", f"sql_key[{wa.group_col}]", [prev],
        {"key_selector": key_fn, "vectorized": True, "traceable": True,
         "sql_origin": True},
    )

    value_fn: Optional[Callable] = None
    if wa.agg.arg is not None:
        value_fn = _value_extractor(index[wa.agg.arg])

    terminal = Transformation(
        "window_aggregate", f"sql_{wa.agg.func.lower()}", [keyed],
        {
            "assigner": _assigner(wa.window),
            "aggregate": wa.agg.device_agg,
            "value_fn": value_fn,
            "value_vectorized": value_fn is not None,
            "value_traceable": value_fn is not None,
            "window_fn": None,
            "trigger": None,
            "evictor": None,
            "allowed_lateness": 0,
            "side_output_late": False,
            "key_selector": key_fn,
            "key_vectorized": True,
            "key_traceable": True,
            "sql_origin": True,
        },
    )
    return LoweredQuery(
        terminal=terminal,
        group_col=wa.group_col,
        size_ms=wa.window.size_ms,
        host_prologue=host_prologue,
        device_agg=wa.agg.device_agg,
    )


def _assigner(window) -> Any:
    """Normalized window -> the existing sliceable assigner. The api
    import is function-scoped — the sanctioned ARCH001 escape hatch, so
    importing the planner never drags the api/runtime stack in."""
    from flink_tpu.api.windowing.assigners import (
        SlidingEventTimeWindows,
        TumblingEventTimeWindows,
    )

    if window.kind == "tumble":
        return TumblingEventTimeWindows.of(window.size_ms)
    return SlidingEventTimeWindows.of(window.size_ms, window.slide_ms)
