"""Rewrite rules over the logical plan.

Each rule either annotates the tree (the lowering reads the annotations)
or raises `Unsupported` with a catalogued reason — the table layer keeps
such statements on the interpreted path with the reason attributed.

The sequence mirrors the reference planner's group-window rewrite set at
the scale this dialect needs:

  normalize_window          TUMBLE/HOP -> the sliceable assigner form
                            (slice granule = gcd(size, slide); session is
                            not sliceable and was rejected at build)
  map_aggregates            agg call -> builtin DeviceAggregator name
                            (COUNT->count, SUM->sum, MIN->min, MAX->max,
                            AVG->mean — mean's two add-scatter fields pass
                            the fused classifier's add/min/max bar)
  push_predicate_below_window
                            WHERE mask proven columnar-traceable over the
                            scanned numeric fields -> marked for the
                            traced device prologue (below the window
                            ingest, above nothing: the filter IS part of
                            the compiled superscan)
  prune_projection          the scan's required field set = group col +
                            agg arg + predicate columns; row-mode tables
                            columnarize exactly these (physical pruning),
                            columnar sources keep their layout and the
                            traced extractors simply never touch pruned
                            columns

Join plans (JoinLogicalPlan — windowed INNER equi-joins) take their own
single rewrite, `rewrite_join_window`: the shared window normalizes onto
the sliceable form whose gcd granule seeds the device join ring's bucket
geometry, which is what turns `SELECT ... FROM a JOIN b ... WINDOW ...`
into a fused-runner selection instead of the old blanket 'join' fallback.
"""

from __future__ import annotations

from flink_tpu.planner.logical import (
    JoinLogicalPlan,
    LogicalPlan,
    Unsupported,
    predicate_is_columnar,
    window_slice_ms,
)
#: single-sourced with the interpreted translation (table_env) — the two
#: front doors must never disagree about which aggregates have a device
#: form; the runtime and the fusion classifier resolve these strings via
#: ops.aggregators.resolve
from flink_tpu.table.sql import DEVICE_AGG_OF, predicate_columns


def optimize(plan):
    """Run the full rule sequence in order; mutates and returns `plan`."""
    if isinstance(plan, JoinLogicalPlan):
        rewrite_join_window(plan)
        return plan
    normalize_window(plan)
    map_aggregates(plan)
    push_predicate_below_window(plan)
    prune_projection(plan)
    return plan


def rewrite_join_window(plan: JoinLogicalPlan) -> None:
    """The join lowering rewrite: normalize the shared window onto the
    sliceable form the device join ring consumes. The ring's bucket
    granule is gcd(size, slide) — the same slice decomposition the
    windowed-aggregate path uses — so a SQL TUMBLE/HOP join lands on the
    fused `DeviceJoinRunner` with NO host re-bucketing: the logical
    window spec IS the ring geometry's seed (joins/spec.py
    plan_join_geometry starts from exactly these numbers)."""
    w = plan.window
    if w.size_ms <= 0 or w.slide_ms <= 0:
        raise Unsupported("bad-window-geometry",
                          f"size={w.size_ms} slide={w.slide_ms}")
    w.slice_ms = window_slice_ms(w.size_ms, w.slide_ms)


def normalize_window(plan: LogicalPlan) -> None:
    w = plan.window_agg.window
    table = plan.scan.table
    if w.size_ms <= 0 or w.slide_ms <= 0:
        raise Unsupported(
            "bad-window-geometry",
            f"size={w.size_ms} slide={w.slide_ms}")
    if table.rowtime is None or w.time_col != table.rowtime:
        raise Unsupported(
            "window-not-on-rowtime",
            f"window over {w.time_col!r}, table rowtime is "
            f"{table.rowtime!r}")
    w.slice_ms = window_slice_ms(w.size_ms, w.slide_ms)


def map_aggregates(plan: LogicalPlan) -> None:
    agg = plan.window_agg.agg
    table = plan.scan.table
    agg.device_agg = DEVICE_AGG_OF.get(agg.func)
    if agg.device_agg is None:   # parser only emits the five; belt+braces
        raise Unsupported("multi-aggregate",
                          f"unmapped aggregate {agg.func}")
    if agg.arg is not None:
        if agg.arg == table.rowtime:
            raise Unsupported("rowtime-in-expression",
                              f"{agg.func}({agg.arg})")
        if agg.arg not in table.fields:
            raise Unsupported("unknown-column",
                              f"{agg.func} over unknown column "
                              f"{agg.arg!r}")
        if table.field_types is None and not table.columnar:
            raise Unsupported("untyped-schema",
                              f"{agg.func}({agg.arg}) over an untyped "
                              f"row-mode table")
        if not table.is_numeric(agg.arg):
            raise Unsupported("non-numeric-field",
                              f"{agg.func}({agg.arg})")


def push_predicate_below_window(plan: LogicalPlan) -> None:
    if plan.filter is None:
        return
    table = plan.scan.table
    if table.field_types is None and not table.columnar:
        raise Unsupported("untyped-schema",
                          "WHERE over an untyped row-mode table")
    code, why = predicate_is_columnar(plan.filter.pred, table)
    if code is not None:
        raise Unsupported(code, why)
    plan.filter.below_window = True


def prune_projection(plan: LogicalPlan) -> None:
    table = plan.scan.table
    wa = plan.window_agg
    key = wa.group_col
    if key == table.rowtime:
        raise Unsupported("rowtime-in-expression", f"GROUP BY {key}")
    if key not in table.fields:
        raise Unsupported("unknown-column",
                          f"unknown GROUP BY column {key!r}")
    if table.field_types is None and not table.columnar:
        raise Unsupported("untyped-schema", f"GROUP BY {key} over an "
                                            "untyped row-mode table")
    if table.type_of(key) != "int":
        if table.field_types is None:
            # columnar registration without declared types: nothing was
            # "declared 'float'" — the user just needs to declare the key
            raise Unsupported(
                "untyped-schema",
                f"GROUP BY {key!r} on a columnar table without "
                "field_types (the group key must be a declared int — "
                "dense device keys, and the row view must emit the same "
                "Python ints the fused path does)")
        raise Unsupported(
            "non-integer-group-key",
            f"GROUP BY {key!r} is declared {table.type_of(key)!r}")
    required = [key]
    if wa.agg.arg is not None and wa.agg.arg not in required:
        required.append(wa.agg.arg)
    if plan.filter is not None:
        for c in predicate_columns(plan.filter.pred):
            if c not in required:
                required.append(c)
    plan.scan.required = required
