"""Runtime: operators, timers, tasks, executors."""
