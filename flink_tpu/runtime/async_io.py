"""Async I/O operator: concurrent external lookups with ordered/unordered
result emission, timeouts, and retry strategies.

Capability parity with AsyncWaitOperator
(flink-streaming-java .../api/operators/async/AsyncWaitOperator.java) and
AsyncDataStream.ordered/unorderedWait: user async functions run with bounded
concurrency (`capacity` — the operator's in-flight buffer), results re-enter
the stream either in input order (ordered) or completion order (unordered);
per-element timeout and fixed-delay/exponential retries.

Here the "async" substrate is a thread pool (the stepped runtime is
synchronous between device steps): a batch fans out to the pool, and the
step completes when the batch's futures resolve — the same batch-level
amortization the AsyncExecutionController applies to state requests (D12).
"""

from __future__ import annotations

import dataclasses
import time
from concurrent.futures import FIRST_COMPLETED, Future, ThreadPoolExecutor, wait
from typing import Any, Callable, Iterable, List, Optional, Tuple

import numpy as np

from flink_tpu.utils.arrays import obj_array


@dataclasses.dataclass(frozen=True)
class RetryStrategy:
    """Fixed-delay retry with optional exponential backoff
    (AsyncRetryStrategies analogue)."""

    max_attempts: int = 1
    delay_ms: float = 0.0
    multiplier: float = 1.0

    def delay_for(self, attempt: int) -> float:
        return self.delay_ms * (self.multiplier ** (attempt - 1)) / 1000.0


NO_RETRY = RetryStrategy()


class AsyncFunction:
    """User contract: async_invoke returns the result (runs on a pool
    thread); raise to signal failure (retried per strategy)."""

    def async_invoke(self, value) -> Any:
        raise NotImplementedError

    def timeout_value(self, value) -> Any:
        """Fallback on timeout; default: raise (fails the job)."""
        raise TimeoutError(f"async I/O timed out for {value!r}")


class _LambdaAsync(AsyncFunction):
    def __init__(self, fn):
        self._fn = fn

    def async_invoke(self, value):
        return self._fn(value)


def as_async_function(fn) -> AsyncFunction:
    return fn if isinstance(fn, AsyncFunction) else _LambdaAsync(fn)


class AsyncExecutor:
    """Batch-level async fan-out engine shared by the runner and direct use."""

    def __init__(
        self,
        fn,
        *,
        capacity: int = 100,
        timeout_ms: Optional[float] = None,
        ordered: bool = True,
        retry: RetryStrategy = NO_RETRY,
    ):
        self.fn = as_async_function(fn)
        self.capacity = capacity
        self.timeout_s = timeout_ms / 1000.0 if timeout_ms else None
        self.ordered = ordered
        self.retry = retry
        self._pool = ThreadPoolExecutor(max_workers=capacity)

    def close(self) -> None:
        self._pool.shutdown(wait=False)

    def _invoke_with_retries(self, value):
        attempt = 1
        while True:
            try:
                return self.fn.async_invoke(value)
            except Exception:
                if attempt >= max(self.retry.max_attempts, 1):
                    raise
                time.sleep(self.retry.delay_for(attempt))
                attempt += 1

    def process(self, values: Iterable) -> List[Tuple[int, Any]]:
        """Returns (input_index, result) pairs — in input order when ordered,
        completion order otherwise."""
        values = list(values)
        results: List[Tuple[int, Any]] = []
        pending: dict[Future, int] = {}
        it = iter(enumerate(values))
        exhausted = False
        deadline_of: dict[Future, float] = {}

        def submit_next() -> bool:
            nonlocal exhausted
            try:
                i, v = next(it)
            except StopIteration:
                exhausted = True
                return False
            f = self._pool.submit(self._invoke_with_retries, v)
            pending[f] = i
            if self.timeout_s is not None:
                deadline_of[f] = time.monotonic() + self.timeout_s
            return True

        while not exhausted and len(pending) < self.capacity:
            if not submit_next():
                break
        while pending:
            wait_timeout = None
            if deadline_of:
                wait_timeout = max(min(deadline_of.values()) - time.monotonic(), 0)
            done, _ = wait(pending, timeout=wait_timeout, return_when=FIRST_COMPLETED)
            now = time.monotonic()
            if not done:  # a deadline expired with nothing completing
                expired = [f for f, d in deadline_of.items() if d <= now]
                for f in expired:
                    i = pending.pop(f)
                    deadline_of.pop(f, None)
                    f.cancel()
                    results.append((i, self.fn.timeout_value(values[i])))
                    if not exhausted:
                        submit_next()
                continue
            for f in done:
                i = pending.pop(f)
                deadline_of.pop(f, None)
                results.append((i, f.result()))
                if not exhausted:
                    submit_next()
        if self.ordered:
            results.sort(key=lambda p: p[0])
        return results


class AsyncMapRunner:
    """Step runner for DataStream.async_map (built by the executor).

    Duck-typed to executor.StepRunner (imported lazily there to avoid a
    module cycle); the input-gate shims below keep it wireable in the
    runner DAG."""

    downstream = None
    num_inputs = 1

    def on_batch_n(self, ordinal, values, timestamps):
        self.on_batch(values, timestamps)

    def on_watermark_n(self, ordinal, watermark):
        self.on_watermark(watermark)

    def on_end_n(self, ordinal):
        self.on_end()

    def on_marker(self, wall_ms):
        # record-then-forward, like every other runner (StepRunner.on_marker):
        # without the histogram here, a slow async stage would show up as
        # latency at the operator AFTER it
        h = getattr(self, "_marker_hist", None)
        if h is not None:
            import time as _time

            h.update(_time.time() * 1000.0 - wall_ms)
        if self.downstream:
            self.downstream.on_marker(wall_ms)

    def on_processing_time(self, now_ms):
        pass

    def __init__(self, transform, _config):
        cfg = transform.config
        self.executor = AsyncExecutor(
            cfg["fn"],
            capacity=cfg.get("capacity", 100),
            timeout_ms=cfg.get("timeout_ms"),
            ordered=cfg.get("ordered", True),
            retry=cfg.get("retry", NO_RETRY),
        )
        self.uid = transform.uid

    def register_metrics(self, group) -> None:
        self.records_in_counter = group.counter("numRecordsIn")
        self._marker_hist = group.histogram("latencyMs")

    def on_batch(self, values: np.ndarray, timestamps: np.ndarray) -> None:
        out = self.executor.process(values)
        if out and self.downstream:
            vals = obj_array([r for _, r in out])
            ts = np.asarray([int(timestamps[i]) for i, _ in out], dtype=np.int64)
            self.downstream.on_batch(vals, ts)

    def on_watermark(self, watermark: int) -> None:
        if self.downstream:
            self.downstream.on_watermark(watermark)

    def on_end(self) -> None:
        self.executor.close()
        if self.downstream:
            self.downstream.on_end()

    def snapshot(self) -> dict:
        return {}

    def restore(self, snap: dict) -> None:
        pass
