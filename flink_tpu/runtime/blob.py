"""Blob service: content-addressed distribution of job artifacts.

Analogue of runtime/blob/BlobServer.java:88: the JobManager hosts a blob
endpoint; TaskExecutors fetch job payloads (pickled plans, UDF closures —
the JAR analogue) by content hash and cache them on local disk, so a plan
is shipped once per host regardless of how many shards run there.

Security: the blob endpoint rides the JM's RPC service, so every fetch is
behind the transport handshake + per-frame MACs (flink_tpu/security) — an
unauthenticated peer is disconnected at the JM RPC port before any request
parses. Content addressing doubles as end-to-end integrity: BlobCache
re-hashes fetched AND disk-cached bytes against the requested key, so a
tampered store or cache directory cannot smuggle a different payload into
`trusted_loads` (the reference's BlobUtils checksum discipline).
"""

from __future__ import annotations

import hashlib
import os
import tempfile
from typing import Dict, Optional

from flink_tpu.runtime.rpc import RpcEndpoint


class BlobServerEndpoint(RpcEndpoint):
    """JM-side store (RPC endpoint name: 'blob')."""

    def __init__(self, storage_dir: Optional[str] = None):
        super().__init__(name="blob")
        self.dir = storage_dir or tempfile.mkdtemp(prefix="flink_tpu_blob_")
        os.makedirs(self.dir, exist_ok=True)

    def put(self, data: bytes) -> str:
        key = hashlib.sha256(data).hexdigest()
        path = os.path.join(self.dir, key)
        if not os.path.exists(path):
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
        return key

    def get(self, key: str) -> bytes:
        path = os.path.join(self.dir, key)
        if not os.path.exists(path):
            raise KeyError(f"no blob {key}")
        with open(path, "rb") as f:
            return f.read()

    def has(self, key: str) -> bool:
        return os.path.exists(os.path.join(self.dir, key))

    def delete(self, key: str) -> None:
        try:
            os.unlink(os.path.join(self.dir, key))
        except FileNotFoundError:
            pass


class BlobCache:
    """TM-side cache: fetch-once per content key (TM blob cache analogue)."""

    def __init__(self, gateway, cache_dir: Optional[str] = None):
        self._gw = gateway
        self.dir = cache_dir or tempfile.mkdtemp(prefix="flink_tpu_blobcache_")
        os.makedirs(self.dir, exist_ok=True)
        self._mem: Dict[str, bytes] = {}

    def get(self, key: str) -> bytes:
        data = self._mem.get(key)
        if data is not None:
            return data
        path = os.path.join(self.dir, key)
        if os.path.exists(path):
            with open(path, "rb") as f:
                data = f.read()
            if hashlib.sha256(data).hexdigest() != key:
                # corrupted/tampered local cache entry: refetch from the JM
                os.unlink(path)
                data = None
        if data is None:
            data = self._gw.get(key)
            if hashlib.sha256(data).hexdigest() != key:
                raise ValueError(
                    f"blob {key} failed content-hash verification after fetch"
                )
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
        self._mem[key] = data
        return data
