"""Distributed cluster runtime: JobManager + TaskExecutors over RPC + DCN.

The multi-host counterpart of the in-process MiniCluster — the analogue of
the reference's control plane (Dispatcher.submitJob Dispatcher.java:835,
JobMaster.java:155) and data plane (TaskExecutor.submitTask
TaskExecutor.java:660) re-expressed for stepped dataflow:

- A **JobManager** endpoint accepts TaskExecutor registrations (slot offers),
  persists submitted job specs in the blob server (JAR-shipping analogue,
  BlobServer.java:88), deploys one shard per slot with the full peer
  exchange-address map, coordinates **step-aligned checkpoints** (the
  barrier is a step boundary: every shard snapshots after processing step
  s_target-1, giving a consistent cut for free — SURVEY.md §7 stage 5), and
  drives **failover**: a TaskExecutor heartbeat timeout fails the job,
  cancels surviving tasks and redeploys attempt n+1 from the latest
  completed checkpoint (RestartPipelinedRegionFailoverStrategy analogue at
  whole-job granularity — stepped all-to-all makes every shard one region).
- A **TaskExecutor** endpoint runs one shard per deployed task: pull a
  source batch, bucket records by key-group owner
  (KeyGroupStreamPartitioner analogue), all-to-all the buckets over the
  credit-controlled exchange (dataplane.py), merge one batch per input
  channel per step with min-combined watermarks (StatusWatermarkValve
  semantics), and feed the shard's keyed window operator.

Exactly-once: snapshots hold (source step cursor, operator state); restart
rewinds sources to the checkpointed step and replays — in-flight exchange
batches need no persistence because they are regenerated (the stepped
equivalent of replaying from the source offset in the snapshot).
"""

from __future__ import annotations

import logging
import pickle
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.chaos import plan as _chaos
from flink_tpu.lint.contracts import absorbs_faults

_LOG = logging.getLogger(__name__)


#: reply timeout for gateways carrying PAYLOAD-shipping calls — deploys
#: restoring large snapshots, checkpoint acks the JM persists before
#: replying, blob fetches. The default 10s wedge detector would hard-fail
#: a genuinely big (and non-retryable) transfer; control-plane-only
#: gateways keep the tight default.
PAYLOAD_REPLY_TIMEOUT_S = 120.0


def _swallow(site: str, exc: BaseException) -> None:
    """Best-effort control-plane calls (cancel fan-out, decline-on-behalf,
    state release, loop ticks) deliberately survive peer failures — but
    never SILENTLY (lint CONC005 no-silent-swallow): every swallowed
    exception is debug-logged with its site so a misbehaving plane is
    diagnosable without a debugger."""
    _LOG.debug("swallowed %r at %s", exc, site)

from flink_tpu.core.keygroups import (
    KeyGroupRange,
    key_group_range_for_operator,
    key_groups_for_hashes,
    key_hash,
    operator_index_for_key_group,
)
from flink_tpu.core.time import MAX_WATERMARK, MIN_WATERMARK
from flink_tpu.checkpoint.storage import FsCheckpointStorage
from flink_tpu.metrics.checkpoint_stats import (
    CheckpointStatsTracker,
    ExceptionHistory,
    operator_bytes_from_snapshot,
    snapshot_bytes_estimate,
)
from flink_tpu.metrics.registry import MetricRegistry, metrics_snapshot
from flink_tpu.metrics.task_io import backpressure_level
from flink_tpu.metrics.traces import Span, job_trace_id
from flink_tpu.runtime.blob import BlobCache, BlobServerEndpoint
from flink_tpu.runtime.dataplane import (
    ExchangeServer,
    OutputChannel,
    SequenceLostError,
)
from flink_tpu.runtime.heartbeat import HeartbeatManager
from flink_tpu.runtime.rpc import (
    RetryPolicy,
    RpcEndpoint,
    RpcGateway,
    RpcService,
    current_trace_id,
    trace_context,
)
from flink_tpu.security.framing import trusted_loads
from flink_tpu.state import key_groups


# ---------------------------------------------------------------------------
# job specification (shipped through the blob server)
# ---------------------------------------------------------------------------

class _PickledSpec:
    """Serialization shared by job specs: cloudpickle (when present) ships
    closures/lambdas the way the reference ships user JARs; plain picklable
    specs need only stdlib.

    Specs are code by definition (they carry user closures), so they bypass
    the transport allowlist — but only ever deserialize AFTER the carrying
    connection authenticated (security/framing.py trusted_loads): the
    user-JAR trust model of the reference."""

    def to_bytes(self) -> bytes:
        try:
            import cloudpickle

            return cloudpickle.dumps(self)
        except ImportError:
            return pickle.dumps(self, protocol=pickle.HIGHEST_PROTOCOL)

    @staticmethod
    def from_bytes(b: bytes):
        return trusted_loads(b)


@dataclass
class DistributedJobSpec(_PickledSpec):
    """A keyed windowed-aggregation pipeline, the distributed hot path.

    source_factory(shard, num_shards) -> list of (keys, vals, ts, wm) step
    batches for that shard's partition of the source."""

    name: str
    source_factory: Callable[[int, int], List[Tuple[np.ndarray, np.ndarray, np.ndarray, int]]]
    assigner: Any
    aggregate: Any
    allowed_lateness: int = 0
    max_parallelism: int = 128
    operator: str = "oracle"          # 'oracle' | 'device'
    # declared source volume (records) for AUTO parallelism: submitting
    # with parallelism=0 derives the task count from this, the
    # AdaptiveBatchScheduler analogue (scheduler/adaptivebatch/ derives
    # per-stage parallelism from produced bytes)
    source_records_hint: Optional[int] = None
    # device-operator construction knobs (e.g. session num_slices /
    # key_capacity for skewed/out-of-order streams)
    operator_options: Optional[Dict[str, Any]] = None
    # optional per-job Configuration (exchange.wire-format,
    # exchange.reconnect.window-ms, observability.sampling.interval-ms...)
    config: Optional[Any] = None


@dataclass
class GraphJobSpec(_PickledSpec):
    """A general StepGraph job for the distributed runtime.

    The keyed-window hot path runs sharded through DistributedJobSpec; any
    OTHER planned pipeline (multi-input DAGs, joins, side outputs, CEP,
    process functions...) ships as its full StepGraph and executes as one
    JobRuntime task on a TaskExecutor — the cluster analogue of submitting
    an arbitrary JobGraph: full operator coverage with cluster supervision
    (checkpoints, failover, local recovery) at task granularity."""

    name: str
    graph: Any          # graph.transformation.StepGraph
    config: Any         # flink_tpu.config.Configuration


def merge_shard_snapshots(handles: Dict[int, dict]) -> dict:
    """Fold per-shard snapshots into one logical-state snapshot for
    rescaling: heap tables union by key group (disjoint by construction,
    the StateAssignmentOperation analogue — state/key_groups.py holds the
    shared remap primitives), timers concatenate, the collect-sink results
    concatenate. Each new shard restores from this and filters to its own
    KeyGroupRange (state/heap.py restore; timers via
    filter_timers_for_range)."""
    ok, why = key_groups.reshardable(handles)
    if not ok:
        raise ValueError(why)
    shards = sorted(handles)
    ops = [handles[s]["operator"] for s in shards]
    merged_op = {
        "state": key_groups.merge_keyed_state(
            [op.get("state", {}) for op in ops]),
        "timers": key_groups.merge_timers([op.get("timers") for op in ops]),
    }
    results: list = []
    for s in shards:
        results.extend(handles[s].get("results", []))
    step = handles[min(handles)]["step"]
    return {"operator": merged_op, "results": results, "step": step, "merged": True}


@dataclass
class _JobState:
    job_id: str
    blob_key: str
    parallelism: int
    spec_name: str
    # rescale eligibility, captured at submit: keyed DistributedJobSpec
    # jobs re-shard by key group up to the spec's key-group count; graph
    # jobs snapshot whole runtimes and cannot change task count
    keyed: bool = True
    spec_max_parallelism: int = 128
    status: str = "CREATED"            # CREATED/RUNNING/RESTARTING/FINISHED/FAILED/CANCELED
    requested_parallelism: int = 0
    attempt: int = 0
    assignment: Dict[int, str] = field(default_factory=dict)   # shard -> tm_id
    finished: Dict[int, list] = field(default_factory=dict)    # shard -> results
    restarts: int = 0
    # checkpointing
    next_checkpoint_id: int = 1
    pending: Dict[int, dict] = field(default_factory=dict)     # cp_id -> {shard: handle}
    pending_target: Dict[int, int] = field(default_factory=dict)
    completed: List[Tuple[int, dict, int]] = field(default_factory=list)  # (cp_id, handles, step)
    cp_origins: Dict[int, Dict[int, str]] = field(default_factory=dict)    # cp_id -> {shard: tm_id}
    steps: Dict[int, int] = field(default_factory=dict)        # shard -> last reported step
    stages: int = 1      # >1: GraphJobSpec split into pipeline stages (slot
    #                      sharing groups); shard index = stage index
    source_stages: List[int] = field(default_factory=list)  # trigger targets
    savepoint_paths: Dict[int, Tuple[str, int]] = field(
        default_factory=dict)   # cp_id -> (target dir, retry margin)
    completed_savepoints: List[str] = field(default_factory=list)
    failed_savepoints: List[str] = field(default_factory=list)
    # observability plane: per-job correlation id, latest per-shard metric
    # snapshot shipped by the TMs, and the bounded span feed (JM trigger
    # spans + TM ack spans, all carrying trace_id)
    trace_id: str = ""
    metric_snapshots: Dict[int, dict] = field(default_factory=dict)
    spans: List[dict] = field(default_factory=list)
    # history plane (ISSUE-19): bounded metric time-series rings sampled
    # from the shard-folded snapshots on the schedule tick, plus the
    # threshold watchdog emitting health.* spans into `spans` (both
    # metrics-layer objects — Any avoids a dataclass-level import)
    history: Any = None
    watchdog: Any = None
    # fault-tolerance observability: per-checkpoint stat records + lifetime
    # counters, and the bounded exception/restart history that replaced the
    # single overwritten failure string (sizes set by the JM at submit)
    stats: CheckpointStatsTracker = field(default_factory=CheckpointStatsTracker)
    exceptions: ExceptionHistory = field(default_factory=ExceptionHistory)
    # elastic autoscaling (scheduler/): deliberate rescale bookkeeping —
    # lifetime count, last redeploy duration, and the perf_counter stamp of
    # an in-flight rescale (cleared when the new attempt reaches RUNNING)
    num_rescales: int = 0
    last_rescale_duration_ms: float = 0.0
    rescale_started: Optional[float] = None
    # stuck-task watchdog: per-shard (last reported step, monotonic stamp
    # of the last time it ADVANCED) — cleared on every (re)deploy
    progress: Dict[int, Tuple[int, float]] = field(default_factory=dict)
    # execution.checkpointing.tolerable-failed-checkpoints accounting:
    # consecutive persist/coordination failures; reset by a completion
    consecutive_cp_failures: int = 0

    @property
    def failure(self) -> Optional[str]:
        """Latest failure cause (legacy single-string view of the bounded
        exception history)."""
        latest = self.exceptions.latest()
        return latest["exception"] if latest is not None else None


_MAX_JOB_SPANS = 1024


def _shard_combine(key: str) -> str:
    """DEPRECATED name-heuristic fold fallback (ISSUE-19).

    Fold kinds are now DECLARED at registration (`MetricGroup.gauge(...,
    fold=...)` in metrics/registry.py) and shipped in each snapshot's
    reserved ``__folds__`` entry — `aggregate_shard_metrics` reads the
    declaration and only reaches here for keys without one (old TMs,
    unmigrated third-party gauges), emitting a once-per-key
    DeprecationWarning. This function is the ONLY place the `current*`
    prefix rule and the exemption tuples may be consulted for folding;
    new metric families must declare instead of growing this heuristic
    (the `_TIER_GAUGES`-omission bug class from PRs 10/11/14/17).

    The heuristic itself: per-task fractions (ratios, pool occupancy,
    busy/idle/backPressured TimeMsPerSecond — each bounded per task)
    average; watermark positions take the MIN (the job-level combined
    watermark is what EVERY subtask has reached — averaging would report
    progress a straggler shard has not made); skew/storm/hot-key gauges
    take the MAX (the job's skew is its worst shard); everything else
    (counters, totals, and THROUGHPUT rates like numRecordsInPerSecond,
    which is work done) sums. Matches on the full key, not just the
    leaf: per-channel gauges like exchange.inPoolUsage.<n> have a
    numeric leaf."""
    leaf = key.rsplit(".", 1)[-1]
    if leaf.startswith("current") and leaf not in _LATENCY_MAX_GAUGES:
        # the current* prefix means "watermark position" (fold MIN: the
        # straggler defines job progress) — EXCEPT currentBatchRung,
        # which is a controller geometry, where the job-level view is the
        # largest rung any shard is still dispatching (worst latency)
        return "min"
    if leaf == "joinFallbackReason":
        # a catalogued reason CODE, not a count: the job-level view is
        # "did ANY shard degrade, and why" — summing codes across shards
        # would fabricate a different (or uncatalogued) code
        return "max"
    if leaf in ("keySkew", "recompileStorm", "hotKeyLoad", "meshLoadSkew",
                "meshDevices") or leaf in _PER_DEVICE_MAX_GAUGES \
            or leaf in _REBALANCE_GAUGES or leaf in _LATENCY_MAX_GAUGES:
        # meshDevices included: each shard reports ITS mesh size — summing
        # across shards would misreport a plain 2-shard job as a 2-device
        # mesh (the job-level view is the largest mesh any shard runs).
        # The skew-rebalance family folds MAX for the same shape reason:
        # rebalance counts, table versions, and durations are per-mesh
        # facts every shard of that mesh reports identically — summing
        # would multiply them by the shard count
        return "max"
    if "Ratio" in leaf or leaf.endswith("TimeMsPerSecond") \
            or leaf.endswith("UtilizationPct") or "inPoolUsage" in key:
        return "mean"
    return "sum"


#: gauges shipped as {device_index: value} maps by mesh shards
#: (metrics/key_stats.py): each is a MAX-rule family, and the fold must
#: take the max across the shard's OWN mesh devices FIRST — the generic
#: dict branch below merges per stat key, which for a per-device map means
#: whichever device index collides across shards wins and the job-level
#: scalar silently becomes device 0's view
#: exactly the maps metrics/key_stats.py registers on mesh operators —
#: keep the two lists in lockstep (compile tracking is per-process SPMD,
#: one program for the whole mesh, so it has no per-device form)
_PER_DEVICE_MAX_GAUGES = ("keySkewPerDevice", "hotKeyLoadPerDevice",
                          "meshDeviceLoad")

#: skew-rebalance gauge family (parallel.mesh.skew-rebalance, registered
#: by the in-process job master): per-mesh facts every shard reports
#: identically, so they fold MAX (the _TIER_GAUGES-omission lesson: a
#: family missing from BOTH the fold rule and the device payload filters
#: silently reads as 0 / absent at the job level)
_REBALANCE_GAUGES = ("meshRebalances", "routingTableVersion",
                     "lastRebalanceDurationMs")

#: state-tier gauge family (state/tier_manager.py, registered by the
#: window-step runner): counters and sizes SUM across shards — each shard
#: owns its contiguous key range, so the job-level vocabulary/eviction/
#: spilled view is the total, never the worst shard — while
#: tierHotFillRatio (a per-shard fraction) takes the generic "Ratio" MEAN
#: rule. Listed here so the distributed /jobs/:id/device payload filter
#: carries them; the fold itself needs no extra rule (sum is the default).
_TIER_GAUGES = ("vocabSize", "residentKeys", "evictions", "promotions",
                "spilledBytes", "changelogBytes", "tierHotFillRatio")

#: device-join gauge family (runtime/device_join_operator.py, registered
#: per join operator): ring occupancy and matches emitted are per-shard
#: counts over owned key ranges, so they SUM (the default rule);
#: joinFallbackReason is a catalogued reason code and folds MAX above.
#: Listed here so both /jobs/:id/device payload filters carry the family
#: (the _TIER_GAUGES-omission lesson again: a family missing from the
#: filters silently reads as absent at the job level).
_JOIN_GAUGES = ("joinRingOccupancy", "joinMatchesEmitted",
                "joinFallbackReason")

#: emission-latency plane (metrics/emission_latency.py, registered per
#: windowed operator + the job-level p99 gauge): emissionLatencyMs ships
#: as a FLAT log-bucket snapshot and folds BUCKET-WISE (merge_snapshots —
#: the generic dict envelope would sum counts but max the percentiles,
#: which overstates the merged tail); watermarkLagMs and the job p99 are
#: worst-shard facts and fold MAX. One shared tuple feeds the fold rule
#: AND both /jobs/:id/device-style payload filters (the _TIER_GAUGES-
#: omission lesson: a family missing from either silently reads 0/absent
#: job-level).
#: latency-mode controller gauges (scheduler/latency_controller.py via
#: FusedWindowOperator.latency_gauges, registered only when
#: execution.latency.target-ms is on): rung depth, in-flight ring depth,
#: and distinct ladder geometries are per-shard controller facts whose
#: job-level view is the worst shard (the deepest rung / fullest ring /
#: most geometries compiled), so the whole family folds MAX; the tuple
#: also feeds _LATENCY_GAUGES below so both /jobs/:id/device payload
#: filters carry it (the _TIER_GAUGES-omission lesson yet again).
_LATENCY_CONTROLLER_GAUGES = ("latencyModeActive", "currentBatchRung",
                              "inflightDepth", "ladderRecompiles")
_LATENCY_MAX_GAUGES = ("watermarkLagMs",
                       "p99EmissionLatencyMs") + _LATENCY_CONTROLLER_GAUGES
_LATENCY_HISTOGRAMS = ("emissionLatencyMs",)
_LATENCY_GAUGES = _LATENCY_MAX_GAUGES + _LATENCY_HISTOGRAMS

#: the ONE leaf-name set both /jobs/:id/device payload filters consult
#: (ISSUE-19 consolidation of the scattered per-filter tuple unions — the
#: _TIER_GAUGES-omission lesson: two hand-maintained filters drift, one
#: derived set cannot)
_DEVICE_PAYLOAD_LEAVES = frozenset(
    ("keySkew", "activeKeys", "hotKeyLoad", "keyGroupLoad",
     "keyGroupStateBytes", "hbmUtilizationPct", "flopsUtilizationPct",
     "meshLoadSkew", "meshDevices")
    + _TIER_GAUGES + _PER_DEVICE_MAX_GAUGES + _REBALANCE_GAUGES
    + _JOIN_GAUGES + _LATENCY_GAUGES)


def _is_device_payload_key(key: str) -> bool:
    """Does `key` belong in a /jobs/:id/device payload (job-level fold
    and per-shard alike)? Reserved ``__`` metadata never does."""
    if key.startswith("__"):
        return False
    return (".device." in key or "keySkew" in key or "meshLoadSkew" in key
            or key.rsplit(".", 1)[-1] in _DEVICE_PAYLOAD_LEAVES)


#: keys that already fell back to the name heuristic (warn once per key,
#: not once per heartbeat fold)
_WARNED_UNDECLARED: set = set()


def _fold_for(key: str, declared: Dict[str, str]) -> str:
    """Declared fold kind, else the DEPRECATED name heuristic (warns once
    per key)."""
    how = declared.get(key)
    if how is not None:
        return how
    if key not in _WARNED_UNDECLARED:
        _WARNED_UNDECLARED.add(key)
        import warnings

        warnings.warn(
            f"metric {key!r} declares no fold kind; falling back to the "
            "deprecated name heuristic — register it with "
            "gauge(..., fold=...) (metrics/registry.py)",
            DeprecationWarning, stacklevel=3)
    return _shard_combine(key)


def aggregate_shard_metrics(per_shard: Dict[int, dict]) -> dict:
    """Fold per-shard metric snapshots into one job-level view.

    The fold kind per key comes from the snapshots' reserved ``__folds__``
    declarations (registered with the metric — metrics/registry.py);
    undeclared keys fall back to the deprecated `_shard_combine` name
    heuristic with a warning. Dict-valued metrics fold by declaration
    too: ``"emission"`` merges log buckets exactly, ``"per-device-max"``
    maxes over the shard's device map first, and everything else takes
    the approximate envelope — max-of-p99 / min-of-min / summed count
    (cheap percentile union; exact merging would need the reservoirs,
    which stay TM-local) — marked ``"approx": true`` in the folded
    payload so readers never mistake it for the exact bucket-wise merge
    emission histograms get."""
    from flink_tpu.metrics.emission_latency import (
        merge_snapshots as _merge_emission,
    )

    declared: Dict[str, str] = {}
    for snap in per_shard.values():
        folds = snap.get("__folds__")
        if isinstance(folds, dict):
            declared.update(folds)

    scalars: Dict[str, List[float]] = {}
    emission: Dict[str, list] = {}
    agg: dict = {}
    for snap in per_shard.values():
        for key, val in snap.items():
            if key.startswith("__"):    # reserved metadata, not a metric
                continue
            leaf = key.rsplit(".", 1)[-1]
            if isinstance(val, dict):
                how = declared.get(key)
                if how == "emission" or (how is None
                                         and leaf in _LATENCY_HISTOGRAMS):
                    # emission-latency histograms carry their log buckets,
                    # so the fold is EXACT: merge bucket counts, recompute
                    # the percentiles — never the generic envelope below
                    emission.setdefault(key, []).append(val)
                    continue
                if how == "per-device-max" or (
                        how is None and leaf in _PER_DEVICE_MAX_GAUGES):
                    # per-mesh-device map: fold across THIS shard's
                    # devices first (MAX — the job's view of a skew/storm/
                    # hot-key family is its worst device, and device
                    # indexes repeat across shards so elementwise merging
                    # would be meaningless), then MAX across shards
                    devs = [v for v in val.values()
                            if isinstance(v, (int, float))]
                    if devs:
                        scalars.setdefault(key, []).append(float(max(devs)))
                    continue
                cur = agg.setdefault(key, {})
                # honest labeling: the envelope is approximate (exact
                # quantile merging needs the TM-local reservoirs)
                cur["approx"] = True
                for stat, v in val.items():
                    if not isinstance(v, (int, float)):
                        continue
                    if stat == "count":
                        cur[stat] = cur.get(stat, 0) + v
                    elif stat == "min":
                        cur[stat] = min(cur.get(stat, v), v)
                    else:   # max / mean / percentiles: upper envelope
                        cur[stat] = max(cur.get(stat, v), v)
            elif isinstance(val, (int, float)):
                scalars.setdefault(key, []).append(val)
    wm_skews = []
    for key, vals in scalars.items():
        how = _fold_for(key, declared)
        if how == "max":
            agg[key] = max(vals)
        elif how == "min":
            agg[key] = min(vals)
            # job-level watermark skew: max-min currentWatermark across the
            # subtasks of one operator — how far the combined (MIN) watermark
            # trails the fastest subtask, i.e. the straggler's lag in event
            # time. The job gauge is the worst skew over all operators.
            # Subtasks still at the MIN_WATERMARK sentinel (no watermark
            # yet) are excluded: differencing against -(1<<63) would export
            # a ~9.2e18 garbage value that wrecks dashboards and alerts.
            if key.rsplit(".", 1)[-1] == "currentWatermark":
                real = [v for v in vals if v > MIN_WATERMARK]
                wm_skews.append(max(real) - min(real) if len(real) >= 2
                                else 0.0)
        elif how == "mean":
            agg[key] = sum(vals) / len(vals)
        else:
            agg[key] = sum(vals)
    for key, snaps in emission.items():
        agg[key] = _merge_emission(snaps)
    if wm_skews:
        agg["job.watermarkSkewMs"] = max(wm_skews)
    return agg


class JobManagerEndpoint(RpcEndpoint):
    """Dispatcher + JobMaster in one endpoint (M2+M3 scope)."""

    def __init__(
        self,
        rpc: RpcService,
        *,
        checkpoint_dir: Optional[str] = None,
        checkpoint_interval: float = 0.0,
        restart_attempts: int = 2,
        restart_delay: float = 0.2,
        heartbeat_interval: float = 0.5,
        heartbeat_timeout: float = 3.0,
        adaptive: bool = True,
        auto_records_per_task: int = 1 << 20,
        checkpoint_history_size: int = 10,
        exception_history_size: int = 16,
        autoscaler_config=None,
        tolerable_failed_checkpoints: int = 0,
        stuck_task_timeout_ms: int = 0,
        history_interval_ms: int = 1000,
        history_retention_points: int = 256,
        doctor_enabled: bool = True,
        doctor_window_ms: float = 60000.0,
        watchdog_min_gap_ms: float = 5000.0,
        p99_breach_ms: float = 0.0,
    ):
        super().__init__(name="jobmanager")
        self.rpc = rpc
        self.auto_records_per_task = auto_records_per_task
        # observability.history.* / observability.doctor.* (ISSUE-19): the
        # JM samples each job's shard-folded snapshot into bounded rings on
        # the schedule tick and runs the threshold watchdog over them
        self.history_interval_ms = history_interval_ms
        self.history_retention_points = history_retention_points
        self.doctor_enabled = doctor_enabled
        self.doctor_window_ms = doctor_window_ms
        self.watchdog_min_gap_ms = watchdog_min_gap_ms
        self.p99_breach_ms = p99_breach_ms
        # execution.checkpointing.tolerable-failed-checkpoints: consecutive
        # checkpoint failures absorbed (FAILED stats record + gauge) before
        # the job takes the restart path
        self.tolerable_failed_checkpoints = tolerable_failed_checkpoints
        # execution.watchdog.stuck-task-timeout-ms: 0 = watchdog off
        self.stuck_task_timeout_ms = stuck_task_timeout_ms
        # observability.checkpoint-history.size / .exception-history.size
        self.checkpoint_history_size = checkpoint_history_size
        self.exception_history_size = exception_history_size
        self.blob = BlobServerEndpoint()
        rpc.register(self)
        rpc.register(self.blob)
        self.checkpoint_interval = checkpoint_interval
        self.restart_attempts = restart_attempts
        self.adaptive = adaptive
        self.restart_delay = restart_delay
        self._storage = FsCheckpointStorage(checkpoint_dir) if checkpoint_dir else None
        self._tms: Dict[str, dict] = {}
        self._jobs: Dict[str, _JobState] = {}
        self.heartbeats = HeartbeatManager(
            interval=heartbeat_interval, timeout=heartbeat_timeout,
            on_dead=self._on_tm_dead,
        )
        if checkpoint_interval > 0:
            threading.Thread(target=self._checkpoint_loop, daemon=True,
                             name="checkpoint-trigger").start()
        # periodic scheduling retry: jobs parked in RESTARTING (e.g. a deploy
        # hit a dead-but-undetected worker) get re-attempted without needing
        # a registration event
        self._stopped = threading.Event()
        threading.Thread(target=self._schedule_loop, daemon=True,
                         name="schedule-retry").start()
        # elastic autoscaler (scheduler/ — AdaptiveScheduler analogue): a
        # controller thread samples each RUNNING job's aggregated gauges
        # into the signal windows and executes policy-driven rescales via
        # _rescale_job; `autoscaler_config` is a Configuration carrying the
        # autoscaler.* group (None or enabled=false leaves it off)
        self.autoscaler = None
        self._autoscaler_interval = 1.0
        if autoscaler_config is not None:
            from flink_tpu.config import AutoscalerOptions
            from flink_tpu.scheduler import AutoscalerCoordinator

            if autoscaler_config.get(AutoscalerOptions.ENABLED):
                self.autoscaler = AutoscalerCoordinator.from_config(
                    autoscaler_config, rescale_executor=self._rescale_job)
                self._autoscaler_interval = autoscaler_config.get(
                    AutoscalerOptions.INTERVAL_MS) / 1000.0
                threading.Thread(target=self._autoscaler_loop, daemon=True,
                                 name="autoscaler").start()

    @absorbs_faults('background autoscaler tick: a failed tick is logged and retried next interval; job failover, not this timer thread, owns fault propagation')
    def _autoscaler_loop(self) -> None:
        while not self._stopped.wait(self._autoscaler_interval):
            try:
                self.run_in_main_thread(self._autoscale_tick).result(timeout=30)
            except Exception as e:
                _swallow("autoscaler_loop", e)

    def _autoscale_tick(self) -> None:
        """One controller evaluation (JM main thread — the coordinator's
        rescale executor mutates job state inline, like every other
        scheduling mutation). Only keyed single-vertex jobs are eligible:
        staged pipelines snapshot per-stage runtimes, not key-group state."""
        for job_id, job in list(self._jobs.items()):
            if job.status != "RUNNING" or job.stages != 1 or not job.keyed:
                continue
            metrics, per_shard, _ = self._aggregated_job_metrics(job)
            if not per_shard:
                continue
            self.autoscaler.observe(
                job_id, job.parallelism, metrics,
                # slots the job could occupy, capped by its key-group count
                max_slots=min(len(self._free_slots()) + job.parallelism,
                              job.spec_max_parallelism),
            )

    @absorbs_faults('JM schedule tick: a failed tick is logged and the loop retries; task failures surface through the failover path, not this timer thread')
    def _schedule_loop(self) -> None:
        while not self._stopped.wait(max(self.restart_delay, 0.2)):
            try:
                self.run_in_main_thread(self._schedule_tick).result(timeout=30)
            except Exception as e:
                _swallow("schedule_loop", e)

    def _schedule_tick(self) -> None:
        self._try_schedule_all()
        self._watchdog_tick()
        self._history_tick()

    @absorbs_faults('metrics history sampling is best-effort observability; a failed sample must not take down the scheduler tick')
    def _history_tick(self) -> None:
        """Sample each RUNNING job's shard-folded snapshot into its
        history rings (JM main thread, riding the existing schedule tick
        — the processing-time tick of the distributed path) and let the
        health watchdog inspect the fresh window. The cheap due() gate
        runs first so an idle tick costs two comparisons."""
        for job in list(self._jobs.values()):
            if (job.status != "RUNNING" or job.history is None
                    or not job.metric_snapshots or not job.history.due()):
                continue
            try:
                agg, per_shard, _ = self._aggregated_job_metrics(job)
                kinds: Dict[str, str] = {}
                for snap in per_shard.values():
                    k = snap.get("__kinds__")
                    if isinstance(k, dict):
                        kinds.update(k)
                job.history.sample(agg, kinds=kinds)
                if job.watchdog is not None:
                    job.watchdog.observe(job.history)
            except Exception as e:
                _swallow("history_tick", e)

    def _watchdog_tick(self) -> None:
        """Stuck-task watchdog (JM main thread): a task whose heartbeat-
        reported step counter has not advanced for
        `stuck_task_timeout_ms` while its TM keeps heartbeating is wedged
        INSIDE a live process — invisible to heartbeat failure detection
        — and is failed through the normal attributed restart path. TM
        loss and finished shards are excluded (their own paths own them)."""
        if self.stuck_task_timeout_ms <= 0:
            return
        now = time.monotonic()
        for job in list(self._jobs.values()):
            if job.status != "RUNNING":
                continue
            for shard, (step, stamped) in list(job.progress.items()):
                if shard in job.finished:
                    continue
                tm_id = job.assignment.get(shard)
                if tm_id is None or not self.heartbeats.is_alive(tm_id):
                    continue      # dead TM: the heartbeat path handles it
                stalled_ms = (now - stamped) * 1000.0
                if stalled_ms >= self.stuck_task_timeout_ms:
                    self._fail_job(
                        job,
                        f"shard {shard} stuck at step {step}: no progress "
                        f"for {stalled_ms:.0f} ms while TM {tm_id} stayed "
                        "alive (stuck-task watchdog)",
                        task=f"shard-{shard}", task_manager=tm_id)
                    break         # one failover per job per tick

    def stop(self) -> None:
        self._stopped.set()
        self.heartbeats.stop()
        super().stop()

    # ---- TaskExecutor registration / liveness (M5/M8/M10 scope) ----------
    def register_task_executor(self, tm_id: str, rpc_address: str,
                               exchange_address: str, slots: int = 1) -> dict:
        self._tms[tm_id] = {
            "rpc": rpc_address, "exchange": exchange_address, "slots": slots,
            # deploy_task ships restore snapshots: payload reply budget
            "gateway": self.rpc.gateway(
                rpc_address, "taskexecutor",
                reply_timeout=PAYLOAD_REPLY_TIMEOUT_S),
        }
        self.heartbeats.monitor(tm_id)
        try:
            self._try_schedule_all()
        except Exception as e:
            _swallow("register.try_schedule", e)  # scheduling trouble must
            #                                      not fail the registration
        return {"registered": True, "jm_blob": "blob"}

    def heartbeat_tm(self, tm_id: str, steps: Optional[dict] = None,
                     metrics: Optional[dict] = None,
                     spans: Optional[list] = None) -> bool:
        # chaos seam: a heartbeat-scope drop rule partitions this TM from
        # the JM's liveness view — beats (and the steps/metrics riding
        # them) vanish exactly as on a one-way network partition
        hook = _chaos.HOOK
        if hook is not None and hook("heartbeat", tm_id) == "drop":
            return False
        self.heartbeats.receive_heartbeat(tm_id)
        # keys are (job_id, shard, attempt) — the attempt guard keeps an
        # in-flight heartbeat snapshotted before a rescale's cancel from
        # re-landing AFTER the redeploy cleared job.steps/metric_snapshots
        # (a dead higher shard would otherwise pollute the aggregates and
        # the autoscaler's signal windows for the whole new attempt);
        # 2-tuple keys (older TMs) are accepted unguarded
        if steps:
            now = time.monotonic()
            for (job_id, shard, *att), step in steps.items():
                job = self._jobs.get(job_id)
                if job is not None and (not att or att[0] == job.attempt):
                    job.steps[shard] = step
                    # watchdog progress stamp: refreshed only when the
                    # step ADVANCES (a frozen counter is what stuck means)
                    prev = job.progress.get(shard)
                    if prev is None or prev[0] != step:
                        job.progress[shard] = (step, now)
        if metrics:
            # TM-shipped metric snapshots (authenticated RPC plane): latest
            # snapshot per shard wins — the JM serves aggregates, history
            # lives in whatever scrapes /metrics
            for (job_id, shard, *att), snap in metrics.items():
                job = self._jobs.get(job_id)
                if job is not None and (not att or att[0] == job.attempt):
                    job.metric_snapshots[shard] = snap
        if spans:
            for sd in spans:
                job = self._jobs.get(sd.get("attributes", {}).get("jobId"))
                if job is not None:
                    job.spans.append(sd)
                    del job.spans[:-_MAX_JOB_SPANS]
        return True

    def peer_alive(self, job_id: str, attempt: int, shard: int) -> bool:
        """Is the TM hosting `shard` of `job_id` (attempt `attempt`) still
        registered and heartbeating? A task seeing a dataplane error asks
        this to distinguish a transient peer blip (TM alive → bounded
        reconnect window) from real TM loss (→ escalate to the restart
        path immediately; reconnecting to a dead peer only burns the
        window)."""
        job = self._jobs.get(job_id)
        if job is None or job.attempt != attempt or job.status != "RUNNING":
            return False
        tm_id = job.assignment.get(shard)
        return (tm_id is not None and tm_id in self._tms
                and self.heartbeats.is_alive(tm_id))

    def _on_tm_dead(self, tm_id: str) -> None:
        self.run_in_main_thread(self._handle_tm_dead, tm_id)

    def _handle_tm_dead(self, tm_id: str) -> None:
        self._tms.pop(tm_id, None)
        self.heartbeats.unmonitor(tm_id)
        for job in self._jobs.values():
            if job.status == "RUNNING" and tm_id in job.assignment.values():
                self._fail_job(
                    job, f"task executor {tm_id} lost (heartbeat timeout)",
                    task_manager=tm_id)

    # ---- job lifecycle (M2/M3) -------------------------------------------
    def submit_job(self, spec_bytes: bytes, parallelism: int,
                   savepoint_path: Optional[str] = None) -> str:
        blob_key = self.blob.put(spec_bytes)
        spec = DistributedJobSpec.from_bytes(spec_bytes)
        stages = 1
        source_stages: List[int] = []
        if isinstance(spec, GraphJobSpec):
            from flink_tpu.runtime.stages import (
                num_stages,
                source_stage_indices,
                validate_stages,
            )

            validate_stages(spec.graph)
            stages = num_stages(spec.graph)
            source_stages = source_stage_indices(spec.graph)
            if parallelism not in (1, stages):
                raise ValueError(
                    "GraphJobSpec jobs deploy one task per slot-sharing "
                    f"group ({stages} stage(s)); keyed sharded execution "
                    "uses DistributedJobSpec"
                )
            parallelism = stages
        if parallelism == 0 and not isinstance(spec, GraphJobSpec):
            # AUTO parallelism (AdaptiveBatchScheduler analogue,
            # scheduler/adaptivebatch/): derive the task count from the
            # declared source volume — one task per auto_records_per_task
            # records — clamped to max_parallelism; with no volume hint,
            # size to the currently free slots (elastic default)
            hint = getattr(spec, "source_records_hint", None)
            if hint is not None:
                parallelism = -(-int(hint) // self.auto_records_per_task)
            else:
                parallelism = max(len(self._free_slots()), 1)
            parallelism = max(1, min(parallelism, spec.max_parallelism))
        elif parallelism <= 0:
            raise ValueError("parallelism must be positive (0 = AUTO is "
                             "only defined for DistributedJobSpec)")
        job_id = uuid.uuid4().hex[:16]
        job = _JobState(
            job_id, blob_key, parallelism, spec.name,
            keyed=not isinstance(spec, GraphJobSpec),
            spec_max_parallelism=getattr(spec, "max_parallelism", 128),
            requested_parallelism=parallelism, stages=stages,
            source_stages=source_stages, trace_id=job_trace_id(job_id),
            stats=CheckpointStatsTracker(
                history_size=self.checkpoint_history_size),
            exceptions=ExceptionHistory(size=self.exception_history_size),
        )
        # history plane + watchdog (ISSUE-19): rings live on the JM job
        # state (the folded view is assembled here); watchdog breaches
        # land in job.spans through the same _job_span path as every
        # other JM control-plane span
        from flink_tpu.metrics.doctor import HealthWatchdog
        from flink_tpu.metrics.history import MetricHistory

        job.history = MetricHistory(
            interval_ms=self.history_interval_ms,
            retention_points=self.history_retention_points)
        if self.doctor_enabled:
            def _health_sink(scope, name, start_ms, end_ms, attrs,
                             _job=job):
                self._job_span(_job, scope, name, start_ms, **attrs)

            job.watchdog = HealthWatchdog(
                _health_sink, min_gap_ms=self.watchdog_min_gap_ms,
                p99_breach_ms=self.p99_breach_ms)
        if savepoint_path is not None:
            # start FROM a savepoint (execution.savepoint.path analogue):
            # seed the restore chain with the written snapshot set — the
            # first schedule restores every shard from it
            st = FsCheckpointStorage(savepoint_path)
            latest = st.latest()
            if latest is None:
                raise ValueError(f"no savepoint found at {savepoint_path!r}")
            data = st.load(latest[1])
            handles = data["shards"]
            # validate the snapshot set against the submitted spec up front:
            # a mismatched savepoint would otherwise surface as an opaque
            # KeyError deep inside _try_schedule/merge_shard_snapshots
            staged_handles = any(
                isinstance(h, dict) and "runtime" in h for h in handles.values()
            )
            if isinstance(spec, GraphJobSpec):
                if set(handles) != set(range(stages)) or not all(
                    isinstance(h, dict) and "runtime" in h
                    for h in handles.values()
                ):
                    raise ValueError(
                        f"savepoint at {savepoint_path!r} does not hold "
                        f"per-stage runtime snapshots for stages "
                        f"0..{stages - 1} (found keys {sorted(handles)}"
                        f"{'' if staged_handles else ', keyed snapshots'}); "
                        "staged jobs can only resume from a staged savepoint "
                        "with a matching stage count (within a stage, state "
                        "is matched by operator uid, as in the reference's "
                        "savepoint uid mapping)"
                    )
            elif staged_handles:
                raise ValueError(
                    f"savepoint at {savepoint_path!r} holds per-stage runtime "
                    "snapshots from a GraphJobSpec job; it cannot seed a "
                    "keyed DistributedJobSpec (key-group state is required "
                    "to re-shard)"
                )
            job.completed.append((0, handles, data["step"]))
        self._jobs[job_id] = job
        self._try_schedule(self._jobs[job_id])
        return job_id

    def job_status(self, job_id: str) -> dict:
        job = self._jobs[job_id]
        return {
            "status": job.status, "attempt": job.attempt, "name": job.spec_name,
            "parallelism": job.parallelism, "stages": job.stages,
            "tasks": len(job.assignment),
            "savepoints": list(job.completed_savepoints),
            "savepoints_failed": list(job.failed_savepoints),
            "failure": job.failure, "restarts": job.restarts,
            "rescales": job.num_rescales,
            "checkpoints": [c[0] for c in job.completed],
            "trace_id": job.trace_id,
        }

    # ---- observability queries (served to REST via rest.py jm bridge) ----
    def list_jobs(self) -> list:
        return [
            {"id": job_id, "name": job.spec_name, "status": job.status}
            for job_id, job in self._jobs.items()
        ]

    def _aggregated_job_metrics(self, job: "_JobState"
                                ) -> "tuple[dict, dict, dict]":
        """One fold of the TM-shipped per-shard snapshots plus the JM-side
        control-plane gauges: checkpoint stats, restart/downtime, and
        rescale counters live on the coordinator, not on any TM. Both
        /jobs/:id/metrics and the autoscaler tick read THIS recipe — the
        signal extractor needs e.g. job.lastCheckpointDuration as its
        rescale-cost proxy, and a fold maintained twice would let the
        autoscaler's view silently diverge from what /metrics reports."""
        per_shard = {int(s): dict(snap)
                     for s, snap in job.metric_snapshots.items()}
        agg = aggregate_shard_metrics(per_shard)
        jm_gauges = job.stats.gauge_values(prefix="job.")
        jm_gauges.update(job.exceptions.gauge_values(prefix="job."))
        jm_gauges["job.numRescales"] = job.num_rescales
        jm_gauges["job.lastRescaleDurationMs"] = job.last_rescale_duration_ms
        # swallowed-ping accounting (heartbeat.py): a climbing value is the
        # early signal of a flapping/partitioned control plane
        jm_gauges["job.heartbeatMissedPings"] = self.heartbeats.missed_pings
        if "job.watermarkSkewMs" in agg:
            jm_gauges["job.watermarkSkewMs"] = agg["job.watermarkSkewMs"]
        agg.update(jm_gauges)
        return agg, per_shard, jm_gauges

    def job_metrics(self, job_id: str) -> dict:
        """Aggregated + per-shard metric view of the TM-shipped snapshots,
        plus the JM-side control-plane gauges (`jm`), which ride as their
        own labeled snapshot in /metrics."""
        job = self._jobs[job_id]
        agg, per_shard, jm_gauges = self._aggregated_job_metrics(job)
        return {
            "job": agg,
            "per_shard": per_shard,
            "jm": jm_gauges,
            "trace_id": job.trace_id,
        }

    def job_checkpoints(self, job_id: str) -> dict:
        """Checkpoint statistics payload (/jobs/:id/checkpoints shape):
        counts, summary, latest completed/failed/restored, bounded
        per-checkpoint history."""
        return self._jobs[job_id].stats.payload()

    def job_checkpoint(self, job_id: str, checkpoint_id: int) -> dict:
        """One retained checkpoint's record (/jobs/:id/checkpoints/:cid)."""
        rec = self._jobs[job_id].stats.checkpoint(int(checkpoint_id))
        if rec is None:
            raise KeyError(
                f"no retained stats for checkpoint {checkpoint_id} "
                f"of job {job_id}")
        return rec

    def job_exceptions(self, job_id: str) -> dict:
        """Bounded exception history + recovery timeline
        (/jobs/:id/exceptions shape)."""
        return self._jobs[job_id].exceptions.payload()

    def job_spans(self, job_id: str) -> list:
        """Span feed (plain dicts) for the job: JM trigger/complete spans
        and TM-shipped ack spans, all stamped with the job's trace_id."""
        return list(self._jobs[job_id].spans)

    def job_latency(self, job_id: str) -> dict:
        """Emission-latency + stall-attribution report
        (/jobs/:id/latency shape, identical to the MiniCluster's so one
        dashboard panel reads both): the shard-folded emissionLatencyMs
        histograms (bucket-wise merge) and watermarkLagMs MAX from
        _aggregated_job_metrics, attributed against the job's span feed —
        TM-shipped EmissionStall outliers vs JM/TM control-plane spans."""
        from flink_tpu.metrics.emission_latency import build_latency_report

        job = self._jobs[job_id]
        agg, _per_shard, _jm = self._aggregated_job_metrics(job)
        return build_latency_report(agg, list(job.spans))

    def job_history(self, job_id: str, metric: Optional[str] = None,
                    since: Optional[float] = None) -> dict:
        """Metric time-series rings (/jobs/:id/history?metric=&since=
        shape, identical to the MiniCluster's): per-key bounded point
        lists sampled from the shard-folded snapshots — counters as
        windowed rates, gauges as values, histograms as per-sample
        p50/p99 sub-series."""
        job = self._jobs[job_id]
        if job.history is None:
            return {"enabled": False, "series": {}, "sample_count": 0}
        payload = job.history.payload(
            metric=metric or None,
            since_ms=float(since) if since not in (None, "") else None)
        payload["enabled"] = True
        return payload

    def job_doctor(self, job_id: str) -> dict:
        """Ranked bottleneck diagnosis (/jobs/:id/doctor shape, identical
        to the MiniCluster's): the job doctor joined over the history
        rings and the span feed."""
        from flink_tpu.metrics.doctor import diagnose

        job = self._jobs[job_id]
        if job.history is None:
            return {"verdict": "unknown", "score": 0.0, "diagnoses": [],
                    "window_ms": self.doctor_window_ms, "samples": 0,
                    "watchdog_events": 0}
        return diagnose(job.history, list(job.spans),
                        window_ms=self.doctor_window_ms)

    def job_backpressure(self, job_id: str) -> dict:
        """Per-shard busy/idle/backPressured ratios from the latest shipped
        snapshots (JobVertexBackPressureHandler analogue)."""
        job = self._jobs[job_id]
        subtasks = []
        worst = 0.0
        for shard in sorted(job.metric_snapshots):
            snap = job.metric_snapshots[shard]
            ratio = float(snap.get("job.backPressuredTimeRatio", 0.0))
            worst = max(worst, ratio)
            idle_ratio = float(snap.get("job.idleTimeRatio", 0.0))
            subtasks.append({
                "subtask": shard,
                "backPressuredRatio": ratio,
                "busyRatio": float(snap.get("job.busyTimeRatio", 0.0)),
                "idleRatio": idle_ratio,
                "backpressureLevel": backpressure_level(ratio),
                # idle-subtask indicator: a subtask spending nearly all its
                # loop time waiting is starved (skewed keys / slow source)
                "idle": idle_ratio >= 0.95,
            })
        return {
            "status": "ok" if subtasks else "deprecated",
            "backpressureLevel": backpressure_level(worst),
            "subtasks": subtasks,
        }

    def _job_span(self, job: _JobState, scope: str, name: str,
                  start_ms: float, **attrs) -> None:
        now = time.time() * 1000.0
        attrs.setdefault("jobId", job.job_id)
        job.spans.append(Span(scope, name, start_ms, now, attrs,
                              trace_id=job.trace_id).to_dict())
        del job.spans[:-_MAX_JOB_SPANS]

    def job_result(self, job_id: str) -> Optional[list]:
        job = self._jobs[job_id]
        if job.status != "FINISHED":
            return None
        out: list = []
        for shard in sorted(job.finished):
            out.extend(job.finished[shard])
        return out

    def cancel_job(self, job_id: str) -> None:
        job = self._jobs[job_id]
        self._cancel_tasks(job)
        job.status = "CANCELED"
        self._release_job_local_state(job)

    # ---- elastic rescaling (scheduler/ executor half) ---------------------
    def rescale_job(self, job_id: str, parallelism: int,
                    reason: str = "manual") -> dict:
        """RPC: deliberate live rescale to `parallelism` — the operator- or
        policy-triggered generalization of the rescale-down-on-TM-loss
        path. Returns {"accepted": bool, "detail": str}."""
        accepted, detail = self._rescale_job(job_id, int(parallelism), reason)
        return {"accepted": accepted, "detail": detail}

    def _rescale_job(self, job_id: str, target: int,
                     reason: str) -> Tuple[bool, str]:
        """Rescale executor: rewind to the latest completed checkpoint and
        remap key-groups onto the new slot set (both directions). The
        mechanics reuse the failover path — cancel the attempt, mark the
        job RESCALING, let _try_schedule merge + re-shard the snapshot —
        so a rescale gets the same recovery-timeline entry (kind
        'rescale'), restore accounting, and exactly-once replay semantics
        as a restart, without consuming the restart-attempts budget."""
        job = self._jobs.get(job_id)
        if job is None:
            return False, f"unknown job {job_id}"
        if job.status != "RUNNING":
            return False, f"job is {job.status}, not RUNNING"
        if job.stages != 1 or not job.keyed:
            return False, ("only keyed jobs can rescale: staged/graph "
                           "pipelines snapshot whole runtimes, not "
                           "key-group state")
        if not job.completed:
            return False, "no completed checkpoint to rewind to"
        if target < 1:
            return False, f"parallelism must be positive, got {target}"
        if target > job.spec_max_parallelism:
            return False, (f"target {target} exceeds the job's "
                           f"max-parallelism (key-group count) "
                           f"{job.spec_max_parallelism}")
        if target == job.parallelism:
            return False, f"already at parallelism {target}"
        capacity = len(self._free_slots()) + job.parallelism
        if target > capacity:
            return False, f"{target} slots needed, {capacity} available"
        _cp_id, handles, _step = job.completed[-1]
        if set(handles) != set(range(target)):
            ok, why = key_groups.reshardable(handles)
            if not ok:
                return False, why
        old = job.parallelism
        job.num_rescales += 1
        job.rescale_started = time.perf_counter()
        # in-flight checkpoints belong to the attempt being cancelled: the
        # attempt guard rejects their remaining acks and checkpoint ids are
        # never reused, so without this sweep (the _fail_job analogue) the
        # stats records would sit IN_PROGRESS forever in /jobs/:id/checkpoints
        for cp_id in list(job.pending):
            job.stats.report_failed(
                cp_id, f"superseded by rescale {old}->{target}",
                benign=True)
        self._cancel_tasks(job)
        job.parallelism = target
        job.status = "RESCALING"
        # the rescale rides the recovery timeline (it IS a rewind+redeploy)
        # tagged kind='rescale'; numRestarts counts it, as the reference's
        # reactive mode does, but restart_attempts is not consumed
        job.exceptions.begin_recovery(
            job.restarts, kind="rescale",
            cause=f"rescale {old}->{target}: {reason}",
            steps_at_failure=max(job.steps.values(), default=0))
        self._job_span(job, "autoscaler", "JobRescale", time.time() * 1000.0,
                       fromParallelism=old, toParallelism=target,
                       reason=reason[:200])
        self._try_schedule(job)
        return True, f"rescaling {old}->{target}"

    def job_autoscaler(self, job_id: str) -> dict:
        """Autoscaler view (/jobs/:id/autoscaler): decision log + rescale
        counters. Manual rescale_job calls count in num_rescales even with
        no coordinator attached."""
        from flink_tpu.scheduler import empty_autoscaler_payload

        job = self._jobs[job_id]
        if self.autoscaler is not None:
            payload = self.autoscaler.payload(
                job_id, num_rescales=job.num_rescales,
                last_rescale_duration_ms=job.last_rescale_duration_ms)
        else:
            payload = empty_autoscaler_payload()
            payload.update(num_rescales=job.num_rescales,
                           last_rescale_duration_ms=job.last_rescale_duration_ms)
        payload["parallelism"] = job.parallelism
        return payload

    def job_device(self, job_id: str) -> dict:
        """Device-plane view (/jobs/:id/device) of a distributed job: the
        job-level fold of the TM-shipped device gauges (compile counters
        sum, storm/skew take the worst shard, roofline percentages
        average) plus the 'device'-scope compile-event spans the TMs
        shipped on the heartbeat — shape-compatible with the MiniCluster
        payload so one dashboard panel reads both."""
        from flink_tpu.metrics.device_stats import empty_device_payload

        job = self._jobs[job_id]
        agg, per_shard, _ = self._aggregated_job_metrics(job)

        def _num(key, cast=float, default=0):
            v = agg.get(key)
            return cast(v) if isinstance(v, (int, float)) else default

        events = []
        for sd in job.spans:
            if sd.get("scope") != "device":
                continue
            attrs = sd.get("attributes") or {}
            events.append({
                "program": attrs.get("program"),
                "signature": attrs.get("signature"),
                "cause": attrs.get("cause"),
                "recompile": bool(attrs.get("recompile", False)),
                "compile_count": attrs.get("compileCount"),
                "duration_ms": attrs.get("durationMs"),
                "wall_ts_ms": sd.get("end_ts_ms"),
                "shard": attrs.get("shard"),
            })
        payload = empty_device_payload()
        payload["compile"].update(
            numCompiles=_num("job.device.numCompiles", int),
            numRecompiles=_num("job.device.numRecompiles", int),
            compileTimeMsTotal=_num("job.device.compileTimeMsTotal"),
            recompileStorm=_num("job.device.recompileStorm", int),
            events=events[-64:],
        )
        device_keys = {k: v for k, v in agg.items()
                       if _is_device_payload_key(k)}
        payload["metrics"] = device_keys
        payload["per_shard"] = {
            s: {k: v for k, v in snap.items() if _is_device_payload_key(k)}
            for s, snap in per_shard.items()
        }
        payload["enabled"] = bool(device_keys or events)
        return payload

    # ---- scheduling (M4-lite: deploy when slots cover parallelism) -------
    def _try_schedule_all(self) -> None:
        for job in self._jobs.values():
            if job.status in ("CREATED", "RESTARTING", "RESCALING"):
                self._try_schedule(job)

    def _free_slots(self) -> List[str]:
        """Slots not currently occupied by a deployed job. Counting total
        capacity here would let two jobs (or a job racing its own restart)
        oversubscribe a TM; the reference's slot pool likewise tracks
        allocation state per slot (DeclarativeSlotPoolBridge)."""
        used: Dict[str, int] = {}
        for job in self._jobs.values():
            if job.status == "RUNNING":
                for tm_id in job.assignment.values():
                    used[tm_id] = used.get(tm_id, 0) + 1
        slots = []
        for tm_id, tm in self._tms.items():
            free = tm["slots"] - used.get(tm_id, 0)
            if free > 0:
                slots.extend([tm_id] * free)
        return slots

    def _try_schedule(self, job: _JobState) -> None:
        if job.status not in ("CREATED", "RESTARTING", "RESCALING"):
            return  # already scheduled (e.g. a TM registration raced the
            # delayed-restart thread) or terminal
        slots = self._free_slots()
        if len(slots) < job.parallelism:
            # AdaptiveScheduler semantics: a restarting job with a completed
            # checkpoint scales DOWN to the available slots rather than
            # waiting (Executing->Restarting->Executing with lower
            # parallelism, scheduler/adaptive/AdaptiveScheduler.java:192);
            # state re-shards by key-group range on restore
            # stage-split jobs cannot rescale: shard index = stage index
            # (their snapshots are per-stage runtimes, not key-group state)
            if not (self.adaptive and slots and job.completed
                    and job.status == "RESTARTING" and job.stages == 1):
                return  # WaitingForResources
            job.parallelism = len(slots)
        elif (self.adaptive and job.status == "RESTARTING" and job.completed
              and job.stages == 1 and len(slots) > job.parallelism):
            job.parallelism = min(len(slots), job.requested_parallelism)
        restore = None
        restore_step = 0
        local_cp = None        # checkpoint id eligible for task-local restore
        if job.completed:
            cp_id, handles, step = job.completed[-1]
            restore, restore_step = handles, step
            local_cp = cp_id
            if set(handles) != set(range(job.parallelism)):
                # parallelism changed since the checkpoint: re-shard
                try:
                    merged = merge_shard_snapshots(handles)
                except ValueError:
                    # unmergeable (device) snapshots: keep the checkpointed
                    # parallelism and wait for enough slots instead
                    job.parallelism = len(handles)
                    if len(slots) < job.parallelism:
                        return
                    merged = None
                if merged is not None:
                    # pre-split per shard: shipping the whole merged state
                    # to every shard would serialize ~parallelism copies
                    # of the job state over the deploy RPCs
                    restore = key_groups.split_merged_snapshot(
                        merged, job.spec_max_parallelism, job.parallelism)
                    local_cp = None  # re-sharded state has no local copy
        job.attempt += 1
        job.assignment = {shard: slots[shard] for shard in range(job.parallelism)}
        peers = {
            shard: self._tms[tm]["exchange"] for shard, tm in job.assignment.items()
        }
        job.finished = {}
        job.steps = {}
        job.progress = {}   # watchdog stamps belong to the dead attempt
        # the new attempt gets its full tolerable-failed-checkpoints
        # budget — carrying an exhausted streak over would re-fail the
        # restarted job on its first isolated persist hiccup
        job.consecutive_cp_failures = 0
        # drop the dead attempt's shipped snapshots: after a rescale-down a
        # stale higher-shard snapshot would keep inflating the aggregates
        # (and the autoscaler's signals) forever
        job.metric_snapshots.clear()
        job.pending.clear()
        job.pending_target.clear()
        # in-flight savepoints belong to the dead attempt: report them as
        # failed (the stale attempt's decline/ack can never complete them)
        for path, _m in job.savepoint_paths.values():
            job.failed_savepoints.append(
                f"{path}: job restarted before the cut completed")
        job.savepoint_paths.clear()
        origins = job.cp_origins.get(local_cp, {}) if local_cp is not None else {}
        restored_cp = job.completed[-1][0] if job.completed else None
        t_deploy = time.perf_counter()
        for shard, tm_id in job.assignment.items():
            # local recovery: a shard redeployed onto the TM that produced
            # its snapshot restores from the TM-local copy — the snapshot is
            # not re-shipped over the wire
            use_local = local_cp is not None and origins.get(shard) == tm_id
            try:
                self._tms[tm_id]["gateway"].deploy_task(
                    job.job_id, job.attempt, shard, job.parallelism, job.blob_key,
                    self.rpc.address, peers,
                    None if use_local else (restore[shard] if restore else None),
                    restore_step,
                    local_cp if use_local else None,
                )
            except Exception:
                # undetected-dead worker: evict it, cancel the partial
                # attempt, go back to WaitingForResources. If this deploy
                # was a deliberate rescale it has degraded into a plain
                # restart (which may land at a different parallelism):
                # the later redeploy must not stamp a rescale completion
                # for a shape change that never took effect
                job.rescale_started = None
                self._tms.pop(tm_id, None)
                self.heartbeats.unmonitor(tm_id)
                self._cancel_tasks(job)
                job.status = "RESTARTING"
                return
        job.status = "RUNNING"
        # recovery timeline: the attempt is live again — rewound checkpoint
        # id, restore (redeploy) duration, rewind depth in steps, and
        # downtime measured fail -> RUNNING. A restart with no completed
        # checkpoint replays from scratch (restored_cp None). The savepoint-
        # seeded first schedule records the restore but has no open
        # recovery, so complete_recovery is a no-op there.
        restore_ms = (time.perf_counter() - t_deploy) * 1000.0
        if restore is not None:
            job.stats.report_restore(restored_cp, restore_ms)
        job.exceptions.complete_recovery(
            restored_checkpoint_id=restored_cp,
            restore_duration_ms=restore_ms,
            restored_step=restore_step,
        )
        if job.rescale_started is not None:
            # deliberate rescale complete: stamp decision-to-RUNNING
            # duration (lastRescaleDurationMs) and restart the autoscaler's
            # stabilization window from completion time
            job.last_rescale_duration_ms = (
                time.perf_counter() - job.rescale_started) * 1000.0
            job.rescale_started = None
            if self.autoscaler is not None:
                # target disambiguates: a manual rescale_job RPC also
                # lands here, and its duration must not stamp a pending
                # coordinator decision for a different parallelism
                self.autoscaler.rescale_completed(
                    job.job_id, job.last_rescale_duration_ms,
                    target=job.parallelism)

    def _cancel_tasks(self, job: _JobState) -> None:
        for tm_id in set(job.assignment.values()):
            tm = self._tms.get(tm_id)
            if tm is not None:
                try:
                    tm["gateway"].cancel_task(job.job_id)
                except Exception as e:
                    _swallow("cancel_tasks", e)

    def _fail_job(self, job: _JobState, reason: str,
                  task: Optional[str] = None,
                  task_manager: Optional[str] = None) -> None:
        job.exceptions.record_failure(
            reason, task=task, task_manager=task_manager,
            restart_number=job.restarts)
        # in-flight checkpoints belong to the dead attempt: their acks can
        # never complete, so their stat records flip to FAILED now
        for cp_id in list(job.pending):
            job.stats.report_failed(cp_id, f"job failure: {reason}",
                                    benign=True)
        self._cancel_tasks(job)
        if job.restarts >= self.restart_attempts:
            job.status = "FAILED"
            self._release_job_local_state(job)
            return
        job.restarts += 1
        job.status = "RESTARTING"
        job.exceptions.begin_recovery(
            job.restarts, cause=reason,
            steps_at_failure=max(job.steps.values(), default=0))
        self._job_span(job, "recovery", "JobRestart", time.time() * 1000.0,
                       attempt=job.restarts, cause=reason[:200])

        def delayed():
            time.sleep(self.restart_delay)
            self.run_in_main_thread(self._try_schedule, job)

        threading.Thread(target=delayed, daemon=True,
                         name=f"restart-delay-{job.job_id[:6]}").start()

    # ---- task callbacks ---------------------------------------------------
    def _release_job_local_state(self, job: _JobState) -> None:
        """Best-effort: tell every TM to drop its task-local snapshot copies
        for a terminally finished job (the copies exist only for recovery)."""
        def _release(gateways=[tm["gateway"] for tm in self._tms.values()],
                     job_id=job.job_id):
            for gw in gateways:
                try:
                    gw.release_job_state(job_id)
                except Exception as e:
                    _swallow("release_job_state", e)

        # off the JM main thread: the TM handler is one-directional, but a
        # dead TM's connect timeout must not stall scheduling
        threading.Thread(target=_release, daemon=True,
                         name=f"release-state-{job.job_id[:6]}").start()

    def task_finished(self, job_id: str, attempt: int, shard: int, results: list) -> None:
        job = self._jobs.get(job_id)
        if job is None or attempt != job.attempt or job.status != "RUNNING":
            # the attempt guard misses a cancelled-but-racing task of the
            # CURRENT attempt (rescale/restart cancels first, bumps the
            # attempt only at redeploy) — a finish landing then must not
            # flip a RESCALING/RESTARTING job to FINISHED
            return
        job.finished[shard] = results
        # abort in-flight checkpoints this shard never snapshotted: a
        # finished task can never ack, so the pending entry would hang
        # forever (reference pre-FLIP-147 behavior: no checkpoints once a
        # task finishes; savepoints report failure instead of hanging)
        for cp_id in [c for c, p in job.pending.items() if shard not in p]:
            self.decline_checkpoint(
                job_id, attempt, shard, cp_id,
                f"shard {shard} finished before snapshotting")
        if len(job.finished) == job.parallelism:
            job.status = "FINISHED"
            self._release_job_local_state(job)

    def task_failed(self, job_id: str, attempt: int, shard: int, error: str) -> None:
        job = self._jobs.get(job_id)
        if job is None or attempt != job.attempt or job.status != "RUNNING":
            return
        self._fail_job(job, f"shard {shard}: {error}",
                       task=f"shard-{shard}",
                       task_manager=job.assignment.get(shard))

    # ---- checkpoint coordination (S7 analogue, step-aligned) -------------
    def trigger_savepoint(self, job_id: str, path: str) -> Optional[int]:
        """User-requested savepoint (CheckpointCoordinator savepoint
        analogue): rides the normal trigger/align/ack machinery; on
        completion the snapshot set is ALSO written to `path` (durable,
        user-owned, never subsumed). Async: poll job_status()'s
        'savepoints' for the written path. The target step is computed
        from heartbeat-stale progress, so a fast job can outrun it —
        declines re-trigger automatically with a doubled margin until the
        cut lands (or the job ends)."""
        job = self._jobs.get(job_id)
        if job is None or job.status != "RUNNING":
            return None
        cp_id = self.trigger_checkpoint(job_id, for_savepoint=True)
        if cp_id is not None:
            job.savepoint_paths[cp_id] = (path, 2)
        return cp_id

    def trigger_checkpoint(self, job_id: str, for_savepoint: bool = False,
                           margin: int = 2) -> Optional[int]:
        job = self._jobs.get(job_id)
        if job is None or job.status != "RUNNING":
            return None
        if self._storage is None and not for_savepoint:
            return None   # periodic checkpoints need configured storage;
            #               savepoints carry their own target directory
        if len(job.steps) < job.parallelism:
            return None
        if job.finished:
            # a finished shard can never snapshot; a new trigger would
            # only be aborted by task_finished's own guard anyway
            return None
        if job.stages > 1:
            # aligned-barrier checkpoint (CheckpointBarrier analogue): the
            # trigger goes to the SOURCE stages only; they snapshot at
            # their next step boundary and emit barriers into the
            # exchanges, downstream stages align, snapshot, forward, ack.
            # All target TMs are resolved BEFORE allocating the cp: a
            # half-delivered trigger would emit barriers that a
            # multi-input downstream stage could never align.
            if not job.source_stages:
                return None
            gws = {}
            for shard in job.source_stages:
                tm = self._tms.get(job.assignment.get(shard))
                if tm is None:
                    return None
                gws[shard] = tm["gateway"]
            cp_id = job.next_checkpoint_id
            job.next_checkpoint_id += 1
            job.pending[cp_id] = {}
            job.pending_target[cp_id] = max(job.steps.values())
            trig_t0 = time.time() * 1000.0
            job.stats.report_pending(cp_id, is_savepoint=for_savepoint,
                                     trigger_ts_ms=trig_t0)
            with trace_context(job.trace_id):
                for shard, gw in gws.items():
                    # margin is honored for symmetry with the keyed branch,
                    # but staged source gates CONSUME past-target requests
                    # at their next step boundary instead of declining them
                    # (the barrier defines the cut, not the step number), so
                    # staged savepoints never outrun-decline and never need
                    # the doubled-margin retry loop
                    gw.trigger_checkpoint(
                        job.job_id, job.attempt, cp_id,
                        job.steps.get(shard, 0) + margin, shard,
                    )
            self._job_span(job, "checkpointing", "CheckpointTrigger",
                           trig_t0, checkpointId=cp_id)
            return cp_id
        gws2 = {}
        for shard, tm_id in job.assignment.items():
            tm = self._tms.get(tm_id)
            if tm is None:
                return None
            gws2[shard] = tm["gateway"]
        cp_id = job.next_checkpoint_id
        job.next_checkpoint_id += 1
        # the cut must land at ONE common step across shards; heartbeat
        # staleness means fast jobs may already be past it — margin covers
        # the lag (savepoint declines re-trigger with a doubled margin)
        target = max(job.steps.values()) + margin
        job.pending[cp_id] = {}
        job.pending_target[cp_id] = target
        trig_t0 = time.time() * 1000.0
        job.stats.report_pending(cp_id, is_savepoint=for_savepoint,
                                 trigger_ts_ms=trig_t0)
        with trace_context(job.trace_id):
            for shard, gw in gws2.items():
                gw.trigger_checkpoint(job.job_id, job.attempt, cp_id, target,
                                      shard)
        self._job_span(job, "checkpointing", "CheckpointTrigger",
                       trig_t0, checkpointId=cp_id)
        return cp_id

    @absorbs_faults('savepoint write failure is recorded in job.failed_savepoints and reported; re-raising on the RPC thread would kill the JM endpoint, not surface the checkpoint failure')
    def ack_checkpoint(self, job_id: str, attempt: int, shard: int,
                       checkpoint_id: int, snapshot: dict) -> None:
        job = self._jobs.get(job_id)
        if job is None or attempt != job.attempt:
            return
        pending = job.pending.get(checkpoint_id)
        if pending is None:
            return
        pending[shard] = snapshot
        # per-task ack record: latency from the trigger timestamp + the
        # shard snapshot's in-memory footprint (the persisted artifact is
        # the whole set, sized below)
        job.stats.report_ack(checkpoint_id, f"shard-{shard}",
                             state_size_bytes=snapshot_bytes_estimate(snapshot))
        if len(pending) == job.parallelism:
            handles = job.pending.pop(checkpoint_id)
            step = job.pending_target.pop(checkpoint_id)
            persist_ms = None
            state_bytes = None
            if self._storage is not None:
                t_save = time.perf_counter()
                try:
                    self._storage.save(
                        checkpoint_id,
                        {"job": job_id, "shards": handles, "step": step}
                    )
                except BaseException as e:  # noqa: BLE001 — record; tolerate
                    # or fail over per tolerable-failed-checkpoints
                    # the entry already left job.pending, so _fail_job's
                    # pending sweep can never reach it — flip it here or the
                    # record stays PENDING forever (local-path _abort parity)
                    job.stats.report_failed(
                        checkpoint_id, f"persist failed: {e!r}")
                    if not isinstance(e, Exception) \
                            or isinstance(e, _chaos.InjectedCrash):
                        # interpreter-level exceptions and chaos crash
                        # faults are never "a tolerated brownout" — they
                        # must reach the failure machinery (plan.py's
                        # InjectedCrash contract)
                        raise
                    sp_fail = job.savepoint_paths.pop(checkpoint_id, None)
                    if sp_fail is not None:
                        job.failed_savepoints.append(
                            f"{sp_fail[0]}: persist failed: {e!r}")
                    job.consecutive_cp_failures += 1
                    if (job.consecutive_cp_failures
                            > self.tolerable_failed_checkpoints):
                        # beyond tolerance: restart through the normal
                        # attributed path (the JM owns the persist — the
                        # acking task did nothing wrong, so the failure is
                        # handled here instead of re-raising into its RPC)
                        self._fail_job(
                            job,
                            f"checkpoint {checkpoint_id} persist failed "
                            f"({job.consecutive_cp_failures} consecutive, "
                            f"tolerable "
                            f"{self.tolerable_failed_checkpoints}): {e!r}")
                        return
                    # tolerated brownout: the job keeps running; the next
                    # periodic trigger retries with a fresh checkpoint id
                    return
                persist_ms = (time.perf_counter() - t_save) * 1000.0
                state_bytes = self._storage.last_save_bytes
                self._job_span(job, "checkpointing", "CheckpointPersist",
                               time.time() * 1000.0 - persist_ms,
                               checkpointId=checkpoint_id,
                               stateSizeBytes=state_bytes)
            sp = job.savepoint_paths.pop(checkpoint_id, None)
            if sp is not None:
                # the checkpoint is complete regardless of the savepoint
                # write: a bad user path must not fail the acking task (and
                # thereby the healthy job)
                sp_path, _margin = sp
                try:
                    FsCheckpointStorage(sp_path).save(
                        checkpoint_id,
                        {"job": job_id, "shards": handles, "step": step,
                         "savepoint": True},
                    )
                    job.completed_savepoints.append(sp_path)
                except OSError as e:
                    job.failed_savepoints.append(
                        f"{sp_path}: {e}")
            job.consecutive_cp_failures = 0   # tolerance is CONSECUTIVE
            job.completed.append((checkpoint_id, handles, step))
            # per-operator breakdown from the stateBytes gauges the TMs
            # already ship on the heartbeat (latest snapshot per shard)
            per_op: Dict[str, int] = {}
            for snap_metrics in job.metric_snapshots.values():
                operator_bytes_from_snapshot(snap_metrics, into=per_op)
            job.stats.report_completed(
                checkpoint_id,
                async_duration_ms=persist_ms,
                state_size_bytes=state_bytes,
                operator_bytes=per_op,
            )
            self._job_span(job, "checkpointing", "CheckpointComplete",
                           time.time() * 1000.0, checkpointId=checkpoint_id,
                           status="COMPLETED", step=step)
            # local recovery (S11): remember which TM produced each shard's
            # snapshot, so a redeploy to the same TM can restore from its
            # task-local copy (TaskLocalStateStoreImpl analogue)
            job.cp_origins[checkpoint_id] = dict(job.assignment)
            # retain a bounded history in JM memory (durable copies live in
            # checkpoint storage); discard superseded ones
            while len(job.completed) > 3:
                old_id, _, _ = job.completed.pop(0)
                job.cp_origins.pop(old_id, None)
                if self._storage is not None:
                    self._storage.discard(old_id)

    def fetch_shard_restore(self, job_id: str, checkpoint_id: int, shard: int) -> dict:
        """Local-recovery fallback: a TM whose task-local copy is missing
        pulls the shard snapshot from the JM's retained checkpoints."""
        job = self._jobs.get(job_id)
        if job is not None:
            for cp_id, handles, _step in job.completed:
                if cp_id == checkpoint_id and shard in handles:
                    return handles[shard]
        raise KeyError(
            f"no retained snapshot for job {job_id} cp {checkpoint_id} shard {shard}"
        )

    def decline_checkpoint(self, job_id: str, attempt: int, shard: int,
                           checkpoint_id: int, reason: str) -> None:
        job = self._jobs.get(job_id)
        if job is not None and attempt == job.attempt:
            if job.pending.pop(checkpoint_id, None) is not None:
                job.stats.report_failed(
                    checkpoint_id, f"declined by shard {shard}: {reason}",
                    benign=True)   # outrun declines retry by design
            job.pending_target.pop(checkpoint_id, None)
            sp = job.savepoint_paths.pop(checkpoint_id, None)
            if sp is None:
                return
            path, margin = sp
            if job.status == "RUNNING" and reason.startswith("at step"):
                # the job outran the target step: retry the savepoint with
                # a doubled margin until the common cut lands
                new_cp = self.trigger_checkpoint(
                    job_id, for_savepoint=True,
                    margin=min(margin * 2, 1 << 14))
                if new_cp is not None:
                    job.savepoint_paths[new_cp] = (path, margin * 2)
                    return
            # permanent (a task finished / job no longer running): report
            # instead of re-triggering at RPC speed forever
            job.failed_savepoints.append(f"{path}: {reason}")

    @absorbs_faults("checkpoint trigger timer: a failed trigger is logged and retried next interval; the coordinator's decline/timeout path owns checkpoint-failure semantics")
    def _checkpoint_loop(self) -> None:
        while True:
            time.sleep(self.checkpoint_interval)
            for job_id, job in list(self._jobs.items()):
                if job.status == "RUNNING":
                    try:
                        self.run_in_main_thread(self.trigger_checkpoint, job_id).result()
                    except Exception as e:
                        _swallow("checkpoint_loop", e)


# ---------------------------------------------------------------------------
# TaskExecutor
# ---------------------------------------------------------------------------

class _ShardTask:
    """One running shard: the stepped source→shuffle→window loop."""

    def __init__(self, te: "TaskExecutorEndpoint", job_id: str, attempt: int,
                 shard: int, parallelism: int, spec: DistributedJobSpec,
                 jm_gateway, peers: Dict[int, str], restore: Optional[dict],
                 restore_step: int, restore_local_cp: Optional[int] = None):
        self.te = te
        self.job_id = job_id
        self.attempt = attempt
        self.shard = shard
        self.parallelism = parallelism
        self.spec = spec
        self.jm = jm_gateway
        self.peers = peers
        self.restore = restore
        self.restore_step = restore_step
        self.restore_local_cp = restore_local_cp
        self.cancelled = threading.Event()
        self.done = threading.Event()
        self.current_step = restore_step
        self._cp_requests: List[Tuple[int, int]] = []   # (cp_id, target_step)
        self._cp_lock = threading.Lock()
        # observability: per-task metric registry (shipped to the JM on the
        # heartbeat) and span buffer. The correlation id is DERIVED from the
        # job id — the id already rides every RPC frame of this job, so JM
        # and TM agree on the trace id with zero extra context shipping.
        self.registry = MetricRegistry()
        self.spans: List[dict] = []
        self._span_lock = threading.Lock()
        self.trace_id = job_trace_id(job_id)
        # trace ctx the JM's trigger RPC carried, per checkpoint id (equals
        # the derived id in practice; kept separate so a caller-supplied
        # context always wins, as with a real traceparent header)
        self._cp_trace: Dict[int, str] = {}
        self.thread = threading.Thread(
            target=self._run_safe, daemon=True,
            name=f"task-{job_id[:6]}-a{attempt}-s{shard}",
        )

    def start(self) -> None:
        self.thread.start()

    def record_span(self, scope: str, name: str, start_ms: float, **attrs) -> None:
        """Buffer one span (plain dict) for the next heartbeat shipment.
        Checkpoint spans prefer the trace ctx their trigger RPC carried."""
        attrs.setdefault("jobId", self.job_id)
        attrs.setdefault("shard", self.shard)
        tid = self._cp_trace.get(attrs.get("checkpointId"), self.trace_id)
        with self._span_lock:
            self.spans.append(Span(scope, name, start_ms, time.time() * 1000.0,
                                   attrs, trace_id=tid).to_dict())
            del self.spans[:-256]

    def _wire_emission_spans(self, rt) -> None:
        """Outlier EmissionStall spans from this task's windowed operators
        ride the heartbeat span buffer (record_span) to the JM's span feed
        exactly like checkpoint-ack spans — the distributed half of the
        /jobs/:id/latency stall attribution (the MiniCluster half wires
        the TraceRegistry in JobRuntime instead)."""
        for r in rt.runners:
            t = getattr(r, "emission_tracker", None)
            if t is not None and t.span_sink is None:
                t.span_sink = (lambda scope, name, s, e, a, _self=self:
                               _self.record_span(scope, name, s, **a))

    def drain_spans(self) -> List[dict]:
        """Atomically take the buffered spans (heartbeat shipping); the
        caller re-inserts on a failed shipment (restore_spans)."""
        with self._span_lock:
            out, self.spans = self.spans, []
        return out

    def restore_spans(self, spans: List[dict]) -> None:
        with self._span_lock:
            self.spans[:0] = spans
            del self.spans[:-256]

    def request_checkpoint(self, cp_id: int, target_step: int,
                           trace_id: Optional[str] = None) -> None:
        if trace_id is not None:
            self._cp_trace[cp_id] = trace_id
            if len(self._cp_trace) > 64:
                for k in sorted(self._cp_trace)[:-64]:
                    self._cp_trace.pop(k, None)
        with self._cp_lock:
            if not self.done.is_set():
                self._cp_requests.append((cp_id, target_step))
                return
        # The task loop has exited: a queued request would never be
        # processed, leaving the JM's pending entry dangling forever —
        # decline on the task's behalf. The decline must NOT run inline:
        # request_checkpoint executes on the TM endpoint main thread while
        # the JM main thread is blocked in its trigger RPC to us, so a
        # synchronous jm.decline_checkpoint here is a circular RPC wait
        # (JM-main -> TM-main -> JM-main) that deadlocks both processes.
        @absorbs_faults("best-effort decline for an already-finished task; the JM's checkpoint timeout covers a lost decline")
        def _decline():
            try:
                self.jm.decline_checkpoint(
                    self.job_id, self.attempt, self.shard, cp_id,
                    "task already finished",
                )
            except Exception as e:
                _swallow("decline_after_finish", e)

        threading.Thread(target=_decline, daemon=True,
                         name=f"cp-decline-{self.job_id[:6]}-s{self.shard}").start()

    def _resolve_local_restore(self) -> None:
        """Local recovery (S11): restore from the TM-local copy of the
        snapshot this shard acked — nothing re-ships over the wire. Runs on
        the task thread, NOT deploy_task (which executes on the TM main
        thread while the JM main thread awaits the deploy reply — a
        synchronous JM fetch there would be a circular RPC)."""
        if self.restore is not None or self.restore_local_cp is None:
            return
        local = self.te._local_state.get((self.job_id, self.shard))
        if local is not None and local[0] == self.restore_local_cp:
            self.restore = local[1]
            self.te.num_local_restores += 1
        else:
            # local copy lost (e.g. the TM process restarted): pull the
            # shard snapshot from the JM's retained checkpoints
            self.restore = self.jm.fetch_shard_restore(
                self.job_id, self.restore_local_cp, self.shard
            )

    @absorbs_faults('stage failover boundary: the failure is reported to the JM as task FAILED and rides the normal restart path — which is exactly where the chaos contract routes injected faults')
    def _run_graph_stage(self) -> None:
        """One stage of a slot-sharing-group-split StepGraph (this task's
        shard index = stage index). The stage's sub-graph runs as a normal
        JobRuntime; cross-stage edges are exchange channels (stages.py), so
        the stages of the job execute CONCURRENTLY as a pipeline with
        credit backpressure — the PIPELINED-result-partition analogue.

        Checkpoints use aligned barriers (stages.py module docstring): the
        JM trigger is this stage's '__source__' barrier (consumed at a step
        boundary); channel barriers arrive inline with data; when the
        aligner completes, the snapshot is taken ON the run-loop thread,
        barriers are forwarded into every out-channel, and the JM is
        acked. Restore = per-stage snapshot + source rewind; FIFO channels
        mean no channel state is part of the cut."""
        from flink_tpu.config import ExchangeOptions
        from flink_tpu.metrics.exchange import register_channel_metrics
        from flink_tpu.runtime.dataplane import BatchDebloater, OutputChannel
        from flink_tpu.runtime.executor import (
            JobCancelledException,
            JobRuntime,
            SinkRunner,
        )
        from flink_tpu.runtime.stages import (
            BarrierAligner,
            build_stage_graph,
            cross_edges,
            stage_has_original_sources,
        )

        cfg = self.spec.config
        wire_fmt = cfg.get(ExchangeOptions.WIRE_FORMAT)
        stage_idx = self.shard
        edges = cross_edges(self.spec.graph)
        ins: Dict[str, object] = {}
        outs: Dict[str, OutputChannel] = {}
        out_order: List[str] = []
        debloaters: Dict[str, BatchDebloater] = {}
        for e in edges:
            cid = f"{self.job_id}/a{self.attempt}/{e.edge_id}"
            if e.dst_stage == stage_idx:
                ins[e.edge_id] = self.te.exchange.channel(cid)
            if e.src_stage == stage_idx:
                outs[e.edge_id] = OutputChannel(
                    self.peers[e.dst_stage], cid,
                    security=self.te.exchange.security,
                    wire_format=wire_fmt)
                out_order.append(e.edge_id)
                if cfg.get(ExchangeOptions.DEBLOAT_ENABLED):
                    debloaters[e.edge_id] = BatchDebloater(
                        target_latency_s=cfg.get(
                            ExchangeOptions.DEBLOAT_TARGET_LATENCY_MS) / 1000.0)
        # input-side ring occupancy (inPoolUsage analogue): persistently
        # full = THIS stage is the bottleneck, empty = starved by upstream;
        # per-channel byte counters/rates on both ends (numBytesIn/Out)
        exch_group = self.registry.group("job", "exchange")
        for eid, ch in ins.items():
            exch_group.gauge(f"inPoolUsage.{eid}", ch.occupancy, fold="mean")
            register_channel_metrics(exch_group, eid, inbound=ch)
        for eid, och in outs.items():
            register_channel_metrics(exch_group, eid, outbound=och)

        task = self
        rt_box: list = [None]

        def on_aligned(cp_id: int) -> None:
            ack_t0 = time.time() * 1000.0
            rt = rt_box[0]
            snap = {"runtime": rt.capture(), "step": task.current_step}
            for eid in out_order:                 # forward BEFORE new data
                while True:      # backpressure-tolerant, cancellation-aware
                    try:
                        outs[eid].send(("barrier", cp_id), timeout=1.0)
                        break
                    except TimeoutError:
                        if task.cancelled.is_set():
                            raise JobCancelledException()
            task.te._local_state[(task.job_id, task.shard)] = (cp_id, snap)
            task.jm.ack_checkpoint(
                task.job_id, task.attempt, task.shard, cp_id, snap)
            task.record_span("checkpointing", "CheckpointAck", ack_t0,
                             checkpointId=cp_id)

        has_sources = stage_has_original_sources(self.spec.graph, stage_idx)
        aligner = BarrierAligner(list(ins), has_sources, on_aligned)

        graph = build_stage_graph(
            self.spec.graph, stage_idx, ins, outs, self.cancelled,
            aligner=aligner, debloaters=debloaters,
        )
        rt = JobRuntime(graph, self.spec.config, registry=self.registry)
        self._wire_emission_spans(rt)
        rt_box[0] = rt
        self._resolve_local_restore()
        if self.restore is not None:
            rt.restore(self.restore["runtime"])
            self.current_step = self.restore["step"]

        class _StepCounter:
            """Step progress for heartbeats + the '__source__' barrier: a
            JM trigger due at this step boundary enters the aligner (for a
            pure source stage that completes the alignment immediately)."""

            def register_on_complete(self, fn):
                pass

            def maybe_trigger(self, capture):
                task.current_step += 1
                if not has_sources:
                    return
                with task._cp_lock:
                    due = [r for r in task._cp_requests
                           if r[1] <= task.current_step]
                    task._cp_requests = [
                        r for r in task._cp_requests
                        if r[1] > task.current_step
                    ]
                for cp_id, _target in due:
                    aligner.on_barrier(BarrierAligner.SOURCE_GATE, cp_id)

        try:
            rt.run(coordinator=_StepCounter(),
                   cancel_check=lambda: self.cancelled.is_set())
        except JobCancelledException:
            return
        finally:
            for ch in outs.values():
                try:
                    ch.end()     # duplicate eos is harmless; frees receivers
                    ch.close()
                except Exception as e:
                    _swallow("stage_channel_close", e)
        if self.cancelled.is_set():
            return
        results: list = []
        for r in rt.runners:
            if isinstance(r, SinkRunner) and hasattr(r.writer, "store"):
                results.extend(r.writer.store)
        self.jm.task_finished(self.job_id, self.attempt, self.shard, results)

    def _run_graph(self) -> None:
        """One-task execution of a general StepGraph under cluster
        supervision: step-aligned checkpoint requests snapshot the whole
        JobRuntime (sources + every runner), failover restores it."""
        from flink_tpu.runtime.executor import (
            JobCancelledException,
            JobRuntime,
            SinkRunner,
        )

        rt = JobRuntime(self.spec.graph, self.spec.config,
                        registry=self.registry)
        self._wire_emission_spans(rt)
        self._resolve_local_restore()
        if self.restore is not None:
            rt.restore(self.restore["runtime"])
            self.current_step = self.restore["step"]

        task = self

        class _Coord:
            def __init__(self):
                self.on_complete = []

            def register_on_complete(self, fn):
                self.on_complete.append(fn)

            def maybe_trigger(self, capture):
                task.current_step += 1
                with task._cp_lock:
                    due = [r for r in task._cp_requests
                           if r[1] <= task.current_step]
                    task._cp_requests = [
                        r for r in task._cp_requests if r[1] > task.current_step
                    ]
                for cp_id, _target in due:
                    ack_t0 = time.time() * 1000.0
                    snap = {"runtime": capture(), "step": task.current_step}
                    task.te._local_state[(task.job_id, task.shard)] = (
                        cp_id, snap)
                    task.jm.ack_checkpoint(
                        task.job_id, task.attempt, task.shard, cp_id, snap)
                    task.record_span("checkpointing", "CheckpointAck",
                                     ack_t0, checkpointId=cp_id)
                    # single-shard job: the ack completes the checkpoint
                    # inside the JM before returning, so completion
                    # callbacks (2PC sink epoch commits) fire now
                    for fn in self.on_complete:
                        fn(cp_id)

        try:
            rt.run(coordinator=_Coord(),
                   cancel_check=lambda: self.cancelled.is_set())
        except JobCancelledException:
            return
        if self.cancelled.is_set():
            return
        results: list = []
        for r in rt.runners:
            if isinstance(r, SinkRunner) and hasattr(r.writer, "store"):
                results.extend(r.writer.store)
        self.jm.task_finished(self.job_id, self.attempt, self.shard, results)

    def _channel_id(self, src: int) -> str:
        return f"{self.job_id}/a{self.attempt}/{src}->{self.shard}"

    @absorbs_faults('task failover boundary: the exception is reported to the JM as task FAILED and rides the restart path; injected faults surfacing as task failure IS the chaos model')
    def _run_safe(self) -> None:
        try:
            self._run()
        except Exception as e:  # noqa: BLE001 — reported to the JM
            if not self.cancelled.is_set():
                try:
                    self.jm.task_failed(self.job_id, self.attempt, self.shard, repr(e))
                except Exception as e2:
                    _swallow("report_task_failed", e2)
        finally:
            # close the request_checkpoint race: anything still queued when
            # the loop exits is declined here, and everything arriving later
            # is declined inline by request_checkpoint (gated on `done`)
            with self._cp_lock:
                self.done.set()
                leftover, self._cp_requests = self._cp_requests, []
            for cp_id, target in leftover:
                try:
                    self.jm.decline_checkpoint(
                        self.job_id, self.attempt, self.shard, cp_id,
                        f"task exited before target step {target}",
                    )
                except Exception as e:
                    _swallow("decline_leftover", e)

    def _make_operator(self):
        from flink_tpu.ops.aggregators import resolve
        from flink_tpu.runtime.oracle_window_operator import OracleWindowOperator

        kg_range = key_group_range_for_operator(
            self.spec.max_parallelism, self.parallelism, self.shard
        )
        if self.spec.operator == "device":
            # imported only on the device path: pulls in jax (on a TPU host,
            # backend init claims the chip — oracle workers must not)
            from flink_tpu.api.windowing.assigners import (
                EventTimeSessionWindows,
            )

            if isinstance(self.spec.assigner, EventTimeSessionWindows) \
                    and self.spec.allowed_lateness == 0:
                # sessions scale past one chip the cluster way: each shard
                # owns a key-group range and runs its own device session
                # operator (sessions never cross keys, so no cross-shard
                # merge exists by construction). allowed_lateness falls
                # back to the oracle below — same gate as the single-node
                # operator selection. Sync emissions: the task loop drains
                # every step, so deferral would only disable the closable
                # precheck.
                from flink_tpu.runtime.tpu_session_operator import (
                    TpuSessionWindowOperator,
                )

                return TpuSessionWindowOperator(
                    self.spec.assigner, self.spec.aggregate,
                    **(self.spec.operator_options or {}),
                )
            if not isinstance(self.spec.assigner, EventTimeSessionWindows):
                from flink_tpu.runtime.tpu_window_operator import (
                    TpuWindowOperator,
                )

                return TpuWindowOperator(
                    self.spec.assigner, self.spec.aggregate,
                    allowed_lateness=self.spec.allowed_lateness,
                )
            # sessions WITH lateness: only the oracle implements the exact
            # late-merge semantics — fall through
        agg = resolve(self.spec.aggregate)
        return OracleWindowOperator(
            self.spec.assigner,
            agg.python_equivalent() if agg is not None else self.spec.aggregate,
            allowed_lateness=self.spec.allowed_lateness,
            max_parallelism=self.spec.max_parallelism,
            key_group_range=kg_range,
        )

    @absorbs_faults('per-record send/close handlers inside the task body feed the same failover boundary as _run_safe: failures surface as task FAILED and ride the restart path')
    def _run(self) -> None:
        if isinstance(self.spec, GraphJobSpec):
            from flink_tpu.runtime.stages import num_stages

            if num_stages(self.spec.graph) > 1:
                return self._run_graph_stage()
            return self._run_graph()
        from flink_tpu.config import ObservabilityOptions
        from flink_tpu.metrics.task_io import TaskIOMetrics

        # DistributedJobSpec carries no Configuration; honor the sampling
        # knob when a config rides the spec, else use the option default
        cfg = getattr(self.spec, "config", None)
        sampling_ms = (cfg.get(ObservabilityOptions.SAMPLING_INTERVAL_MS)
                       if cfg is not None
                       else ObservabilityOptions.SAMPLING_INTERVAL_MS.default)

        P = self.parallelism
        batches = self.spec.source_factory(self.shard, P)
        op = self._make_operator()
        # task-scope observability for the keyed hot path: throughput,
        # busy/idle/backPressured ratios (busy = partition/send + operator
        # sections; credit waits measured at the senders are subtracted;
        # cross-shard channel-merge polling is idle — the self-partition
        # never waits; checkpoint snapshot/ack time counts as neither, so
        # utilization tracks offered load, not checkpoint cost), plus the
        # window operator's HBM footprint / key cardinality gauges
        job_group = self.registry.group("job")
        records_in = job_group.counter("numRecordsIn")
        io = TaskIOMetrics()
        io.register(job_group)
        op_group = self.registry.group("job", "operator", "keyed-window")
        for gauge_name, attr in (("stateBytes", "state_bytes"),
                                 ("stateKeyCount", "state_key_count")):
            fn = getattr(op, attr, None)
            if fn is not None:
                op_group.gauge(gauge_name, fn, fold="sum")
        op_group.gauge("numLateRecordsDropped",
                       lambda: getattr(op, "num_late_records_dropped", 0),
                       fold="sum", kind="counter")
        # device-plane observability: compile tracking where the operator
        # exposes the attach surface (fused/sharded paths), key-skew
        # telemetry wherever per-key counts are device-resident. The
        # gauges ship to the JM on the heartbeat snapshots (job.device.*,
        # job.keySkew feeds scheduler/signals.py); compile events ride the
        # span buffer as 'device'-scope spans.
        key_stats = None
        O = ObservabilityOptions

        def _opt(option):
            return cfg.get(option) if cfg is not None else option.default

        if _opt(O.DEVICE_STATS_ENABLED):
            attach = getattr(op, "attach_device_stats", None)
            if attach is not None:
                from flink_tpu.metrics.device_stats import CompileTracker

                def _emit_compile_span(ev, task=self):
                    task.record_span(
                        "device", "XlaCompile",
                        ev["wall_ts_ms"] - ev["duration_ms"],
                        program=ev.get("program"),
                        signature=ev.get("signature"),
                        cause=ev.get("cause"),
                        recompile=bool(ev.get("recompile", False)),
                        compileCount=int(ev.get("compile_count", 1)),
                        durationMs=float(ev.get("duration_ms", 0.0)),
                    )

                tracker = CompileTracker(
                    history_size=_opt(O.DEVICE_RECOMPILE_HISTORY_SIZE),
                    storm_threshold=_opt(O.DEVICE_RECOMPILE_STORM_THRESHOLD),
                    storm_window_ms=_opt(O.DEVICE_RECOMPILE_STORM_WINDOW_MS),
                    cost_analysis=_opt(O.DEVICE_COST_ANALYSIS_ENABLED),
                    memory_analysis=_opt(O.DEVICE_MEMORY_ANALYSIS_ENABLED),
                    on_event=_emit_compile_span,
                )
                attach(tracker)
                tracker.register(self.registry.group("job", "device"))
            loads_fn = getattr(op, "key_loads", None)
            if loads_fn is not None:
                from flink_tpu.metrics.key_stats import KeyStatsCollector

                key_stats = KeyStatsCollector(
                    loads_fn,
                    num_key_groups=self.spec.max_parallelism,
                    top_k=_opt(O.DEVICE_KEY_STATS_TOP_K),
                    row_bytes_fn=getattr(op, "state_row_bytes", None),
                    ready_fn=getattr(op, "key_stats_ready", None),
                    interval_ms=_opt(O.DEVICE_KEY_STATS_INTERVAL_MS),
                    # mesh operators expose per-device local loads; the
                    # shipped {device: value} maps fold MAX across this
                    # shard's devices in aggregate_shard_metrics
                    mesh_loads_fn=(
                        getattr(op, "per_device_key_loads", None)
                        if getattr(op, "mesh_devices", lambda: 1)() > 1
                        else None),
                )
                key_stats.register(op_group)
                # the job-level gauge the autoscaler's signal extractor
                # reads (absent on builds without device stats — the
                # signal is OPTIONAL there, never implicit zero)
                job_group.gauge("keySkew", key_stats.skew, fold="max")
        results: list = []
        self._resolve_local_restore()
        if self.restore is not None:
            op_snap = self.restore["operator"]
            if self.restore.get("merged"):
                # rescaled restore: keep only timers whose key falls in this
                # shard's key-group range (state filters itself by range)
                kg_range = key_group_range_for_operator(
                    self.spec.max_parallelism, P, self.shard
                )
                op_snap = {
                    "state": op_snap["state"],
                    "timers": key_groups.filter_timers_for_range(
                        op_snap["timers"], kg_range,
                        self.spec.max_parallelism),
                }
            op.restore(op_snap)
            # the collect-sink is stateful: outputs emitted before the
            # checkpoint are part of the cut (post-checkpoint emissions of
            # the failed attempt are discarded and re-fired on replay)
            results.extend(self.restore.get("results", []))

        # output channels to every OTHER shard; the self-partition takes a
        # local fast path (a plain deque — producer and consumer are this
        # same thread, strictly send-then-poll per step). Riding the
        # loopback socket instead costs an encode/MAC/decode round trip
        # through the exchange thread per step, and under CPU saturation
        # that transit wait reads as idle — capping a saturated p=1 job's
        # utilization far below 1.0 and blinding the autoscaler.
        from flink_tpu.config import ExchangeOptions
        from flink_tpu.metrics.exchange import register_channel_metrics

        wire_fmt = (cfg.get(ExchangeOptions.WIRE_FORMAT) if cfg is not None
                    else ExchangeOptions.WIRE_FORMAT.default)
        reconnect_window_ms = (
            cfg.get(ExchangeOptions.RECONNECT_WINDOW_MS) if cfg is not None
            else ExchangeOptions.RECONNECT_WINDOW_MS.default)
        exch_metrics_group = self.registry.group("job", "exchange")
        self_parts: deque = deque()
        outs: Dict[int, OutputChannel] = {}
        for dst in range(P):
            if dst == self.shard:
                continue
            outs[dst] = OutputChannel(
                self.peers[dst], f"{self.job_id}/a{self.attempt}/{self.shard}->{dst}",
                security=self.te.exchange.security, wire_format=wire_fmt,
            )
            io.add_backpressure_source(
                lambda ch=outs[dst]: ch.backpressured_s)
            register_channel_metrics(exch_metrics_group, str(dst),
                                     outbound=outs[dst])
        ins = {src: self.te.exchange.channel(self._channel_id(src))
               for src in range(P) if src != self.shard}
        for src, ch in ins.items():
            job_group.gauge(f"exchange.inPoolUsage.{src}", ch.occupancy,
                            fold="mean")
            register_channel_metrics(exch_metrics_group, str(src), inbound=ch)
        job_group.gauge("numDataplaneReconnects", lambda: sum(
            ch.num_reconnects for ch in outs.values()),
            fold="sum", kind="counter")
        # liveness probe for the reconnect window: its OWN tight-timeout
        # gateway — the task's main jm gateway runs at the 120s payload
        # reply budget, and a peer_alive probe blocking that long on a
        # wedged JM would stretch the "bounded" reconnect window ~24x
        probe_timeout = max(min(reconnect_window_ms / 1000.0 / 2, 2.0), 0.5)
        probe_jm = RpcGateway(
            self.jm.address, "jobmanager", timeout=probe_timeout,
            security=self.te.rpc.security,
            # single attempt: the retry deadline (8s) would stretch the
            # reconnect window just like the payload reply budget; the
            # send_part loop is the retry policy here
            retry=RetryPolicy(max_attempts=1))

        def send_part(dst: int, part) -> None:
            """Transient-fault hardening on the keyed exchange: a send
            failing with a connection error gets a BOUNDED reconnect
            window (exchange.reconnect.window-ms) — but only while the JM
            confirms the peer TM is still heartbeating, and only when the
            re-run open/credit negotiation proves seq continuity (no frame
            lost). Anything else re-raises into the normal task-failure →
            checkpoint-rewind restart path. Credit-starvation TimeoutError
            is NOT a connection fault and never reconnects (a reconnect
            re-grants credits, which would tunnel through backpressure)."""
            try:
                outs[dst].send(part)
                return
            except _chaos.InjectedCrash:
                raise
            except TimeoutError:
                raise
            except OSError as first_err:
                if reconnect_window_ms <= 0:
                    raise
                deadline = time.monotonic() + reconnect_window_ms / 1000.0
                backoff = 0.05
                last_err = first_err
                while not self.cancelled.is_set():
                    if time.monotonic() >= deadline:
                        raise last_err
                    try:
                        alive = probe_jm.peer_alive(
                            self.job_id, self.attempt, dst)
                    except Exception as e:
                        _swallow("peer_alive_probe", e)
                        alive = True   # an unreachable JM is its own story
                    if not alive:
                        raise last_err   # real TM loss: fail over now
                    try:
                        outs[dst].reconnect()
                        outs[dst].send(part)
                        return
                    except TimeoutError:
                        raise
                    except SequenceLostError:
                        raise   # provably unrecoverable: re-dialing can
                        #         never heal a lost frame — fail over NOW
                    except OSError as e:
                        last_err = e
                        time.sleep(min(
                            backoff,
                            max(deadline - time.monotonic(), 0.0)))
                        backoff = min(backoff * 2, 1.0)
                raise last_err

        step = self.restore_step
        n_steps = len(batches)
        try:
            while not self.cancelled.is_set():
                # ---- step-aligned checkpoint barrier -----------------------
                # (snapshot/ack/persist time deliberately sits OUTSIDE the
                # busy accounting: utilization must track offered load, not
                # checkpoint cost — a result-heavy job checkpointing often
                # would otherwise read busy while idle and mislead the
                # autoscaler in both directions)
                with self._cp_lock:
                    due = [r for r in self._cp_requests if r[1] <= step]
                    self._cp_requests = [r for r in self._cp_requests if r[1] > step]
                for cp_id, target in due:
                    if target == step:
                        ack_t0 = time.time() * 1000.0
                        snap = {"operator": op.snapshot(), "step": step,
                                "results": list(results)}
                        # task-local state store (S11): keep the latest
                        # snapshot on this TM for cheap local recovery
                        self.te._local_state[(self.job_id, self.shard)] = (
                            cp_id, snap)
                        self.jm.ack_checkpoint(
                            self.job_id, self.attempt, self.shard, cp_id, snap
                        )
                        self.record_span("checkpointing", "CheckpointAck",
                                         ack_t0, checkpointId=cp_id)
                    else:  # already past the target: cannot form the cut
                        self.jm.decline_checkpoint(
                            self.job_id, self.attempt, self.shard, cp_id,
                            f"at step {step} > target {target}",
                        )

                if step >= n_steps:
                    break
                loop_t0 = time.perf_counter()
                keys, vals, ts, wm = batches[step]

                # ---- keyBy partition: bucket by owning shard ---------------
                busy_t0 = time.perf_counter()
                hashes = np.asarray([key_hash(k) for k in keys], dtype=np.int64)
                kgs = key_groups_for_hashes(hashes, self.spec.max_parallelism)
                owner = (kgs.astype(np.int64) * P) // self.spec.max_parallelism
                for dst in range(P):
                    m = owner == dst
                    part = (keys[m], vals[m], ts[m], int(wm), step)
                    if dst == self.shard:
                        self_parts.append(part)
                    else:
                        send_part(dst, part)
                busy_dt = time.perf_counter() - busy_t0

                # ---- merge one batch per input channel (min watermark) -----
                # (channel polling is the task's IDLE time — excluded from
                # busy; credit waits inside send() above are subtracted by
                # TaskIOMetrics via the senders' backpressured_s)
                parts = []
                wms = []
                for src in range(P):
                    if src == self.shard:
                        got = self_parts.popleft()   # sent above, same thread
                    else:
                        got = None
                        while True:  # short waits so cancellation stays responsive
                            try:
                                got = ins[src].poll(timeout=0.5)
                                break
                            except TimeoutError:
                                if self.cancelled.is_set():
                                    return
                        if got is None:
                            raise RuntimeError(
                                f"channel from shard {src} ended early")
                    k, v, t, w, s = got
                    assert s == step, f"step skew: got {s} expected {step}"
                    parts.append((k, v, t))
                    wms.append(w)
                busy_t0 = time.perf_counter()
                mk = np.concatenate([p[0] for p in parts])
                mv = np.concatenate([p[1] for p in parts])
                mt = np.concatenate([p[2] for p in parts])
                combined_wm = min(wms)
                records_in.inc(len(mk))

                if hasattr(op, "process_batch") and len(mk):
                    # columnar feeding for device operators: ONE batched
                    # ingest instead of a per-record python loop (the
                    # oracle has no batch form — sessions with lateness
                    # fall back to it even under operator='device')
                    op.process_batch(
                        mk, np.asarray(mv, dtype=np.float32),
                        np.asarray(mt, dtype=np.int64))
                else:
                    for i in range(len(mk)):
                        op.process_record(mk[i], float(mv[i]), int(mt[i]))
                if key_stats is not None:
                    # one clock compare when not due; a due fold runs
                    # BEFORE the watermark's purge sweep
                    key_stats.maybe_collect()
                if combined_wm > MIN_WATERMARK:
                    op.process_watermark(combined_wm)
                results.extend(op.drain_output())
                busy_dt += time.perf_counter() - busy_t0
                io.record_step(busy_dt, time.perf_counter() - loop_t0)
                io.maybe_sample(sampling_ms)

                step += 1
                self.current_step = step

            # checkpoints targeted past the end of the stream are declined
            # by the `done` drain in _run_safe's finally block
            if not self.cancelled.is_set():
                op.process_watermark(MAX_WATERMARK)
                results.extend(op.drain_output())
                out = [
                    (k, (w.start, w.end), r, t) for k, w, r, t in results
                ]
                self.jm.task_finished(self.job_id, self.attempt, self.shard, out)
        finally:
            for ch in outs.values():
                try:
                    ch.end()
                    ch.close()
                except Exception as e:
                    _swallow("channel_close", e)


class TaskExecutorEndpoint(RpcEndpoint):
    """TM RPC endpoint (D1 scope): deploy/cancel/checkpoint tasks."""

    def __init__(self, rpc: RpcService, *, tm_id: Optional[str] = None,
                 slots: int = 1, shipping_interval_ms: int = 500,
                 config=None):
        super().__init__(name="taskexecutor")
        self.tm_id = tm_id or f"tm-{uuid.uuid4().hex[:8]}"
        self.rpc = rpc
        self.slots = slots
        # observability.shipping.interval-ms: how often metric snapshots and
        # span buffers piggyback on the heartbeat
        self.shipping_interval_ms = shipping_interval_ms
        self._last_ship = 0.0
        # one SecurityConfig governs both of this TM's planes: the exchange
        # handshakes with the same cluster secret as the RPC service.
        # `config` (a Configuration, e.g. from the taskmanager's --conf)
        # sets the TM-level exchange knobs: what wire format this receiver
        # advertises and the credit-coalescing grain.
        exch_kw = {}
        if config is not None:
            from flink_tpu.config import ExchangeOptions

            exch_kw = dict(
                wire_format=config.get(ExchangeOptions.WIRE_FORMAT),
                credit_batch=config.get(ExchangeOptions.CREDIT_BATCH),
            )
        self.exchange = ExchangeServer(security=rpc.security, **exch_kw)
        self._tasks: Dict[Tuple[str, int, int], _ShardTask] = {}
        # task-local state store (S11): latest acked snapshot per (job, shard)
        self._local_state: Dict[Tuple[str, int], Tuple[int, dict]] = {}
        self.num_local_restores = 0
        self._jm_gateway = None
        self._blob: Optional[BlobCache] = None
        rpc.register(self)
        self._hb_thread: Optional[threading.Thread] = None
        self._hb_stop = threading.Event()

    def connect(self, jm_address: str) -> None:
        gw = self.rpc.gateway(jm_address, "jobmanager")
        self._jm_gateway = gw
        self._blob = BlobCache(self.rpc.gateway(
            jm_address, "blob", reply_timeout=PAYLOAD_REPLY_TIMEOUT_S))
        gw.register_task_executor(self.tm_id, self.rpc.address, self.exchange.address, self.slots)
        if self._hb_thread is None:
            self._hb_thread = threading.Thread(target=self._hb_loop, daemon=True,
                                               name=f"hb-{self.tm_id}")
            self._hb_thread.start()

    @absorbs_faults('heartbeat sender: a failed beat is retried next interval and the JM-side liveness timeout owns the death verdict; re-raising would kill the beat thread and falsify liveness')
    def _hb_loop(self) -> None:
        # beat at least every 0.5s (liveness), faster when the shipping
        # interval asks for fresher metric/step snapshots — a sub-500ms
        # observability.shipping.interval-ms was previously unreachable,
        # which left the autoscaler's signal windows up to one full beat
        # stale and starved fast-stepping jobs of checkpoint-target margin
        beat_s = min(0.5, max(self.shipping_interval_ms, 50) / 1000.0)
        # wait() not sleep(): a stopped endpoint's thread must exit — a
        # leaked loop keeps dialing the dead JM at up to 5 Hz forever
        # (real TM processes run until killed, but in-process tests stack
        # dozens of endpoints per run)
        while not self._hb_stop.wait(beat_s):
            try:
                steps = {
                    (t.job_id, t.shard, t.attempt): t.current_step
                    for t in self._tasks.values()
                    if not t.cancelled.is_set()
                }
                metrics = None
                spans = None
                drained: List[Tuple["_ShardTask", List[dict]]] = []
                now = time.monotonic()
                shipping = (now - self._last_ship) * 1000.0 \
                    >= self.shipping_interval_ms
                if shipping:
                    metrics = {}
                    spans = []
                    for t in list(self._tasks.values()):
                        if t.cancelled.is_set():
                            continue
                        snap = metrics_snapshot(t.registry.all_metrics())
                        if snap:
                            metrics[(t.job_id, t.shard, t.attempt)] = snap
                        sp = t.drain_spans()
                        if sp:
                            spans.extend(sp)
                            drained.append((t, sp))
                try:
                    self._jm_gateway.heartbeat_tm(self.tm_id, steps,
                                                  metrics, spans)
                except Exception:
                    # shipment failed: put the drained spans back for the
                    # next beat (bounded by the task buffer cap); _last_ship
                    # stays untouched so metrics re-ship on the next beat
                    # instead of waiting out another full interval
                    for t, sp in drained:
                        t.restore_spans(sp)
                    raise
                if shipping:
                    self._last_ship = now
            except Exception as e:
                _swallow("hb_loop", e)

    # ---- RPC methods ------------------------------------------------------
    def ping(self) -> str:
        return self.tm_id

    def deploy_task(self, job_id: str, attempt: int, shard: int, parallelism: int,
                    blob_key: str, jm_address: str, peers: Dict[int, str],
                    restore: Optional[dict], restore_step: int,
                    restore_local_cp: Optional[int] = None) -> bool:
        spec = DistributedJobSpec.from_bytes(self._blob.get(blob_key))
        # acks ship shard snapshots and block on the JM-side persist
        jm = self.rpc.gateway(jm_address, "jobmanager",
                              reply_timeout=PAYLOAD_REPLY_TIMEOUT_S)
        task = _ShardTask(self, job_id, attempt, shard, parallelism, spec, jm,
                          peers, restore, restore_step,
                          restore_local_cp=restore_local_cp)
        # superseded attempts can never be checkpointed or resumed: cancel
        # and drop them so restarts don't grow the task table without bound
        # (a still-running old-attempt thread would otherwise be unreachable
        # by cancel_task/stop once evicted)
        keep = {}
        for k, t in self._tasks.items():
            if k[0] == job_id and k[1] < attempt:
                t.cancelled.set()
            else:
                keep[k] = t
        self._tasks = keep
        self._tasks[(job_id, attempt, shard)] = task
        task.start()
        return True

    def trigger_checkpoint(self, job_id: str, attempt: int, cp_id: int,
                           target_step: int, shard: Optional[int] = None) -> bool:
        """Deliver a checkpoint request to this TM's task(s) of the job.
        `shard` addresses ONE task — required when a TM hosts several tasks
        of the job (fanning the request to co-located tasks would duplicate
        source barriers on multi-stage jobs); None keeps the legacy
        broadcast for old callers."""
        trace_id = current_trace_id()   # ctx the JM attached to this frame
        for (jid, att, sh), task in self._tasks.items():
            if jid == job_id and att == attempt and not task.cancelled.is_set() \
                    and (shard is None or sh == shard):
                task.request_checkpoint(cp_id, target_step, trace_id)
        return True

    def release_job_state(self, job_id: str) -> bool:
        """Drop task-local snapshot copies for a TERMINALLY finished job
        (sent by the JM on FINISHED/FAILED/CANCELED — failover cancels must
        NOT release, that is exactly when local recovery needs the copies)."""
        for key in [k for k in self._local_state if k[0] == job_id]:
            self._local_state.pop(key, None)
        return True

    def cancel_task(self, job_id: str) -> bool:
        for (jid, _att, _shard), task in self._tasks.items():
            if jid == job_id:
                task.cancelled.set()
        return True

    def stop(self) -> None:
        self._hb_stop.set()
        for task in self._tasks.values():
            task.cancelled.set()
        self.exchange.stop()
        super().stop()


# ---------------------------------------------------------------------------
# process entrypoints (M1 analogue: ClusterEntrypoint mains)
# ---------------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> None:
    """`python -m flink_tpu.runtime.cluster jobmanager|taskmanager ...`"""
    import argparse

    from flink_tpu.security.transport import SecurityConfig

    p = argparse.ArgumentParser(prog="flink_tpu.runtime.cluster")
    sub = p.add_subparsers(dest="role", required=True)
    jm = sub.add_parser("jobmanager")
    jm.add_argument("--host", default="127.0.0.1")
    jm.add_argument("--port", type=int, default=6123)
    jm.add_argument("--checkpoint-dir", default=None)
    jm.add_argument("--checkpoint-interval", type=float, default=0.0)
    tm = sub.add_parser("taskmanager")
    tm.add_argument("--jobmanager", required=True, help="host:port of the JM RPC service")
    tm.add_argument("--slots", type=int, default=1)
    for sp in (jm, tm):
        sp.add_argument(
            "--conf", default=None,
            help="configuration file (JSON or `key: value` subset); the "
                 "security.* option group resolves from it, layered under "
                 "FLINK_TPU_* env dynamic properties")
        sp.add_argument(
            "--secret-file", default=None,
            help="file holding the cluster transport secret "
                 "(default: FLINK_TPU_SECURITY_TRANSPORT_SECRET[_FILE] env, "
                 "else an auto-generated per-user secret)")
        sp.add_argument(
            "--cluster-id", default=None,
            help="handshake cluster identity (security.transport.cluster-id)")
        sp.add_argument(
            "--insecure", action="store_true",
            help="disable transport auth (legacy plaintext wire; local "
                 "debugging only)")
    args = p.parse_args(argv)

    if args.insecure:
        security = SecurityConfig.disabled()
    else:
        # layering: conf file (with env dynamic properties) is the base;
        # --secret-file/--cluster-id overlay it, so e.g. ssl.internal.*
        # from --conf still applies when the secret comes from a flag
        security = None   # process default: env > per-user secret file
        if args.conf:
            from flink_tpu.config import Configuration

            security = SecurityConfig.resolve(
                Configuration.load(args.conf).add_all(Configuration.from_env()))
        if args.secret_file or args.cluster_id:
            import dataclasses as _dc
            import os as _os

            from flink_tpu.security.transport import (
                ENV_CLUSTER_ID,
                _env_or_default_secret,
                _read_secret_file,
            )

            # the flag-less fields must match what env-only processes of
            # the same cluster resolve (_process_default), or a flag-started
            # JM and an env-started TM could never authenticate
            base = security if security is not None else SecurityConfig(
                enabled=True, secret=_env_or_default_secret(),
                cluster_id=_os.environ.get(ENV_CLUSTER_ID, "flink-tpu"))
            overlay = {}
            if args.secret_file:
                overlay["secret"] = _read_secret_file(args.secret_file)
            if args.cluster_id:
                overlay["cluster_id"] = args.cluster_id
            security = _dc.replace(base, enabled=True, **overlay)

    def _install_chaos_from_conf(conf) -> None:
        # chaos.* config group: a --conf-driven fault drill (default off).
        # Installed process-wide exactly once; every injected fault carries
        # the injected-attribution marker (docs/robustness.md).
        plan = _chaos.FaultPlan.from_config(conf)
        if plan is not None and _chaos.active_plan() is None:
            _chaos.install_plan(plan)
            print(f"chaos plane ENABLED: {len(plan.rules)} rule(s), "
                  f"seed {plan.seed}", flush=True)

    if args.role == "jobmanager":
        svc = RpcService(args.host, args.port, security=security)
        hist_kw = {}
        if args.conf:
            from flink_tpu.config import (
                CheckpointingOptions,
                Configuration,
                ObservabilityOptions,
                WatchdogOptions,
            )

            conf = Configuration.load(args.conf).add_all(Configuration.from_env())
            hist_kw = dict(
                checkpoint_history_size=conf.get(
                    ObservabilityOptions.CHECKPOINT_HISTORY_SIZE),
                exception_history_size=conf.get(
                    ObservabilityOptions.EXCEPTION_HISTORY_SIZE),
                # autoscaler.* group (scheduler/): enabled=false is inert
                autoscaler_config=conf,
                tolerable_failed_checkpoints=conf.get(
                    CheckpointingOptions.TOLERABLE_FAILED_CHECKPOINTS),
                stuck_task_timeout_ms=conf.get(
                    WatchdogOptions.STUCK_TASK_TIMEOUT_MS),
                # observability.history.* / observability.doctor.* group
                history_interval_ms=conf.get(
                    ObservabilityOptions.HISTORY_INTERVAL_MS),
                history_retention_points=conf.get(
                    ObservabilityOptions.HISTORY_RETENTION_POINTS),
                doctor_enabled=conf.get(
                    ObservabilityOptions.DOCTOR_ENABLED),
                doctor_window_ms=float(conf.get(
                    ObservabilityOptions.DOCTOR_WINDOW_MS)),
                watchdog_min_gap_ms=float(conf.get(
                    ObservabilityOptions.DOCTOR_WATCHDOG_MIN_GAP_MS)),
                p99_breach_ms=conf.get(
                    ObservabilityOptions.DOCTOR_P99_BREACH_MS),
            )
            _install_chaos_from_conf(conf)
        JobManagerEndpoint(
            svc,
            checkpoint_dir=args.checkpoint_dir,
            checkpoint_interval=args.checkpoint_interval,
            **hist_kw,
        )
        print(f"jobmanager listening on {svc.address}", flush=True)
    else:
        svc = RpcService(security=security)
        ship_ms = 500
        conf = None
        if args.conf:
            from flink_tpu.config import Configuration, ObservabilityOptions

            conf = Configuration.load(args.conf).add_all(Configuration.from_env())
            ship_ms = conf.get(ObservabilityOptions.SHIPPING_INTERVAL_MS)
            _install_chaos_from_conf(conf)
        te = TaskExecutorEndpoint(svc, slots=args.slots,
                                  shipping_interval_ms=ship_ms, config=conf)
        te.connect(args.jobmanager)
        print(f"taskmanager {te.tm_id} registered with {args.jobmanager} "
              f"(rpc {svc.address}, exchange {te.exchange.address})", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
