"""OperatorCoordinator SPI (D15).

The reference pairs job-scope coordinators with operators
(runtime/operators/coordination/OperatorCoordinator.java): the operator
sends OperatorEvents up, the coordinator reacts (and can send events back
down), and the coordinator's state rides checkpoints alongside the
operator's. Split enumerators are the flagship implementation there; here
enumerators already live with the source driver, and this module provides
the GENERIC event bus for user operators: a ProcessFunction (or any
operator function) that defines ``create_coordinator()`` gets one
coordinator instance per job, a gateway to reach it, and callbacks for
events the coordinator pushes back.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional


class OperatorCoordinator:
    """One coordinator instance per (job, operator uid)."""

    def start(self, context: "CoordinatorContext") -> None:
        """Called once before the job runs; keep the context for replies."""

    def handle_event(self, event: Any) -> None:
        """An OperatorEvent arrived from the operator."""

    def checkpoint(self) -> dict:
        """State to ride the job checkpoint (restored via restore())."""
        return {}

    def restore(self, snap: dict) -> None:
        pass

    def close(self) -> None:
        pass


class CoordinatorContext:
    """Coordinator-side handle: push events back to the operator."""

    def __init__(self, deliver: Callable[[Any], None]):
        self._deliver = deliver

    def send_to_operator(self, event: Any) -> None:
        self._deliver(event)


class CoordinatorGateway:
    """Operator-side handle: push events up to the coordinator.

    In this single-control-plane runtime delivery is a direct call on the
    job thread (the reference routes the same contract through RPC)."""

    def __init__(self, coordinator: OperatorCoordinator):
        self._coordinator = coordinator

    def send_event(self, event: Any) -> None:
        self._coordinator.handle_event(event)


def wire(fn: Any) -> Optional[OperatorCoordinator]:
    """If the operator function declares create_coordinator(), instantiate
    and wire the bidirectional event bus:

    - fn.coordinator_gateway.send_event(ev)  -> coordinator.handle_event
    - context.send_to_operator(ev) -> fn.handle_coordinator_event (if any)

    The coordinator is paired with the FUNCTION INSTANCE (idempotent): a
    second JobRuntime built over the same graph reuses the same
    coordinator rather than silently re-pointing the gateway — shared
    function objects mean shared coordinator state, exactly like shared
    operator state."""
    factory = getattr(fn, "create_coordinator", None)
    if factory is None:
        return None
    existing = getattr(fn, "_operator_coordinator", None)
    if existing is not None:
        return existing
    coordinator = factory()

    def deliver(event: Any) -> None:
        handler = getattr(fn, "handle_coordinator_event", None)
        if handler is not None:
            handler(event)

    coordinator.start(CoordinatorContext(deliver))
    fn.coordinator_gateway = CoordinatorGateway(coordinator)
    fn._operator_coordinator = coordinator
    return coordinator
