"""Data plane: record-batch exchange between task executors over TCP (DCN).

Within a TPU slice, keyBy shuffles ride ICI as compiled all-to-all
collectives (flink_tpu/parallel). ACROSS hosts, batches travel on this
plane — the DCN counterpart of the reference's Netty shuffle
(io/network/netty/**) with the same backpressure discipline:

- **Credit-based flow control** (CreditBasedPartitionRequestClientHandler.java:61,
  RemoteInputChannel.java:114): a receiving channel grants credits equal to
  its free ring slots; the sender spends one credit per batch and BLOCKS
  when out of credit — backpressure propagates to the producing step loop
  with no unbounded buffering, exactly the "no credit ⇒ no send ⇒ writer
  blocks on LocalBufferPool" chain of the reference.
- **Batch debloating** (runtime/throughput/BufferDebloater.java): senders
  size batches toward `target_latency x observed_throughput` with an EMA,
  trading latency for amortization the way buffer debloating resizes
  network buffers.

Wire (flink_tpu/security): the same handshake + MAC-signed framing as the
RPC plane. Control frames — ("open", channel, offered_formats) /
("credit", channel, n, chosen_format) / ("eos", channel) — and non-batch
payloads travel as restricted-pickle frames exactly as before. Record
BATCHES take the zero-copy binary columnar wire (security/wire.py): a
little-endian header + restricted-pickle sidecar + the raw array buffers,
sent with scatter-gather I/O and MACed incrementally, so a contiguous
numeric column crosses the host boundary without a single serialization
copy (the Netty zero-copy buffer-transfer analogue). The format is
negotiated per connection on the open/credit exchange, so an old-wire peer
transparently downgrades the channel to the legacy pickled
("data", channel, seq, payload) frames (`exchange.wire-format: pickle`
forces that everywhere). Frames are MAC-verified before deserialization
exactly like RPC frames; `security.transport.enabled: false` yields the
same binary wire without authentication.

Credit grants are COALESCED: the receiver banks freed ring slots and sends
one ("credit", ch, n) frame per `exchange.credit-batch` slots (default:
capacity/4) instead of one per consumed batch, quartering the control-frame
rate on the hot path without changing the blocking discipline — the sender
still stalls exactly when the receiver's ring is full.
"""

from __future__ import annotations

import socket
import socketserver
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from flink_tpu.chaos import plan as _chaos
from flink_tpu.lint.contracts import absorbs_faults
from flink_tpu.metrics.registry import Meter
from flink_tpu.security.framing import FrameAuthError, RestrictedUnpicklingError
from flink_tpu.security.transport import (
    SecurityConfig,
    client_handshake,
    recv_msg,
    recv_obj,
    send_data_frame,
    send_obj,
    server_handshake,
    validate_server_config,
    wrap_client_socket,
    wrap_server_socket,
)
from flink_tpu.security.wire import WireFormatError, extract_columns


class SequenceLostError(ConnectionError):
    """Raised by OutputChannel.reconnect() when the re-run open/credit
    negotiation proves a frame was LOST (receiver's next expected seq !=
    sender's): provably unrecoverable at this layer — callers must
    escalate to the checkpoint-rewind restart path immediately instead of
    burning the reconnect window on re-dials that can never heal it."""


def _validate_wire_format(wire_format: str) -> str:
    """exchange.wire-format must be exactly 'binary' or 'pickle': a typo
    silently negotiating the whole cluster down to the pickle wire would
    throw away the zero-copy speedup with no signal — fail at startup."""
    if wire_format not in ("binary", "pickle"):
        raise ValueError(
            f"exchange.wire-format must be 'binary' or 'pickle', "
            f"got {wire_format!r}"
        )
    return wire_format


class InputChannel:
    """Receiver side of one channel: a bounded ring of batches; consuming
    batches releases credits back to the sender in coalesced grants of
    `credit_batch` (banked freed slots), and every arriving frame must
    extend the sender's sequence contiguously — a dropped or reordered
    frame surfaces as a loud ConnectionError at poll(), never as silent
    corruption."""

    def __init__(self, channel_id: str, capacity: int,
                 grant: Callable[[int], None], credit_batch: int = 1):
        self.channel_id = channel_id
        self.capacity = capacity
        self._grant = grant
        self._credit_batch = max(1, min(credit_batch, capacity))
        self._pending_credits = 0
        self._ring: deque = deque()
        self._cv = threading.Condition()
        self._eos = False
        self._next_seq = 0
        self._error: Optional[Exception] = None
        self.bytes_in = 0
        self._in_meter = Meter()

    def _on_data(self, seq: int, payload, nbytes: int = 0) -> bool:
        """False when the frame breaks sequence contiguity — the server
        handler then drops the connection; consumers see the error on the
        next poll() once the ring's valid prefix is drained."""
        with self._cv:
            if seq != self._next_seq:
                self._error = ConnectionError(
                    f"channel {self.channel_id}: sequence gap (got seq {seq},"
                    f" expected {self._next_seq}) — a frame was dropped or"
                    " reordered in transit"
                )
                self._cv.notify_all()
                return False
            self._next_seq += 1
            self.bytes_in += nbytes
            self._in_meter.mark(nbytes)
            self._ring.append(payload)
            self._cv.notify_all()
        return True

    def _on_eos(self) -> None:
        with self._cv:
            self._eos = True
            self._cv.notify_all()

    def poll(self, timeout: Optional[float] = None):
        """Next batch, or None at end-of-stream."""
        grant_n = 0
        with self._cv:
            while not self._ring and not self._eos and self._error is None:
                if not self._cv.wait(timeout=timeout):
                    raise TimeoutError(f"channel {self.channel_id} starved")
            if self._ring:
                batch = self._ring.popleft()
                # bank the freed slot; one grant frame per credit_batch slots
                self._pending_credits += 1
                if self._pending_credits >= self._credit_batch:
                    grant_n, self._pending_credits = self._pending_credits, 0
            elif self._error is not None:
                raise self._error
            else:
                return None
        if grant_n:
            self._grant(grant_n)  # outside the lock: grants hit the socket
        return batch

    def in_rate(self) -> float:
        """Received bytes per second over the meter window (numBytesInPerSecond)."""
        return self._in_meter.rate()

    def occupancy(self) -> float:
        """Fraction of ring slots holding unconsumed batches (0..1) — the
        inPoolUsage analogue, registered as a per-channel gauge on staged
        tasks (cluster._run_graph_stage): a persistently full ring means
        THIS task is the bottleneck (its upstream is backpressured); a
        persistently empty one means it is starved."""
        with self._cv:
            return min(len(self._ring) / max(self.capacity, 1), 1.0)

    def on_reopen(self) -> "Tuple[int, int]":
        """Credits + next expected seq for an open/re-open reply. A fresh
        channel grants full capacity from seq 0 (identical to the old
        protocol); a RECONNECTING sender gets only the currently FREE ring
        slots — re-granting full capacity would mint credits for batches
        still parked in the ring — plus the sequence number this receiver
        will accept next, so the sender can verify that no frame was lost
        before resuming (seq mismatch = real loss = restart, not resume)."""
        with self._cv:
            self._pending_credits = 0   # banked grants died with the socket
            return max(self.capacity - len(self._ring), 0), self._next_seq

    @property
    def ended(self) -> bool:
        with self._cv:
            return self._eos and not self._ring


class ExchangeServer:
    """One per task executor: accepts peer connections, routes messages to
    registered input channels, sends credits back on the same socket.

    `wire_format` is what this receiver ADVERTISES on the open reply:
    "binary" accepts the zero-copy columnar wire from senders that offer it
    (old senders simply never offer, and keep the pickle wire); "pickle"
    forces every sender to the legacy frames. `credit_batch` is the
    coalescing grain for credit grants (0 = capacity/4)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, capacity: int = 8,
                 security: Optional[SecurityConfig] = None,
                 wire_format: str = "binary", credit_batch: int = 0):
        self.capacity = capacity
        self.wire_format = _validate_wire_format(wire_format)
        self.credit_batch = credit_batch if credit_batch > 0 else max(1, capacity // 4)
        self.security = SecurityConfig.resolve() if security is None else security
        validate_server_config(self.security)
        self._channels: Dict[str, InputChannel] = {}
        self._lock = threading.Lock()
        server_self = self

        class Handler(socketserver.BaseRequestHandler):
            @absorbs_faults('exchange server connection thread: disconnects and injected crashes sever the connection — returning models peer death; the consumer surfaces the stall via its channel-failure path')
            def handle(self):
                sock = self.request
                try:
                    # credit grants are tiny frames racing 1 MiB batches the
                    # other way; Nagle coalescing them stalls the sender's
                    # credit wait (Netty sets TCP_NODELAY on the same plane)
                    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                except OSError:
                    pass
                codec = None
                if server_self.security.enabled:
                    try:
                        sock.settimeout(server_self.security.handshake_timeout_s)
                        sock = wrap_server_socket(sock, server_self.security)
                        codec = server_handshake(sock, server_self.security)
                        sock.settimeout(None)
                    except (FrameAuthError, OSError, ValueError):
                        return   # unauthenticated peer: drop pre-parse
                sock_lock = threading.Lock()

                def grant_for(channel: str):
                    def grant(n: int):
                        try:
                            with sock_lock:
                                send_obj(sock, ("credit", channel, n), codec)
                        except OSError:
                            pass
                    return grant

                while True:
                    try:
                        msg, nbytes = recv_msg(sock, codec)
                    except (FrameAuthError, RestrictedUnpicklingError,
                            WireFormatError):
                        return   # tampered/malformed frame: drop pre-use
                    except OSError:
                        return   # abrupt peer disconnect (task cancel/kill)
                    if msg is None:
                        return
                    kind, channel = msg[0], msg[1]
                    if kind == "open":
                        ch = server_self._ensure(channel, grant_for(channel))
                        # wire-format negotiation rides the open reply: the
                        # 4th element names the format this receiver will
                        # accept for the channel's batches. Old senders
                        # ignore extra elements; old receivers reply with a
                        # 3-tuple, which new senders read as "pickle". The
                        # 5th element is the next seq this receiver expects
                        # — 0 on a fresh channel, the resume point for a
                        # sender re-running the open after a transient
                        # disconnect (OutputChannel.reconnect).
                        offered = msg[2] if len(msg) > 2 else ()
                        chosen = ("binary"
                                  if server_self.wire_format == "binary"
                                  and "binary" in tuple(offered) else "pickle")
                        grant_n, next_seq = ch.on_reopen()
                        with sock_lock:
                            send_obj(sock, ("credit", channel, grant_n,
                                            chosen, next_seq), codec)
                    elif kind == "data":
                        ch = server_self._channels.get(channel)
                        if ch is not None:
                            if not ch._on_data(msg[2], msg[3], nbytes):
                                return   # sequence gap: drop the connection
                    elif kind == "eos":
                        ch = server_self._channels.get(channel)
                        if ch is not None:
                            ch._on_eos()

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.host, self.port = self._server.server_address
        threading.Thread(target=self._server.serve_forever, daemon=True,
                         name=f"exchange-{self.port}").start()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def _ensure(self, channel_id: str, grant) -> InputChannel:
        with self._lock:
            ch = self._channels.get(channel_id)
            if ch is None:
                ch = InputChannel(channel_id, self.capacity, grant,
                                  self.credit_batch)
                self._channels[channel_id] = ch
            else:
                ch._grant = grant
            return ch

    def channel(self, channel_id: str) -> InputChannel:
        """Local handle (register before peers connect to avoid races)."""
        with self._lock:
            ch = self._channels.get(channel_id)
            if ch is None:
                ch = InputChannel(channel_id, self.capacity, lambda n: None,
                                  self.credit_batch)
                self._channels[channel_id] = ch
            return ch

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


class OutputChannel:
    """Sender side: one channel to a remote InputChannel; send() blocks when
    out of credit (the reference's writer blocking on LocalBufferPool)."""

    def __init__(self, address: str, channel_id: str, connect_timeout: float = 10.0,
                 security: Optional[SecurityConfig] = None,
                 wire_format: str = "binary"):
        host, port = address.rsplit(":", 1)
        self._addr = (host, int(port))
        self._connect_timeout = connect_timeout
        self._wire_format = _validate_wire_format(wire_format)  # before the dial
        self.security = SecurityConfig.resolve() if security is None else security
        self.channel_id = channel_id
        # negotiated on the open reply (None until the first credit grant
        # arrives; the first send always waits for that grant): "binary"
        # only when this sender offered it AND the receiver advertised it —
        # an old-wire peer downgrades the channel to pickled frames
        self._wire: Optional[str] = None
        self._credits = 0
        self._cv = threading.Condition()
        self._seq = 0
        # next seq the receiver advertised on the open reply (None from an
        # old receiver) — reconnect() verifies continuity against it
        self._advertised_seq: Optional[int] = None
        self._linger_timer: Optional[threading.Timer] = None
        self._send_lock = threading.Lock()
        # cumulative seconds send() spent blocked waiting for credits — the
        # task-side backpressure signal (TaskIOMetrics reads this; the
        # reference's backPressuredTimeMsPerSecond measures the same wait
        # on LocalBufferPool)
        self.backpressured_s = 0.0
        self.bytes_out = 0
        self._out_meter = Meter()
        # transient-fault hardening accounting (numDataplaneReconnects)
        self.num_reconnects = 0
        self._credit_thread: Optional[threading.Thread] = None
        self._sock, self._codec = self._dial()
        self._start_credit_loop(self._sock, self._codec)
        self._send_open()

    def _dial(self):
        sock = socket.create_connection(self._addr,
                                        timeout=self._connect_timeout)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        codec = None
        if self.security.enabled:
            try:
                sock = wrap_client_socket(sock, self.security)
                codec = client_handshake(sock, self.security)
            except BaseException:
                sock.close()
                raise
        sock.settimeout(None)
        return sock, codec

    def _start_credit_loop(self, sock, codec) -> None:
        t = threading.Thread(target=self._credit_loop, args=(sock, codec),
                             daemon=True, name=f"credits-{self.channel_id}")
        self._credit_thread = t
        t.start()

    def _send_open(self) -> None:
        open_msg = (("open", self.channel_id, ("binary",))
                    if self._wire_format == "binary"
                    else ("open", self.channel_id))
        with self._send_lock:
            n = send_obj(self._sock, open_msg, self._codec)
            self.bytes_out += n
            self._out_meter.mark(n)

    @absorbs_faults('credit listener: a broken credit socket wakes the sender with channel-closed, which surfaces as a send failure on the task thread — this loop has no caller to re-raise to')
    def _credit_loop(self, sock, codec) -> None:
        while True:
            try:
                msg = recv_obj(sock, codec)
            except (OSError, FrameAuthError, RestrictedUnpicklingError):
                msg = None
            if msg is None:
                with self._cv:
                    # a stale loop (its socket replaced by reconnect())
                    # must not poison the NEW connection's credit state
                    current = sock is self._sock
                    if current:
                        self._credits = -1  # poisoned: connection gone
                        self._cv.notify_all()
                # the peer closed (or close() shut down our write side and
                # the peer answered with FIN): now fully close the socket
                try:
                    sock.close()
                except OSError:
                    pass
                if current:
                    t = self._linger_timer
                    if t is not None:
                        t.cancel()     # fast FIN: don't hold the timer thread
                return
            if msg[0] == "credit" and msg[1] == self.channel_id:
                with self._cv:
                    if sock is not self._sock:
                        continue        # grant raced a reconnect: stale
                    if self._wire is None:
                        # open reply: the receiver's chosen wire format (a
                        # 3-tuple reply = old receiver = pickle) and, from
                        # new receivers, its next expected sequence number
                        self._wire = ("binary" if len(msg) > 3
                                      and msg[3] == "binary" else "pickle")
                        if len(msg) > 4:
                            self._advertised_seq = int(msg[4])
                    self._credits += msg[2]
                    self._cv.notify_all()

    def reconnect(self, timeout: float = 5.0) -> None:
        """Transient-fault hardening: re-dial the peer and re-run the
        open/credit negotiation IN PLACE (same object, counters and seq
        preserved, registered gauges stay valid). Resumes only on exact
        sequence continuity — the receiver's advertised next seq must
        equal this sender's, meaning no frame was lost — otherwise raises
        ConnectionError so the caller escalates to the checkpoint-rewind
        restart path. The caller owns retry pacing and the bounded
        reconnect window (cluster._ShardTask)."""
        try:
            # shutdown, not just close: close() does NOT wake a recv
            # already blocked in the credit thread (see close()'s linger
            # note) — without it every reconnect over a still-readable
            # socket burns the full join timeout and leaks the thread
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        t = self._credit_thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)
        sock, codec = self._dial()
        with self._cv:
            self._sock, self._codec = sock, codec
            self._credits = 0
            self._wire = None
            self._advertised_seq = None
        self._start_credit_loop(sock, codec)
        self._send_open()
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._wire is None and self._credits >= 0:
                left = deadline - time.monotonic()
                if left <= 0 or not self._cv.wait(timeout=left):
                    raise ConnectionError(
                        f"channel {self.channel_id}: no open reply within "
                        f"{timeout}s of reconnect")
            if self._credits < 0:
                raise ConnectionError(
                    f"channel {self.channel_id}: peer refused the reconnect")
            adv = self._advertised_seq
        with self._send_lock:
            local = self._seq
        if adv is not None and adv != local:
            raise SequenceLostError(
                f"channel {self.channel_id}: receiver expects seq {adv} but "
                f"this sender is at {local} — frame(s) lost in transit; "
                "only a checkpoint rewind can recover them")
        self.num_reconnects += 1

    def send(self, payload, timeout: Optional[float] = 30.0) -> None:
        # chaos seam: `error` raises before any credit/seq is consumed
        # (reconnectable blip); `drop` consumes the seq but skips the wire
        # write — a frame lost in transit, which the receiver surfaces as
        # a sequence gap (the unrecoverable-loss path)
        hook = _chaos.HOOK
        directive = (hook("dataplane", self.channel_id)
                     if hook is not None else None)
        with self._cv:
            if self._credits == 0:
                t0 = time.perf_counter()
                try:
                    while self._credits == 0:
                        if not self._cv.wait(timeout=timeout):
                            raise TimeoutError(
                                f"no credit on {self.channel_id} "
                                "(receiver backpressured)"
                            )
                finally:
                    self.backpressured_s += time.perf_counter() - t0
            if self._credits < 0:
                raise ConnectionError(f"exchange channel {self.channel_id} closed")
            self._credits -= 1
            wire_fmt = self._wire
        # column extraction + sidecar pickling stay OUTSIDE the send lock
        # (only the header build and the socket write serialize)
        enc = None
        if wire_fmt == "binary" and self._wire_format == "binary":
            enc = extract_columns(payload)
        with self._send_lock:
            # seq assignment rides the SAME lock as the socket write, so
            # two threads sharing a sender cannot interleave sequence
            # numbers against frame order; the increment lands only AFTER
            # a successful write — a refused frame (e.g. the >=2GiB size
            # guard, raised before any byte hits the wire) must not burn a
            # seq, or the receiver would misread the next good frame as a
            # sequence gap
            seq = self._seq
            if directive == "drop":
                n = 0   # chaos: the frame "left" but never hits the wire
            elif enc is not None:
                n = send_data_frame(self._sock, self.channel_id, seq,
                                    enc[0], enc[1], self._codec)
            else:
                n = send_obj(self._sock,
                             ("data", self.channel_id, seq, payload),
                             self._codec)
            self._seq = seq + 1
            self.bytes_out += n
            self._out_meter.mark(n)

    def available_credits(self) -> int:
        with self._cv:
            return max(self._credits, 0)

    def out_rate(self) -> float:
        """Sent bytes per second over the meter window (numBytesOutPerSecond)."""
        return self._out_meter.rate()

    def end(self) -> None:
        with self._send_lock:
            n = send_obj(self._sock, ("eos", self.channel_id), self._codec)
            self.bytes_out += n
            self._out_meter.mark(n)

    def close(self) -> None:
        # graceful FIN, not a hard close: an immediate close() with unread
        # credit messages in the receive buffer sends RST, which can discard
        # the just-sent eos before the receiver processes it (observed as a
        # downstream stage waiting forever). Shut down the write side only;
        # _credit_loop closes the socket once the peer answers with FIN.
        # A hung/partitioned peer never sends that FIN, so a timer forces
        # shutdown(SHUT_RDWR) after a bounded linger — unlike settimeout or
        # close(), shutdown DOES wake a recv already blocked in the credit
        # thread, so the fd and thread cannot leak.
        try:
            self._sock.shutdown(socket.SHUT_WR)
        except OSError:
            try:
                self._sock.close()
            except OSError:
                pass
            return

        def _force(sock=self._sock):
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass   # already closed by the credit loop — the normal case

        t = threading.Timer(30.0, _force)
        t.daemon = True
        self._linger_timer = t
        t.start()


class BatchDebloater:
    """Adaptive batch sizing: EMA of throughput x target latency, clamped.
    (BufferDebloater.java / BufferSizeEMA analogue at batch granularity.)"""

    def __init__(self, *, target_latency_s: float = 0.2, min_size: int = 256,
                 max_size: int = 1 << 20, alpha: float = 0.3):
        self.target = target_latency_s
        self.min_size = min_size
        self.max_size = max_size
        self.alpha = alpha
        self._rate: Optional[float] = None

    def observe(self, records: int, elapsed_s: float) -> None:
        if elapsed_s <= 0:
            return
        r = records / elapsed_s
        self._rate = r if self._rate is None else (1 - self.alpha) * self._rate + self.alpha * r

    @property
    def observed(self) -> bool:
        """True once at least one throughput observation has landed; senders
        pass batches through unsplit until then (min_size would shred the
        very first batch for no reason)."""
        return self._rate is not None

    def batch_size(self) -> int:
        if self._rate is None:
            return self.min_size
        return int(min(self.max_size, max(self.min_size, self._rate * self.target)))
