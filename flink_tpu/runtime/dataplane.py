"""Data plane: record-batch exchange between task executors over TCP (DCN).

Within a TPU slice, keyBy shuffles ride ICI as compiled all-to-all
collectives (flink_tpu/parallel). ACROSS hosts, batches travel on this
plane — the DCN counterpart of the reference's Netty shuffle
(io/network/netty/**) with the same backpressure discipline:

- **Credit-based flow control** (CreditBasedPartitionRequestClientHandler.java:61,
  RemoteInputChannel.java:114): a receiving channel grants credits equal to
  its free ring slots; the sender spends one credit per batch and BLOCKS
  when out of credit — backpressure propagates to the producing step loop
  with no unbounded buffering, exactly the "no credit ⇒ no send ⇒ writer
  blocks on LocalBufferPool" chain of the reference.
- **Batch debloating** (runtime/throughput/BufferDebloater.java): senders
  size batches toward `target_latency x observed_throughput` with an EMA,
  trading latency for amortization the way buffer debloating resizes
  network buffers.

Wire (flink_tpu/security): the same handshake + MAC-signed framing as the
RPC plane, carrying restricted-pickled ("data", channel, seq, payload) /
("credit", channel, n) / ("eos", channel). Payloads are columnar dicts of
numpy arrays (the host-side RecordBatch), ready for device staging. An
exchange port is reachable from every peer host, so frames are MAC-verified
before deserialization exactly like RPC frames; `security.transport.enabled:
false` restores the legacy plain-pickle wire.
"""

from __future__ import annotations

import socket
import socketserver
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from flink_tpu.security.framing import FrameAuthError, RestrictedUnpicklingError
from flink_tpu.security.transport import (
    SecurityConfig,
    client_handshake,
    recv_obj,
    send_obj,
    server_handshake,
    validate_server_config,
    wrap_client_socket,
    wrap_server_socket,
)


class InputChannel:
    """Receiver side of one channel: a bounded ring of batches; consuming a
    batch releases a credit back to the sender."""

    def __init__(self, channel_id: str, capacity: int, grant: Callable[[int], None]):
        self.channel_id = channel_id
        self.capacity = capacity
        self._grant = grant
        self._ring: deque = deque()
        self._cv = threading.Condition()
        self._eos = False

    def _on_data(self, seq: int, payload) -> None:
        with self._cv:
            self._ring.append(payload)
            self._cv.notify_all()

    def _on_eos(self) -> None:
        with self._cv:
            self._eos = True
            self._cv.notify_all()

    def poll(self, timeout: Optional[float] = None):
        """Next batch, or None at end-of-stream."""
        with self._cv:
            while not self._ring and not self._eos:
                if not self._cv.wait(timeout=timeout):
                    raise TimeoutError(f"channel {self.channel_id} starved")
            if self._ring:
                batch = self._ring.popleft()
            else:
                return None
        self._grant(1)  # slot freed -> one more credit to the sender
        return batch

    def occupancy(self) -> float:
        """Fraction of ring slots holding unconsumed batches (0..1) — the
        inPoolUsage analogue, registered as a per-channel gauge on staged
        tasks (cluster._run_graph_stage): a persistently full ring means
        THIS task is the bottleneck (its upstream is backpressured); a
        persistently empty one means it is starved."""
        with self._cv:
            return min(len(self._ring) / max(self.capacity, 1), 1.0)

    @property
    def ended(self) -> bool:
        with self._cv:
            return self._eos and not self._ring


class ExchangeServer:
    """One per task executor: accepts peer connections, routes messages to
    registered input channels, sends credits back on the same socket."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, capacity: int = 8,
                 security: Optional[SecurityConfig] = None):
        self.capacity = capacity
        self.security = SecurityConfig.resolve() if security is None else security
        validate_server_config(self.security)
        self._channels: Dict[str, InputChannel] = {}
        self._lock = threading.Lock()
        server_self = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                sock = self.request
                codec = None
                if server_self.security.enabled:
                    try:
                        sock.settimeout(server_self.security.handshake_timeout_s)
                        sock = wrap_server_socket(sock, server_self.security)
                        codec = server_handshake(sock, server_self.security)
                        sock.settimeout(None)
                    except (FrameAuthError, OSError, ValueError):
                        return   # unauthenticated peer: drop pre-parse
                sock_lock = threading.Lock()

                def grant_for(channel: str):
                    def grant(n: int):
                        try:
                            with sock_lock:
                                send_obj(sock, ("credit", channel, n), codec)
                        except OSError:
                            pass
                    return grant

                while True:
                    try:
                        msg = recv_obj(sock, codec)
                    except (FrameAuthError, RestrictedUnpicklingError):
                        return   # tampered frame / disallowed global: drop
                    except OSError:
                        return   # abrupt peer disconnect (task cancel/kill)
                    if msg is None:
                        return
                    kind, channel = msg[0], msg[1]
                    if kind == "open":
                        ch = server_self._ensure(channel, grant_for(channel))
                        with sock_lock:
                            send_obj(sock, ("credit", channel, ch.capacity), codec)
                    elif kind == "data":
                        ch = server_self._channels.get(channel)
                        if ch is not None:
                            ch._on_data(msg[2], msg[3])
                    elif kind == "eos":
                        ch = server_self._channels.get(channel)
                        if ch is not None:
                            ch._on_eos()

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.host, self.port = self._server.server_address
        threading.Thread(target=self._server.serve_forever, daemon=True,
                         name=f"exchange-{self.port}").start()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def _ensure(self, channel_id: str, grant) -> InputChannel:
        with self._lock:
            ch = self._channels.get(channel_id)
            if ch is None:
                ch = InputChannel(channel_id, self.capacity, grant)
                self._channels[channel_id] = ch
            else:
                ch._grant = grant
            return ch

    def channel(self, channel_id: str) -> InputChannel:
        """Local handle (register before peers connect to avoid races)."""
        with self._lock:
            ch = self._channels.get(channel_id)
            if ch is None:
                ch = InputChannel(channel_id, self.capacity, lambda n: None)
                self._channels[channel_id] = ch
            return ch

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


class OutputChannel:
    """Sender side: one channel to a remote InputChannel; send() blocks when
    out of credit (the reference's writer blocking on LocalBufferPool)."""

    def __init__(self, address: str, channel_id: str, connect_timeout: float = 10.0,
                 security: Optional[SecurityConfig] = None):
        host, port = address.rsplit(":", 1)
        self.security = SecurityConfig.resolve() if security is None else security
        sock = socket.create_connection((host, int(port)), timeout=connect_timeout)
        self._codec = None
        if self.security.enabled:
            try:
                sock = wrap_client_socket(sock, self.security)
                self._codec = client_handshake(sock, self.security)
            except BaseException:
                sock.close()
                raise
        sock.settimeout(None)
        self._sock = sock
        self.channel_id = channel_id
        self._credits = 0
        self._cv = threading.Condition()
        self._seq = 0
        self._linger_timer: Optional[threading.Timer] = None
        self._send_lock = threading.Lock()
        # cumulative seconds send() spent blocked waiting for credits — the
        # task-side backpressure signal (TaskIOMetrics reads this; the
        # reference's backPressuredTimeMsPerSecond measures the same wait
        # on LocalBufferPool)
        self.backpressured_s = 0.0
        threading.Thread(target=self._credit_loop, daemon=True,
                         name=f"credits-{channel_id}").start()
        with self._send_lock:
            send_obj(self._sock, ("open", channel_id), self._codec)

    def _credit_loop(self) -> None:
        while True:
            try:
                msg = recv_obj(self._sock, self._codec)
            except (OSError, FrameAuthError, RestrictedUnpicklingError):
                msg = None
            if msg is None:
                with self._cv:
                    self._credits = -1  # poisoned: connection gone
                    self._cv.notify_all()
                # the peer closed (or close() shut down our write side and
                # the peer answered with FIN): now fully close the socket
                try:
                    self._sock.close()
                except OSError:
                    pass
                t = self._linger_timer
                if t is not None:
                    t.cancel()     # fast FIN: don't hold the timer thread
                return
            if msg[0] == "credit" and msg[1] == self.channel_id:
                with self._cv:
                    self._credits += msg[2]
                    self._cv.notify_all()

    def send(self, payload, timeout: Optional[float] = 30.0) -> None:
        with self._cv:
            if self._credits == 0:
                t0 = time.perf_counter()
                try:
                    while self._credits == 0:
                        if not self._cv.wait(timeout=timeout):
                            raise TimeoutError(
                                f"no credit on {self.channel_id} "
                                "(receiver backpressured)"
                            )
                finally:
                    self.backpressured_s += time.perf_counter() - t0
            if self._credits < 0:
                raise ConnectionError(f"exchange channel {self.channel_id} closed")
            self._credits -= 1
        with self._send_lock:
            send_obj(self._sock, ("data", self.channel_id, self._seq, payload),
                     self._codec)
        self._seq += 1

    def available_credits(self) -> int:
        with self._cv:
            return max(self._credits, 0)

    def end(self) -> None:
        with self._send_lock:
            send_obj(self._sock, ("eos", self.channel_id), self._codec)

    def close(self) -> None:
        # graceful FIN, not a hard close: an immediate close() with unread
        # credit messages in the receive buffer sends RST, which can discard
        # the just-sent eos before the receiver processes it (observed as a
        # downstream stage waiting forever). Shut down the write side only;
        # _credit_loop closes the socket once the peer answers with FIN.
        # A hung/partitioned peer never sends that FIN, so a timer forces
        # shutdown(SHUT_RDWR) after a bounded linger — unlike settimeout or
        # close(), shutdown DOES wake a recv already blocked in the credit
        # thread, so the fd and thread cannot leak.
        try:
            self._sock.shutdown(socket.SHUT_WR)
        except OSError:
            try:
                self._sock.close()
            except OSError:
                pass
            return

        def _force(sock=self._sock):
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass   # already closed by the credit loop — the normal case

        t = threading.Timer(30.0, _force)
        t.daemon = True
        self._linger_timer = t
        t.start()


class BatchDebloater:
    """Adaptive batch sizing: EMA of throughput x target latency, clamped.
    (BufferDebloater.java / BufferSizeEMA analogue at batch granularity.)"""

    def __init__(self, *, target_latency_s: float = 0.2, min_size: int = 256,
                 max_size: int = 1 << 20, alpha: float = 0.3):
        self.target = target_latency_s
        self.min_size = min_size
        self.max_size = max_size
        self.alpha = alpha
        self._rate: Optional[float] = None

    def observe(self, records: int, elapsed_s: float) -> None:
        if elapsed_s <= 0:
            return
        r = records / elapsed_s
        self._rate = r if self._rate is None else (1 - self.alpha) * self._rate + self.alpha * r

    def batch_size(self) -> int:
        if self._rate is None:
            return self.min_size
        return int(min(self.max_size, max(self.min_size, self._rate * self.target)))
