"""DeviceJoinRunner: the two-input keyed window join on the device ring.

StepRunner (kind 'window_join', two gates) that keeps both sides' records
in the `flink_tpu.joins` bucketed-ring pipeline instead of host dicts:
each batch is keyed, bucketed, and scattered into HBM in one dispatch;
when the two-gate watermark valve advances, every ripe window becomes one
gather + segment cross-match kernel call whose (left, right) row-id pairs
the host expands into join_fn outputs. Inherits the valve semantics —
watermarks min-combine across the inputs and end-of-input fires only
after BOTH sides end — so its behavior is batch-for-batch comparable to
the host `WindowJoinRunner` oracle.

Refusal vs degrade: shapes the ring cannot represent AT BUILD TIME
(processing time, session windows, coGroup, outer joins, device joins
disabled) raise `JoinUnsupported` out of the constructor and the factory
falls back to the host runner — an attributed refusal, not an error.
Shapes that break MID-STREAM (a (key, bucket) past its record capacity,
event time wrapping the ring, key cardinality past the key capacity)
degrade in place: the live ring contents replay into a freshly built host
`WindowJoinRunner` (its watermark set first, so already-fired windows
drop as late instead of re-emitting — exactly-once), the failed batch
replays whole (ring ingest is all-or-nothing per batch), and the reason
lands in the `joinFallbackReason` gauge.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from flink_tpu.config import Configuration, ExecutionOptions
from flink_tpu.chaos import plan as _chaos
from flink_tpu.core.time import MIN_WATERMARK
from flink_tpu.joins.pipeline import FusedJoinPipeline
from flink_tpu.joins.sharded import ShardedJoinPipeline
from flink_tpu.joins.spec import (
    JoinUnsupported,
    fallback_code,
    plan_join_geometry,
)
from flink_tpu.metrics.emission_latency import watermark_lag_ms
from flink_tpu.runtime.executor import (
    StepRunner,
    WindowJoinRunner,
    _mesh_for_config,
    make_emission_tracker,
)
from flink_tpu.utils.arrays import obj_array


class DeviceJoinRunner(StepRunner):

    num_inputs = 2

    def __init__(self, step, config: Configuration):
        t = step.terminal
        if t.kind == "co_group":
            raise JoinUnsupported("join-cogroup")
        if not config.get(ExecutionOptions.DEVICE_JOINS):
            raise JoinUnsupported("join-device-disabled")
        assigner = t.config["assigner"]
        if not assigner.is_event_time:
            raise JoinUnsupported("join-processing-time")
        if assigner.slice_ms is None:
            raise JoinUnsupported("join-session-window")
        if t.config.get("join_type", "inner") != "inner":
            raise JoinUnsupported("join-outer-windowed")
        self.step = step
        self.config = config
        self.uid = t.uid
        self.sql_origin = bool(t.config.get("sql_origin"))
        self.key_selectors = (t.config["key_selector1"],
                              t.config["key_selector2"])
        self.join_fn = t.config["join_fn"]
        self.assigner = assigner
        size = assigner.slices_per_window * assigner.slice_ms
        slide = assigner.slide_slices * assigner.slice_ms
        # the configured capacities are CAPS; the rings allocate small and
        # double toward them on demand (the key-capacity growth contract),
        # so a join over a handful of keys never pins cap-sized arrays
        self._max_keys = config.get(ExecutionOptions.KEY_CAPACITY)
        self._max_slots = config.get(ExecutionOptions.JOIN_BUCKET_CAPACITY)
        geom = plan_join_geometry(
            size, slide, assigner.offset_ms,
            key_capacity=min(1024, self._max_keys),
            bucket_capacity=min(16, self._max_slots),
            ring_slack_buckets=config.get(ExecutionOptions.JOIN_RING_SLACK))
        self.geom = geom
        mesh = _mesh_for_config(config, geom.key_capacity)
        self.pipeline: Optional[FusedJoinPipeline] = (
            ShardedJoinPipeline(geom, mesh) if mesh is not None
            else FusedJoinPipeline(geom))
        self.sharded = mesh is not None
        # key -> dense key lane; per-lane inverse is never needed (pairs
        # come back as row ids whose payloads the rings own)
        self._keys: Dict[Any, int] = {}
        self._wm = MIN_WATERMARK
        # emission-latency plane: stamped in the on_watermark fire loop
        # right after take_rows (the matches' host-visibility point)
        self.emission_tracker = make_emission_tracker(t.uid, config)
        self.num_late_dropped = 0
        self.matches_emitted = 0
        self.fallback_reason: Optional[str] = None
        self._host: Optional[WindowJoinRunner] = None

    # -- adaptive geometry -------------------------------------------------
    @staticmethod
    def _fit(cur: int, need: int, cap: int) -> int:
        while cur < need:
            cur *= 2
        return min(cur, cap)

    def _grow(self, **changes) -> None:
        import dataclasses

        self.geom = dataclasses.replace(self.geom, **changes)
        self.pipeline.regrow(self.geom)

    # -- degrade-to-host ---------------------------------------------------
    def _degrade(self, reason: str, detail: str = "") -> WindowJoinRunner:
        host = WindowJoinRunner(self.step, self.config)
        host.downstream = self.downstream
        host.sides = self.sides
        # watermark FIRST: replayed records re-assign their windows and
        # the already-fired ones drop as late — nothing double-emits
        host._wm = self._wm
        pipeline, self.pipeline = self.pipeline, None
        if pipeline is not None and pipeline.ts_base is not None:
            inv = [None] * len(self._keys)
            for key, kid in self._keys.items():
                inv[kid] = key
            for side, ring in ((0, pipeline.left), (1, pipeline.right)):
                recs = ring.live_records()
                if recs:
                    host.on_batch_n(
                        side,
                        obj_array([row for _kid, row, _ts in recs]),
                        np.asarray([ts for _kid, _row, ts in recs],
                                   dtype=np.int64))
        # the replay's late drops were counted (and emitted) on the device
        # path already — the public counter carries on from ours
        host.num_late_dropped = self.num_late_dropped
        self.fallback_reason = reason
        self._host = host
        return host

    # -- ingest ------------------------------------------------------------
    def on_batch_n(self, ordinal: int, values, timestamps) -> None:
        counter = getattr(self, "records_in_counter", None)
        if counter is not None:
            counter.inc(len(timestamps))
        if self._host is not None:
            self._host.on_batch_n(ordinal, values, timestamps)
            self._sync_late()
            return
        hook = _chaos.HOOK
        if hook is not None:
            hook("device", self.uid)
        n = len(timestamps)
        if n == 0:
            return
        ts = np.asarray(timestamps, dtype=np.int64)
        ks = self.key_selectors[ordinal]
        kdict = self._keys
        kids = np.empty(n, dtype=np.int64)
        for i, v in enumerate(values):
            k = ks(v)
            kid = kdict.get(k)
            if kid is None:
                kid = len(kdict)
                kdict[k] = kid
            kids[i] = kid
        if len(kdict) > self.geom.key_capacity:
            if len(kdict) > self._max_keys:
                self._degrade(
                    "join-key-capacity",
                    f"distinct join keys exceeded "
                    f"execution.state.key-capacity={self._max_keys}"
                ).on_batch_n(ordinal, values, timestamps)
                self._sync_late()
                return
            self._grow(key_capacity=self._fit(self.geom.key_capacity,
                                              len(kdict), self._max_keys))
        g = self.geom
        # late accounting, mirroring the host oracle's per-(record, window)
        # drop counts: a record whose LAST window already fired is dropped
        # whole; a straggler with only some windows late still ingests (its
        # bucket feeds the remaining live windows) and counts the late ones
        ws_last = (ts - g.offset_ms) // g.slide_ms * g.slide_ms + g.offset_ms
        covered = ((ts - g.offset_ms) // g.slide_ms
                   - (ts - g.size_ms - g.offset_ms) // g.slide_ms)
        from flink_tpu.core.time import MAX_WATERMARK
        if self._wm >= MAX_WATERMARK - g.size_ms:
            # terminal watermark: every window is closed, the whole batch
            # is late (int64-safe: no wm+1 arithmetic at the MAX bound)
            self.num_late_dropped += int(covered.sum())
            return
        if self._wm > MIN_WATERMARK:
            ws_late_max = ((self._wm + 1 - g.size_ms - g.offset_ms)
                           // g.slide_ms * g.slide_ms + g.offset_ms)
            ws_first = ws_last - (covered - 1) * g.slide_ms
            n_late = np.clip(
                (np.minimum(ws_late_max, ws_last) - ws_first) // g.slide_ms
                + 1, 0, covered)
        else:
            n_late = np.zeros(n, dtype=np.int64)
        self.num_late_dropped += int(n_late.sum())
        keep = n_late < covered
        if not np.all(keep):
            kids, ts = kids[keep], ts[keep]
            values = [v for v, k in zip(values, keep) if k]
            if len(ts) == 0:
                return
        while True:
            try:
                self.pipeline.ingest(ordinal, kids, ts, list(values))
                return
            except JoinUnsupported as e:
                # a slots overflow under the configured cap grows the ring
                # and retries (ingest is all-or-nothing, so retry is safe);
                # at-cap overflows and ring wraps degrade to the host
                need = getattr(e, "required", 0)
                if (getattr(e, "overflow", "") == "slots"
                        and need <= self._max_slots):
                    self._grow(bucket_capacity=self._fit(
                        self.geom.bucket_capacity, need, self._max_slots))
                    continue
                # nothing of this batch landed: the host replay takes the
                # WHOLE (filtered) batch; late drops were already counted
                # above and the host recounts them on replay — reset after
                saved_late = self.num_late_dropped
                host = self._degrade(e.reason, e.detail)
                host.on_batch_n(ordinal, obj_array(list(values)), ts)
                host.num_late_dropped = saved_late
                self._sync_late()
                return

    def on_batch(self, values, timestamps) -> None:  # pragma: no cover
        raise AssertionError("DeviceJoinRunner consumes via input gates")

    def _sync_late(self) -> None:
        if self._host is not None:
            self.num_late_dropped = self._host.num_late_dropped

    # -- fire --------------------------------------------------------------
    def _ripe_windows(self, prev_wm: int, wm: int) -> List[tuple]:
        """(start, end) of every window over an occupied bucket with
        prev_wm < end-1 <= wm — bounded by resident state, so a terminal
        MAX watermark enumerates only what exists."""
        g = self.geom
        out = set()
        for b in self.pipeline.occupied_buckets():
            bt = g.offset_ms + b * g.bucket_ms
            ws_max = (bt - g.offset_ms) // g.slide_ms * g.slide_ms \
                + g.offset_ms
            ws = ((bt - g.size_ms - g.offset_ms) // g.slide_ms + 1) \
                * g.slide_ms + g.offset_ms
            while ws <= ws_max:
                if prev_wm < ws + g.size_ms - 1 <= wm:
                    out.add((ws, ws + g.size_ms))
                ws += g.slide_ms
        return sorted(out, key=lambda w: w[1])

    def on_watermark(self, watermark: int) -> None:
        if self._host is not None:
            self._host.on_watermark(watermark)
            return
        prev, self._wm = self._wm, max(self._wm, watermark)
        out_vals: List[Any] = []
        out_ts: List[int] = []
        fn = self.join_fn
        tracker = self.emission_tracker
        for start, end in self._ripe_windows(prev, self._wm):
            lids, rids, _kids = self.pipeline.fire_window(start, end)
            if len(lids) == 0:
                continue
            lrows = self.pipeline.left.take_rows(lids)
            rrows = self.pipeline.right.take_rows(rids)
            if tracker is not None:
                # take_rows above is the host-visibility point of this
                # window's matches — stamp after it, never before
                tracker.record_fire(end)
            max_ts = end - 1
            out_vals.extend(fn(a, b) for a, b in zip(lrows, rrows))
            out_ts.extend([max_ts] * len(lrows))
        if out_vals:
            self.matches_emitted += len(out_vals)
            if self.downstream:
                self.downstream.on_batch(
                    obj_array(out_vals),
                    np.asarray(out_ts, dtype=np.int64))
        g = self.geom
        # purge horizon: the start of the earliest window still live
        min_live_ws = ((self._wm + 1 - g.size_ms - g.offset_ms)
                       // g.slide_ms + 1) * g.slide_ms + g.offset_ms
        self.pipeline.purge_below_window(min_live_ws)
        super().on_watermark(watermark)

    def on_end(self) -> None:
        if self._host is not None:
            self._host.on_end()
        else:
            super().on_end()

    # -- metrics -----------------------------------------------------------
    def register_metrics(self, group) -> None:
        super().register_metrics(group)
        group.gauge("currentWatermark",
                    lambda: self._host._wm if self._host is not None
                    else self._wm,
                    fold="min")
        if self.emission_tracker is not None:
            group.gauge("emissionLatencyMs", self.emission_tracker.snapshot,
                        fold="emission", kind="histogram")
            group.gauge(
                "watermarkLagMs",
                lambda: watermark_lag_ms(
                    self._host._wm if self._host is not None else self._wm),
                fold="max")
        group.gauge("numLateRecordsDropped",
                    lambda: (self._sync_late(), self.num_late_dropped)[1],
                    fold="sum", kind="counter")
        group.gauge("joinRingOccupancy",
                    lambda: 0 if self.pipeline is None
                    else self.pipeline.occupancy(),
                    fold="sum")
        group.gauge("joinMatchesEmitted", lambda: self.matches_emitted,
                    fold="sum", kind="counter")
        # a catalogued reason CODE, not a count — "did ANY shard degrade"
        group.gauge("joinFallbackReason",
                    lambda: fallback_code(self.fallback_reason),
                    fold="max")
        group.gauge("stateBytes",
                    lambda: 0 if self.pipeline is None
                    else self.pipeline.state_bytes(),
                    fold="sum")
        group.gauge("stateKeyCount", lambda: len(self._keys), fold="sum")

    # -- checkpointing -----------------------------------------------------
    def snapshot(self) -> dict:
        if self._host is not None:
            return {"mode": "host", "reason": self.fallback_reason,
                    "late": self._host.num_late_dropped,
                    "matches": self.matches_emitted,
                    "host": self._host.snapshot()}
        return {"mode": "device",
                "wm": self._wm,
                "late": self.num_late_dropped,
                "matches": self.matches_emitted,
                "keys": list(self._keys.items()),
                "geom": (self.geom.key_capacity,
                         self.geom.bucket_capacity),
                "pipeline": self.pipeline.snapshot()}

    def restore(self, snap: dict) -> None:
        self.matches_emitted = snap["matches"]
        if snap["mode"] == "host":
            host = WindowJoinRunner(self.step, self.config)
            host.downstream = self.downstream
            host.sides = self.sides
            host.restore(snap["host"])
            self.fallback_reason = snap["reason"]
            self.pipeline = None
            self._host = host
            self.num_late_dropped = snap["late"]
            return
        import dataclasses

        self._host = None
        self.fallback_reason = None
        self._wm = snap["wm"]
        self.num_late_dropped = snap["late"]
        self._keys = dict(snap["keys"])
        # the snapshot's geometry may have grown past a fresh runner's
        # initial rings: restore at the snapshotted shape BEFORE replay
        k_cap, c_cap = snap["geom"]
        self.geom = dataclasses.replace(
            self.geom, key_capacity=k_cap, bucket_capacity=c_cap)
        mesh = _mesh_for_config(self.config, self.geom.key_capacity)
        self.pipeline = (ShardedJoinPipeline(self.geom, mesh)
                         if mesh is not None
                         else FusedJoinPipeline(self.geom))
        self.pipeline.restore(snap["pipeline"])
