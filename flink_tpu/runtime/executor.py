"""Local pipeline executor: the host-driven stepped dataflow loop.

The reference drives records through a per-task mailbox loop
(StreamTask.java:205 processInput :655, MailboxProcessor.runMailboxLoop
:214) with operators chained by direct calls (OperatorChain.java:108). Here
execution is *stepped*: the source reader yields a columnar batch, the batch
flows through push-based StepRunners (a fused stateless chain, then a keyed
window step backed by the device operator, then sinks), and one combined
watermark is advanced between steps (core/watermarks.py valve). There is no
per-record scheduling — the device program IS the inner loop.

Operator selection mirrors WindowOperatorBuilder.java:79: the keyed window
step uses the batched TpuWindowOperator when the aggregate has a columnar
device form, the assigner is sliceable and event-time, and no custom
trigger/evictor is set; otherwise the per-record oracle operator (same
semantics, CPU).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from flink_tpu.api.functions import AggregateFunction, ProcessFunction, ReduceAggregate
from flink_tpu.chaos import plan as _chaos
from flink_tpu.config import (
    Configuration,
    ExecutionOptions,
    ObservabilityOptions,
    ParallelOptions,
    PipelineOptions,
)
from flink_tpu.core.time import MAX_WATERMARK, MIN_TIMESTAMP, MIN_WATERMARK
from flink_tpu.core.watermarks import WatermarkStrategy
from flink_tpu.graph.transformation import Step, StepGraph, Transformation
from flink_tpu.ops.aggregators import resolve
from flink_tpu.runtime.oracle_window_operator import OracleWindowOperator
from flink_tpu.runtime.tpu_window_operator import TpuWindowOperator
from flink_tpu.runtime.timers import InternalTimerService
from flink_tpu.metrics.emission_latency import (
    EmissionLatencyTracker,
    merge_snapshots as _merge_emission_snapshots,
    watermark_lag_ms,
)
from flink_tpu.metrics.registry import MetricRegistry
from flink_tpu.metrics.task_io import DeviceTimer, TaskIOMetrics
from flink_tpu.state.heap import HeapKeyedStateBackend, value_state
from flink_tpu.utils.arrays import as_device_column, canonical_column, obj_array
from flink_tpu.core.keygroups import KeyGroupRange


@dataclasses.dataclass
class JobExecutionResult:
    job_name: str
    runtime_ms: float
    records_in: int
    metrics: Dict[str, Any]


# ---------------------------------------------------------------------------
# step runners (push-based; each pushes into `downstream`)
# ---------------------------------------------------------------------------

class _FanOut:
    """Downstream edge set of one runner. Runners emit through
    `self.downstream` exactly as in a linear pipeline; the fan-out routes to
    every consumer's input gate (ordinal), which is how one runner feeds
    multiple sinks and how two-input operators distinguish their sides."""

    __slots__ = ("edges",)

    def __init__(self):
        self.edges: List = []   # (runner, input_ordinal)

    def __bool__(self) -> bool:
        return bool(self.edges)

    def add(self, runner: "StepRunner", ordinal: int) -> None:
        self.edges.append((runner, ordinal))

    def on_batch(self, values: np.ndarray, timestamps: np.ndarray) -> None:
        for r, o in self.edges:
            r.on_batch_n(o, values, timestamps)

    def on_watermark(self, watermark: int) -> None:
        for r, o in self.edges:
            r.on_watermark_n(o, watermark)

    def on_marker(self, wall_ms: float) -> None:
        for r, _o in self.edges:
            r.on_marker(wall_ms)

    def on_end(self) -> None:
        for r, o in self.edges:
            r.on_end_n(o)


class StepRunner:
    downstream: Optional[_FanOut] = None
    sides: Optional[Dict[str, _FanOut]] = None   # side-output channels by tag
    num_inputs: int = 1

    def side_channel(self, tag_id: str) -> _FanOut:
        if self.sides is None:
            self.sides = {}
        if tag_id not in self.sides:
            self.sides[tag_id] = _FanOut()
        return self.sides[tag_id]

    def emit_side(self, tag_id: str, values, timestamps) -> None:
        if self.sides and tag_id in self.sides:
            self.sides[tag_id].on_batch(values, timestamps)

    def register_metrics(self, group) -> None:
        # operator-scope IO metrics (TaskIOMetricGroup.java:48 analogue)
        self.records_in_counter = group.counter("numRecordsIn")
        # source->operator transit latency per latency marker (the
        # per-operator LatencyStats histogram of the reference): updated as
        # each marker PASSES this operator, so a slow stage shows up as the
        # step where the percentile jumps
        self._marker_hist = group.histogram("latencyMs")

    # -- input-gate protocol (multi-input valve) --------------------------
    def on_batch_n(self, ordinal: int, values: np.ndarray,
                   timestamps: np.ndarray) -> None:
        self.on_batch(values, timestamps)

    def on_watermark_n(self, ordinal: int, watermark: int) -> None:
        """Per-gate watermark: min-combine across gates before processing
        (StatusWatermarkValve.java semantics)."""
        if self.num_inputs <= 1:
            self.on_watermark(watermark)
            return
        wms = self.__dict__.setdefault("_gate_wms", {})
        wms[ordinal] = max(wms.get(ordinal, MIN_WATERMARK), watermark)
        if len(wms) < self.num_inputs:
            return
        combined = min(wms.values())
        if combined > self.__dict__.get("_combined_wm", MIN_WATERMARK):
            self.__dict__["_combined_wm"] = combined
            self.on_watermark(combined)

    def on_end_n(self, ordinal: int) -> None:
        ended = self.__dict__.setdefault("_ended_gates", set())
        ended.add(ordinal)
        if len(ended) >= self.num_inputs:
            self.on_end()

    # -- processing -------------------------------------------------------
    def on_batch(self, values: np.ndarray, timestamps: np.ndarray) -> None:
        raise NotImplementedError

    def on_watermark(self, watermark: int) -> None:
        if self.downstream:
            self.downstream.on_watermark(watermark)
        if self.sides:
            for f in self.sides.values():
                f.on_watermark(watermark)

    def on_marker(self, wall_ms: float) -> None:
        """Latency marker (LatencyMarker analogue): a wall-clock stamp from
        the source that flows straight through every operator — windows and
        buffers forward it immediately, so a sink's (now - stamp) measures
        true pipeline transit latency rather than event-time residence.
        Each operator it passes records (now - stamp) into its own latency
        histogram before forwarding."""
        h = getattr(self, "_marker_hist", None)
        if h is not None:
            h.update(time.time() * 1000.0 - wall_ms)
        if self.downstream:
            self.downstream.on_marker(wall_ms)
        if self.sides:
            for f in self.sides.values():
                f.on_marker(wall_ms)

    def on_processing_time(self, now_ms: int) -> None:
        """Wall-clock tick driven by the run loop (ProcessingTimeService
        analogue); runners with processing-time timers fire them here."""

    def on_end(self) -> None:
        if self.downstream:
            self.downstream.on_end()
        if self.sides:
            for f in self.sides.values():
                f.on_end()

    def snapshot(self) -> dict:
        return {}

    def restore(self, snap: dict) -> None:
        pass


def make_emission_tracker(uid: str, config: Configuration):
    """Per-operator emission-latency tracker, or None when the plane is
    off (observability.emission-latency.enabled). One policy for every
    windowed runner family — classic/fused/session/global/join — so the
    /jobs/:id/latency fold always sees one key shape."""
    if not config.get(ObservabilityOptions.EMISSION_LATENCY_ENABLED):
        return None
    return EmissionLatencyTracker(
        uid,
        outlier_pct=config.get(
            ObservabilityOptions.EMISSION_LATENCY_OUTLIER_PCT),
        outlier_floor_ms=config.get(
            ObservabilityOptions.EMISSION_LATENCY_OUTLIER_FLOOR_MS),
        ring_size=config.get(
            ObservabilityOptions.EMISSION_LATENCY_OUTLIER_RING),
        min_samples=config.get(
            ObservabilityOptions.EMISSION_LATENCY_OUTLIER_MIN_SAMPLES),
    )


def _fused_chunk(batch_size: int) -> int:
    """Superscan ingest chunk for a configured batch size: the next power
    of two, clamped to [256, 4096] — one policy for the classic fused
    window runner and the fused device chain, so the two paths can never
    silently drift to different dispatch geometries."""
    return min(4096, max(256, 1 << (max(batch_size, 1) - 1).bit_length()))


def _mesh_for_config(config: Configuration, key_capacity: int):
    """The job's device mesh when multichip execution applies, else None.

    parallel.mesh.enabled makes the mesh a slot resource of this process:
    the requested device count (0 = all visible) is clamped to what the
    backend exposes, then rounded DOWN to the largest divisor of the
    operator's key capacity so the contiguous key-group ranges divide
    evenly — a capacity/mesh mismatch degrades the mesh, never the
    key-range semantics. Under 2 usable devices (or a jax build without
    shard_map) the job silently stays single-chip."""
    if not config.get(ParallelOptions.MESH_ENABLED):
        return None
    from flink_tpu.utils.jax_compat import HAS_SHARD_MAP

    if not HAS_SHARD_MAP:
        import warnings

        warnings.warn(
            "parallel.mesh.enabled is set but this jax build lacks "
            "shard_map; running single-chip",
            RuntimeWarning,
        )
        return None
    import jax

    from flink_tpu.parallel.mesh import build_mesh, usable_mesh_size

    n = usable_mesh_size(config.get(ParallelOptions.MESH_DEVICES),
                         len(jax.devices()), key_capacity)
    if n <= 1:
        return None
    return build_mesh(n)


def _mesh_exchange_kwargs(config: Configuration) -> dict:
    """The skew-adaptive exchange options threaded to FusedWindowOperator
    (ignored off the mesh): the map-side combiner and the key-group
    routing table (docs/multichip.md). Single-sourced so the classic and
    traced-chain construction sites can never drift."""
    return {
        "mesh_local_combine": config.get(ParallelOptions.MESH_LOCAL_COMBINE),
        "mesh_skew_routing": config.get(ParallelOptions.MESH_SKEW_REBALANCE),
        "mesh_key_groups": config.get(ParallelOptions.MESH_KEY_GROUPS),
    }


def _latency_kwargs(config: Configuration) -> dict:
    """The latency-mode option bundle threaded to FusedWindowOperator —
    empty (NOT latency=None) when execution.latency.target-ms is off, so
    the default config constructs the operator exactly as before the mode
    existed. Single-sourced like _mesh_exchange_kwargs: both fused
    construction sites (WindowStepRunner and _init_fused) consume it."""
    from flink_tpu.config import LatencyOptions as _L

    target = config.get(_L.TARGET_MS)
    if target is None or int(target) <= 0:
        return {}
    from flink_tpu.scheduler.latency_controller import LatencySpec

    return {"latency": LatencySpec(
        target_ms=int(target),
        max_inflight=config.get(_L.MAX_INFLIGHT),
        floor_steps=config.get(_L.FLOOR_STEPS),
        readback_steps=config.get(_L.READBACK_STEPS),
        min_dwell_ms=config.get(_L.MIN_DWELL_MS),
        hysteresis_pct=config.get(_L.HYSTERESIS_PCT),
    )}


def _tier_for_config(config: Configuration):
    """The fused window path's TierConfig when the million-key state
    plane applies (state.tier.enabled), else None. Tiering needs the host
    key dictionary, so the traced-chain runner (dense device keying)
    never receives one."""
    from flink_tpu.config import StateTierOptions as _ST

    if not config.get(_ST.TIER_ENABLED):
        return None
    from flink_tpu.state.tier_manager import TierConfig

    return TierConfig(
        hot_key_capacity=config.get(_ST.HOT_KEY_CAPACITY),
        eviction_policy=config.get(_ST.EVICTION_POLICY),
        admission_min_count=config.get(_ST.ADMISSION_MIN_COUNT),
        cold_dir=config.get(_ST.COLD_DIR) or None,
        changelog_enabled=config.get(_ST.CHANGELOG_ENABLED),
        changelog_dir=config.get(_ST.CHANGELOG_DIR) or None,
        materialize_interval=config.get(_ST.CHANGELOG_MATERIALIZE_INTERVAL),
        retained_bases=config.get(_ST.CHANGELOG_RETAINED_BASES),
    )


class MeshRescaleRequested(BaseException):
    """Control-flow signal, not a failure: the run loop reached a step
    boundary with a pending mesh-rescale request. Carries the target
    device count and the step-aligned state capture the rebuilt runtime
    restores from (checkpoint rewind across device counts — the snapshot
    is canonical [K, S], so any mesh size re-shards it). BaseException so
    ordinary `except Exception` operator guards can never swallow it.

    With `routing` set this is a skew REBALANCE, not a resize: the mesh
    size stays `target` (== current) and the rebuilt runtime applies the
    new key-group -> device assignment BEFORE restoring the capture —
    placement changes ride the same exactly-once capture/restore
    machinery, and checkpoints stay canonical [K, S] throughout."""

    def __init__(self, target: int, snapshot: dict, routing=None):
        super().__init__(
            f"mesh rescale to {target} devices" if routing is None
            else f"mesh key-group rebalance over {target} devices")
        self.target = int(target)
        self.snapshot = snapshot
        self.routing = routing


def _columnarize_records(vals, where: str):
    """Record-mode (object column) → numeric column, for UDFs declared
    traceable=True: the declared contract is a numeric column function, so
    the host fallback paths must feed them the same representation the
    fused device path stages (CHAIN_FUSION is a perf switch, never a
    semantics switch). Raises if the records do not columnarize."""
    arr = np.asarray(vals.tolist() if isinstance(vals, np.ndarray)
                     else list(vals))
    if arr.dtype == object:
        raise TypeError(
            f"{where} is declared traceable=True and requires numeric "
            "record columns; these records do not columnarize — drop "
            "traceable=True to run per-record instead"
        )
    return arr


class ChainRunner(StepRunner):
    """Fused stateless chain: map/filter/flat_map applied per batch
    (OperatorChain ChainingOutput analogue, StreamingJobGraphGenerator.java:1730).

    Vectorized transforms (declared with vectorized=True at the API, plus
    map_batch) execute as whole-column array ops — the chain stays columnar
    end to end and a filter+projection before a window costs two numpy
    kernels per step instead of a Python loop per record. Scalar transforms
    fall back to per-record application; mixed chains switch representation
    at segment boundaries."""

    def __init__(self, transforms: List[Transformation]):
        self.transforms = transforms

    @staticmethod
    def _to_column(vals, columnar: bool = False) -> np.ndarray:
        """Normalize a transform's output. `columnar=True` marks the output
        of a vectorized/traceable fn, which is by contract a whole column —
        numeric arrays of ANY rank pass through (a traceable UDF written
        with jnp ops returns a jax array; objectifying its rows would
        silently de-columnarize the fusion-off fallback path)."""
        if isinstance(vals, np.ndarray):
            return vals
        arr = np.asarray(vals)
        if arr.dtype.kind in "OUSifub" and (arr.ndim == 1 or columnar):
            return arr
        return obj_array(list(vals))

    def on_batch(self, values: np.ndarray, timestamps: np.ndarray) -> None:
        vals = values
        ts = np.asarray(timestamps, dtype=np.int64)
        for t in self.transforms:
            if len(ts) == 0:
                return
            fn = t.config["fn"]
            vec = t.config.get("vectorized", False)
            if t.config.get("traceable"):
                if getattr(vals, "dtype", None) == object:
                    # fusion-off / mixed-chain fallback of a traceable UDF
                    # fed by a record-mode segment: same columnarization
                    # the fused device path performs at ingest
                    vals = _columnarize_records(vals, f"{t.kind} '{t.name}'")
                # canonical-dtype contract: the fused path computes on
                # canonical columns, so the fallback must too (same checked
                # cast — identical inputs, identical results)
                vals = canonical_column(vals, f"{t.kind} '{t.name}'")
            if t.kind == "map":
                if vec:
                    vals = self._to_column(fn(vals), columnar=True)
                else:
                    vals = obj_array([fn(v) for v in vals])
            elif t.kind == "map_ts":
                if vec:
                    vals = self._to_column(fn(vals, ts), columnar=True)
                else:
                    vals = obj_array([fn(v, int(x)) for v, x in zip(vals, ts)])
            elif t.kind == "filter":
                if vec:
                    mask = np.asarray(fn(vals), dtype=bool)
                else:
                    mask = np.fromiter(
                        (bool(fn(v)) for v in vals), dtype=bool, count=len(vals)
                    )
                vals = vals[mask]
                ts = ts[mask]
            elif t.kind == "map_batch":
                # whole-batch transform (amortized device dispatch: model
                # inference, vectorized UDFs)
                vals = self._to_column(fn(list(vals) if not vec else vals),
                                       columnar=vec)
                if len(vals) != len(ts):
                    # a hard error, not an assert: asserts vanish under
                    # `python -O`, and a 1:N map_batch would silently
                    # corrupt timestamp alignment for everything downstream
                    raise ValueError(
                        f"map_batch '{t.name}' returned {len(vals)} values "
                        f"for {len(ts)} input records; map_batch must be "
                        "1:1 (use flat_map for 1:N transforms)"
                    )
            elif t.kind == "flat_map":
                if vec:
                    out, src_idx = fn(vals)
                    vals = self._to_column(out, columnar=True)
                    ts = ts[np.asarray(src_idx, dtype=np.int64)]
                else:
                    new_vals, new_ts = [], []
                    for v, x in zip(vals, ts):
                        for out in fn(v):
                            new_vals.append(out)
                            new_ts.append(int(x))
                    vals = obj_array(new_vals)
                    ts = np.asarray(new_ts, dtype=np.int64)
            else:
                raise NotImplementedError(t.kind)
        if len(ts) and self.downstream:
            self.downstream.on_batch(vals, ts)


def _max_source_out_of_orderness(step: Step) -> Optional[int]:
    """Largest bounded-out-of-orderness delay (ms) among the source
    watermark strategies feeding `step`, walking the step DAG back to its
    source transformations. Returns None when any reachable source uses a
    generator whose bound is not statically knowable (punctuated/custom)."""
    from flink_tpu.core.watermarks import BoundedOutOfOrdernessWatermarks

    bound = 0
    seen = set()
    stack = [step]
    while stack:
        s = stack.pop()
        if id(s) in seen:
            continue
        seen.add(id(s))
        for edge in s.inputs:
            producer = edge[0]
            if isinstance(producer, Step):
                stack.append(producer)
                continue
            cfg = producer.config
            if "out_of_orderness_hint" in cfg:
                # carved stage boundary (runtime/stages.py): the channel
                # strategy only forwards watermarks, but the hint carries
                # the ORIGINAL job sources' disorder bound across it
                hint = cfg["out_of_orderness_hint"]
                if hint is None:
                    return None
                bound = max(bound, hint)
                continue
            strategy = cfg.get("watermark_strategy")
            if strategy is None:
                continue     # no watermarks: never advances event time
            gen = strategy.create_generator()
            if not isinstance(gen, BoundedOutOfOrdernessWatermarks):
                return None
            bound = max(bound, gen._delay)
    return bound


def _session_disorder_within_gap(step: Step, assigner) -> bool:
    """Device-session routing gate: the device operator's late contract
    (drop records whose standalone session is already expired) matches the
    merging oracle only while watermark out-of-orderness stays BELOW the
    session gap — with bound >= gap a record can arrive late enough that
    the oracle would still merge it into an open session the device path
    already expired, i.e. silent data loss. Refuse the device operator for
    such pipelines and fall back to the oracle with a warning.

    Deliberate fail-OPEN on an unknowable bound (custom/punctuated
    generators return None): demoting those would leave users of custom
    strategies no way to ever select the device operator, and the common
    in-repo opaque case (stage boundaries) now carries the real bound via
    out_of_orderness_hint. A custom generator's author owns keeping its
    effective lag below the session gap — the DEVICE_SESSIONS option
    description states the contract; set it false to force the oracle."""
    bound = _max_source_out_of_orderness(step)
    if bound is None or bound < assigner.gap:
        return True
    import warnings

    warnings.warn(
        f"session windows: watermark out-of-orderness bound ({bound} ms) >= "
        f"session gap ({assigner.gap} ms) — using the per-record oracle "
        "operator instead of the device session operator, whose late "
        "contract would silently drop records the oracle merges. Shrink the "
        "out-of-orderness bound below the gap to re-enable the device path, "
        "or set execution.window.device-sessions false to silence this.",
        RuntimeWarning,
    )
    return False


class WindowStepRunner(StepRunner):
    """Keyed window aggregation step wrapping the device or oracle operator."""

    def __init__(self, step: Step, config: Configuration):
        t = step.terminal
        cfg = t.config
        assigner = cfg["assigner"]
        aggregate = cfg["aggregate"]
        self.key_selector = cfg["key_selector"]
        self.key_vectorized = cfg.get("key_vectorized", False)
        self.key_traceable = cfg.get("key_traceable", False)
        self.value_fn = cfg.get("value_fn") or (lambda v: v)
        self.value_vectorized = cfg.get("value_vectorized", False) and cfg.get("value_fn")
        self.window_fn = cfg.get("window_fn")
        device_agg = resolve(aggregate)
        use_device = (
            device_agg is not None
            and assigner.slice_ms is not None
            and assigner.is_event_time
            and cfg.get("trigger") is None
            and cfg.get("evictor") is None
            and self.window_fn is None
        )
        max_par = config.get(PipelineOptions.MAX_PARALLELISM)
        from flink_tpu.ops.aggregators import ONE

        self._needs_value = device_agg is None or any(
            f.source != ONE for f in device_agg.fields
        )
        from flink_tpu.api.windowing.assigners import EventTimeSessionWindows, GlobalWindows
        from flink_tpu.runtime.tpu_global_window_operator import (
            TpuGlobalWindowOperator,
            supported_trigger,
        )

        count_spec = supported_trigger(cfg.get("trigger"))
        use_fused = (
            use_device
            and cfg["allowed_lateness"] == 0
            and not cfg["side_output_late"]
            and config.get(ExecutionOptions.FUSED_WINDOWS)
            and all(f.scatter in ("add", "min", "max") for f in device_agg.fields)
        )
        if (
            isinstance(assigner, GlobalWindows)
            and device_agg is not None
            and count_spec is not None
            and cfg.get("evictor") is None
            and self.window_fn is None
        ):
            n, purging = count_spec
            self.op = TpuGlobalWindowOperator(
                device_agg,
                count_n=n,
                purging=purging,
                key_capacity=config.get(ExecutionOptions.KEY_CAPACITY),
            )
            self.device = True
        elif (
            isinstance(assigner, EventTimeSessionWindows)
            and device_agg is not None
            and assigner.is_event_time
            and config.get(ExecutionOptions.DEVICE_SESSIONS)
            and cfg.get("trigger") is None
            and cfg.get("evictor") is None
            and self.window_fn is None
            and cfg["allowed_lateness"] == 0
            and not cfg["side_output_late"]
            and _session_disorder_within_gap(step, assigner)
        ):
            # device-path sessions: per-slice fragments + vectorized
            # gap-merge (the MergingWindowSet re-design; see
            # runtime/tpu_session_operator.py)
            from flink_tpu.runtime.tpu_session_operator import (
                TpuSessionWindowOperator,
            )

            self.op = TpuSessionWindowOperator(
                assigner,
                device_agg,
                key_capacity=min(1 << 10, config.get(ExecutionOptions.KEY_CAPACITY)),
            )
            self.device = True
        elif use_fused:
            # the flagship path: T-step compiled superscan, one dispatch +
            # one async readback per superbatch (WindowOperatorBuilder.java:79
            # buildAsyncWindowOperator :472 is the reference's swap precedent)
            from flink_tpu.runtime.fused_window_operator import FusedWindowOperator

            batch_size = config.get(ExecutionOptions.BATCH_SIZE)
            # only the fused operator's drain is a blocking device readback
            # (deferred superbatch resolution); everywhere else drain is a
            # host list swap and timing it would inflate deviceDispatches
            self._drain_resolves_device = True
            # start small, grow by doubling with the key dictionary —
            # superscan cost scales with key capacity, so tiny jobs must
            # not pay for the configured maximum up front. With the state
            # tier enabled (state.tier.*) capacity is FIXED at the hot
            # cap instead: the vocabulary evicts, capacity never grows.
            tier = _tier_for_config(config)
            if tier is not None:
                capacity = tier.hot_key_capacity
            else:
                capacity = min(1 << 10,
                               config.get(ExecutionOptions.KEY_CAPACITY))
            self.op = FusedWindowOperator(
                assigner,
                device_agg,
                key_capacity=capacity,
                superbatch_steps=config.get(ExecutionOptions.SUPERBATCH_STEPS),
                chunk=_fused_chunk(batch_size),
                columnar_output=config.get(ExecutionOptions.COLUMNAR_OUTPUT),
                # multichip (parallel.mesh.*): the same fused operator runs
                # SPMD over the mesh; None keeps today's single-chip path
                mesh=_mesh_for_config(config, capacity),
                tier=tier,
                **_mesh_exchange_kwargs(config),
                **_latency_kwargs(config),
            )
            self.device = True
        elif use_device:
            # the per-batch classic path honors the state tier too, via
            # its grow-only hot/cold id split (ids past the hot cap
            # aggregate in the cold tier) — no vocabulary/eviction here,
            # but HBM stays bounded when the fused path is switched off
            tier = _tier_for_config(config)
            tier_kwargs = {}
            if tier is not None and cfg["allowed_lateness"] == 0:
                tier_kwargs = dict(
                    hot_key_capacity=tier.hot_key_capacity,
                    cold_tier_dir=tier.cold_dir,
                )
            self.op = TpuWindowOperator(
                assigner,
                device_agg,
                allowed_lateness=cfg["allowed_lateness"],
                key_capacity=config.get(ExecutionOptions.KEY_CAPACITY),
                emit_late_to_side_output=cfg["side_output_late"],
                columnar_output=config.get(ExecutionOptions.COLUMNAR_OUTPUT),
                **tier_kwargs,
            )
            self.device = True
        else:
            agg_fn = aggregate
            if device_agg is not None and not isinstance(aggregate, AggregateFunction):
                agg_fn = device_agg.python_equivalent()
            self.op = OracleWindowOperator(
                assigner,
                agg_fn,
                trigger=cfg.get("trigger"),
                allowed_lateness=cfg["allowed_lateness"],
                max_parallelism=max_par,
                window_function=self.window_fn,
                evictor=cfg.get("evictor"),
                emit_late_to_side_output=cfg["side_output_late"],
            )
            self.device = False
        self.processing_time = not assigner.is_event_time
        self.uid = t.uid
        # SQL-originated window steps (flink_tpu/planner lowering) are
        # marked so the job can report which execution path SQL selected
        # (job.sqlFusedSelected gauge + /jobs/:id visibility)
        self.sql_origin = bool(cfg.get("sql_origin"))
        # per-fused-stage device-time attribution (host clock around the
        # already-synchronous dispatch/readback sections; never adds syncs)
        self._drain_resolves_device = getattr(
            self, "_drain_resolves_device", False)
        self.device_timer = (
            DeviceTimer()
            if self.device and config.get(ObservabilityOptions.DEVICE_TIMING_ENABLED)
            else None
        )
        self._init_device_stats(config)
        self._init_emission_plane(config)

    def _init_emission_plane(self, config: Configuration) -> None:
        """Emission-latency plane (observability.emission-latency.*).
        Device operators stamp INLINE at their own deferred-resolve /
        fire-loop sites (the host-visibility instant of a fired window);
        the host oracle has no tracker surface, so the runner stamps its
        drained rows instead — drain IS the oracle's visibility point."""
        self.emission_tracker = make_emission_tracker(self.uid, config)
        self._emission_lateness = getattr(self.op, "allowed_lateness", 0)
        self._emission_at_drain = False
        if self.emission_tracker is not None:
            if hasattr(type(self.op), "emission_tracker"):
                self.op.emission_tracker = self.emission_tracker
            else:
                self._emission_at_drain = True

    def _init_device_stats(self, config: Configuration) -> None:
        """Device-plane observability (metrics/device_stats.py + key_stats):
        a CompileTracker wrapped around the operator's jit entry points
        (operators without the attach surface — oracle, session, global —
        simply skip it) and a throttled key-stats collector over the
        operator's device-resident per-key counts. Gated like device
        timing; per-batch host cost is one clock compare."""
        self.device_stats = None
        self.key_stats = None
        self._roofline_peaks = None
        if not (self.device
                and config.get(ObservabilityOptions.DEVICE_STATS_ENABLED)):
            return
        from flink_tpu.metrics.device_stats import (
            CompileTracker,
            platform_peaks,
        )

        attach = getattr(self.op, "attach_device_stats", None)
        if attach is not None:
            tracker = CompileTracker(
                history_size=config.get(
                    ObservabilityOptions.DEVICE_RECOMPILE_HISTORY_SIZE),
                storm_threshold=config.get(
                    ObservabilityOptions.DEVICE_RECOMPILE_STORM_THRESHOLD),
                storm_window_ms=config.get(
                    ObservabilityOptions.DEVICE_RECOMPILE_STORM_WINDOW_MS),
                cost_analysis=config.get(
                    ObservabilityOptions.DEVICE_COST_ANALYSIS_ENABLED),
                memory_analysis=config.get(
                    ObservabilityOptions.DEVICE_MEMORY_ANALYSIS_ENABLED),
            )
            attach(tracker)
            self.device_stats = tracker
            self._roofline_peaks = platform_peaks(
                config.get(ObservabilityOptions.DEVICE_HBM_GBPS),
                config.get(ObservabilityOptions.DEVICE_PEAK_TFLOPS))
        loads_fn = getattr(self.op, "key_loads", None)
        if loads_fn is not None:
            from flink_tpu.config import PipelineOptions as _PO
            from flink_tpu.metrics.key_stats import KeyStatsCollector

            self.key_stats = KeyStatsCollector(
                loads_fn,
                num_key_groups=config.get(_PO.MAX_PARALLELISM),
                top_k=config.get(
                    ObservabilityOptions.DEVICE_KEY_STATS_TOP_K),
                row_bytes_fn=getattr(self.op, "state_row_bytes", None),
                ready_fn=getattr(self.op, "key_stats_ready", None),
                interval_ms=config.get(
                    ObservabilityOptions.DEVICE_KEY_STATS_INTERVAL_MS),
                # mesh operators additionally expose per-device local
                # loads, so the skew fold sees the worst DEVICE too;
                # single-chip operators keep a clean gauge surface
                mesh_loads_fn=(
                    getattr(self.op, "per_device_key_loads", None)
                    if getattr(self.op, "mesh_devices", lambda: 1)() > 1
                    else None),
            )

    def _device_stats_tick(self) -> None:
        if self.key_stats is not None:
            self.key_stats.maybe_collect()

    def device_roofline(self) -> Dict[str, float]:
        """hbmUtilizationPct / flopsUtilizationPct over the DeviceTimer's
        measured device wall time (0.0 when either side is ungated)."""
        from flink_tpu.metrics.device_stats import roofline_pct

        tracker, timer = self.device_stats, self.device_timer
        if tracker is None or timer is None or self._roofline_peaks is None:
            return {"hbmUtilizationPct": 0.0, "flopsUtilizationPct": 0.0}
        hbm, tflops = self._roofline_peaks
        return roofline_pct(tracker.bytes_accessed_total(),
                            tracker.flops_total(), timer.total_s,
                            hbm, tflops)

    def on_batch(self, values: np.ndarray, timestamps: np.ndarray) -> None:
        # chaos seam (device dispatch boundary): one is-None check per
        # batch when chaos is off; an injected error surfaces exactly like
        # a real dispatch failure and rides the normal restart path
        hook = _chaos.HOOK
        if hook is not None:
            hook("device", self.uid)
        if self.key_traceable and len(timestamps):
            # fusion-off fallback of a traceable program: columnarize
            # record-mode sources and cast to the canonical dtype exactly
            # like the fused ingest would, so CHAIN_FUSION stays a perf
            # switch, never a semantics switch
            if getattr(values, "dtype", None) == object:
                values = _columnarize_records(values, "key_by selector")
            values = canonical_column(values, "key_by selector input")
        if self.device:
            if self.key_vectorized:
                keys = np.asarray(self.key_selector(values))
            else:
                raw_keys = [self.key_selector(v) for v in values]
                keys = np.asarray(raw_keys)
                if keys.ndim != 1 or keys.dtype.kind not in "iuUS":
                    keys = obj_array(raw_keys)
            # typed key columns (int/str) unlock the native C++ dictionary
            if keys.ndim != 1 or keys.dtype.kind not in "iuUSO":
                keys = obj_array(list(keys))
            if self._needs_value:
                if self.value_vectorized:
                    nums = np.asarray(self.value_fn(values), dtype=np.float32)
                else:
                    nums = np.asarray(
                        [self.value_fn(v) for v in values], dtype=np.float32
                    )
            else:  # pure-count aggregates ignore the value column
                nums = np.zeros(len(values), dtype=np.float32)
            if self.device_timer is not None:
                with self.device_timer.section():
                    self.op.process_batch(keys, nums, timestamps)
            else:
                self.op.process_batch(keys, nums, timestamps)
            self._device_stats_tick()
        else:
            if self.processing_time:
                # PT windows: assignment & timers use wall clock, not event ts
                now = int(time.time() * 1000)
                timestamps = np.full(len(values), now, dtype=np.int64)
            # vectorized selectors see a one-row column per record here;
            # np.asarray on the result keeps jnp-written (traceable) fns
            # usable — a bare jax scalar is unhashable as an oracle key
            key_of = (
                (lambda v: np.asarray(
                    self.key_selector(np.asarray(v)[None, ...]))[0])
                if self.key_vectorized
                else self.key_selector
            )
            val_of = (
                (lambda v: np.asarray(
                    self.value_fn(np.asarray(v)[None, ...]))[0])
                if self.value_vectorized
                else self.value_fn
            )
            for v, ts in zip(values, timestamps):
                self.op.process_record(key_of(v), val_of(v), int(ts))
            if self.processing_time:
                self.op.advance_processing_time(int(time.time() * 1000))
                self._drain()

    def on_watermark(self, watermark: int) -> None:
        if self.device and self.key_stats is not None:
            # fold BEFORE the watermark's purge sweep so a due collection
            # sees the state the advance is about to retire
            self._device_stats_tick()
        if self.device_timer is not None:
            with self.device_timer.section():
                self.op.process_watermark(watermark)
        else:
            self.op.process_watermark(watermark)
        self._drain()
        # fused operators emit asynchronously (superbatch granularity):
        # forward only the watermark their resolved output already covers,
        # so downstream never sees a watermark ahead of pending fires
        safe = getattr(self.op, "emitted_watermark", None)
        if safe is not None:
            watermark = min(watermark, safe)
        if watermark > MIN_WATERMARK:
            self._forward_watermark(watermark)

    def _forward_watermark(self, watermark: int) -> None:
        if self.downstream:
            self.downstream.on_watermark(watermark)
        if self.sides:
            for f in self.sides.values():
                f.on_watermark(watermark)

    def on_end(self) -> None:
        self._drain()
        super().on_end()

    def on_processing_time(self, now_ms: int) -> None:
        # PT windows fire from the shared ProcessingTimeService tick, not
        # only when their own source produces a batch
        self._device_stats_tick()
        if self.processing_time:
            self.op.advance_processing_time(now_ms)
            self._drain()

    def _drain(self) -> None:
        op_sides = getattr(self.op, "side_output", None)
        if op_sides:
            for tag_id, rows in list(op_sides.items()):
                if rows and self.sides and tag_id in self.sides:
                    vals = obj_array([(k, v) for (k, v, _t) in rows])
                    tss = np.asarray([t for (_k, _v, t) in rows], dtype=np.int64)
                    self.emit_side(tag_id, vals, tss)
                # rows without a consumer are dropped, not accumulated
                op_sides[tag_id] = []
        if self.device_timer is not None and self._drain_resolves_device:
            # the fused operator resolves deferred dispatches here — drain
            # IS the blocking readback section; other operators' drain is a
            # host list swap and is deliberately not timed
            with self.device_timer.section():
                out = self.op.drain_output()
        else:
            out = self.op.drain_output()
        if out and self._emission_at_drain:
            tr, lateness = self.emission_tracker, self._emission_lateness
            for _k, w, _r, t in out:
                tr.record_fire(getattr(w, "end", int(t) + 1),
                               lateness_ms=lateness)
        if out and self.downstream:
            vals = obj_array(
                [
                    r if (self.window_fn is not None or k is None) else (k, r)
                    for (k, _w, r, _t) in out
                ]
            )
            ts = np.asarray([t for (_k, _w, _r, t) in out], dtype=np.int64)
            self.downstream.on_batch(vals, ts)

    def register_metrics(self, group) -> None:
        super().register_metrics(group)
        group.gauge("numLateRecordsDropped",
                    lambda: self.op.num_late_records_dropped,
                    fold="sum", kind="counter")

        def _wm():
            return getattr(
                self.op,
                "current_watermark",
                getattr(getattr(self.op, "timer_service", None),
                        "current_watermark", 0),
            )

        # watermark position: the job-level combined watermark is what
        # EVERY subtask has reached, so the fold is MIN
        group.gauge("currentWatermark", _wm, fold="min")
        if self.emission_tracker is not None:
            # emission-latency plane: flat log-bucket snapshot (declared
            # "emission" — folds bucket-wise EXACTLY across shards) +
            # wall-vs-watermark lag (worst shard -> MAX)
            group.gauge("emissionLatencyMs", self.emission_tracker.snapshot,
                        fold="emission", kind="histogram")
            group.gauge("watermarkLagMs", lambda: watermark_lag_ms(_wm()),
                        fold="max")
        if self.device_timer is not None:
            self.device_timer._hist = group.histogram("deviceDispatchMs")
            self.device_timer.register(group)
        state_bytes = getattr(self.op, "state_bytes", None)
        if state_bytes is not None:
            # HBM-resident state footprint of this operator's device arrays
            group.gauge("stateBytes", state_bytes, fold="sum")
        key_count = getattr(self.op, "state_key_count", None)
        if key_count is not None:
            group.gauge("stateKeyCount", key_count, fold="sum")
        # device plane: compile counters, roofline, phase counters, key
        # telemetry — all on the operator scope so laggard kernels are
        # attributable per step
        if self.device_stats is not None:
            self.device_stats.register(group)
            # roofline fractions are each shard's own chip's view -> MEAN
            group.gauge("hbmUtilizationPct",
                        lambda: self.device_roofline()["hbmUtilizationPct"],
                        fold="mean")
            group.gauge("flopsUtilizationPct",
                        lambda: self.device_roofline()["flopsUtilizationPct"],
                        fold="mean")
            phases = getattr(self.op, "phase_totals", None)
            if callable(phases):
                group.gauge("phaseIngestRecords",
                            lambda: phases()["ingestRecords"],
                            fold="sum", kind="counter")
                group.gauge("phaseFireSteps",
                            lambda: phases()["fireSteps"],
                            fold="sum", kind="counter")
                group.gauge("phasePurgeSteps",
                            lambda: phases()["purgeSteps"],
                            fold="sum", kind="counter")
        if self.key_stats is not None:
            self.key_stats.register(group)
        # state-tier gauges (state/tier_manager.py): counters/sizes SUM
        # across shards — each shard owns its key range; tierHotFillRatio
        # (a per-shard fraction) MEANs. Eviction/promotion totals are
        # monotone, so the history plane records them as churn RATES.
        tier_gauges = getattr(self.op, "tier_gauges", None)
        if callable(tier_gauges) and tier_gauges() is not None:
            for key, kind in (("vocabSize", None), ("residentKeys", None),
                              ("evictions", "counter"),
                              ("promotions", "counter"),
                              ("spilledBytes", "counter"),
                              ("changelogBytes", "counter")):
                group.gauge(key, lambda k=key: self.op.tier_gauges().get(k),
                            fold="sum", kind=kind)
            group.gauge("tierHotFillRatio",
                        lambda: self.op.tier_gauges().get("tierHotFillRatio"),
                        fold="mean")
        # latency-mode controller gauges (execution.latency.target-ms):
        # registered only when the mode is on, folded MAX across shards
        # (the deepest rung / fullest ring / most geometries is the job's
        # latency view) — the controller's rung/ring/ladder decisions
        # surface in /jobs/:id/device and /latency
        latency_gauges = getattr(self.op, "latency_gauges", None)
        if callable(latency_gauges) and latency_gauges() is not None:
            for key in ("latencyModeActive", "currentBatchRung",
                        "inflightDepth", "ladderRecompiles"):
                group.gauge(key,
                            lambda k=key: self.op.latency_gauges().get(k),
                            fold="max")

    def snapshot(self) -> dict:
        return {"operator": self.op.snapshot()}

    def restore(self, snap: dict) -> None:
        self.op.restore(snap["operator"])


class DeviceChainRunner(WindowStepRunner):
    """Whole-graph fusion runner (graph/fusion.py): one runner for a fused
    device chain — the traceable map/filter/map_ts prologue, key/value
    extraction, and the windowed aggregation compile into ONE jitted
    multi-step device program (`lax.scan` over T batches) with
    device-resident intermediates. Raw source columns are the only thing
    the host stages; the post-transform columns, key column and value
    column never materialize host-side.

    This is the reference's operator chaining taken to its TPU-native
    conclusion (StreamingJobGraphGenerator chains operators into direct
    calls; XLA chains them into one program). Inherits the watermark
    clamping, drain, metrics, and snapshot surfaces of WindowStepRunner —
    only construction and ingest differ."""

    def __init__(self, step: Step, plan, config: Configuration):
        self._init_fused(plan.terminal, plan.transforms, config)

    def _init_fused(self, t, transforms, config: Configuration,
                    assigners=None) -> None:
        """Shared construction of the fused device surface (also used by
        SharedWindowRunner, which passes the group's `assigners` — any new
        option threaded to FusedWindowOperator lands on both paths)."""
        from flink_tpu.runtime.fused_window_operator import FusedWindowOperator
        from flink_tpu.runtime.fused_window_pipeline import TracedPrologue

        cfg = t.config
        prologue = TracedPrologue(
            transforms=tuple(
                (tr.kind, tr.config["fn"]) for tr in transforms),
            key_fn=cfg["key_selector"],
            value_fn=cfg.get("value_fn"),
        )
        batch_size = config.get(ExecutionOptions.BATCH_SIZE)
        # dense device keying cannot grow mid-dispatch: capacity is the
        # configured bound, and an out-of-range traced key raises at
        # resolve (never silently aliases another key's row)
        capacity = config.get(ExecutionOptions.KEY_CAPACITY)
        self.op = FusedWindowOperator(
            None if assigners is not None else cfg["assigner"],
            cfg["aggregate"],
            key_capacity=capacity,
            superbatch_steps=config.get(ExecutionOptions.SUPERBATCH_STEPS),
            chunk=_fused_chunk(batch_size),
            columnar_output=config.get(ExecutionOptions.COLUMNAR_OUTPUT),
            prologue=prologue,
            # multichip SPMD (parallel.mesh.*): the fused USER job — not a
            # hand-built kernel — shards over the mesh; the traced prologue
            # runs on each device's slice and one in-scan all-to-all per
            # step is the keyBy exchange
            mesh=_mesh_for_config(config, capacity),
            **_mesh_exchange_kwargs(config),
            **_latency_kwargs(config),
            **({} if assigners is None else {"assigners": list(assigners)}),
        )
        self.device = True
        self.window_fn = None
        self.processing_time = False
        self.uid = t.uid
        self.sql_origin = bool(cfg.get("sql_origin"))
        self._drain_resolves_device = True
        self.device_timer = (
            DeviceTimer()
            if config.get(ObservabilityOptions.DEVICE_TIMING_ENABLED)
            else None
        )
        self._init_device_stats(config)
        self._init_emission_plane(config)
        self._warned_object_columns = False

    def on_batch(self, values: np.ndarray, timestamps: np.ndarray) -> None:
        hook = _chaos.HOOK   # chaos seam: fused-chain dispatch boundary
        if hook is not None:
            hook("device", self.uid)
        if len(timestamps) == 0:
            return   # idle poll / watermark-only step: nothing to stage
        vals = values
        if getattr(vals, "dtype", None) == object or not isinstance(vals, np.ndarray):
            # record-mode source: one columnarization pass per batch. A
            # columnar source (numeric ndarray batches) or the binary wire
            # (frombuffer views, runtime/stages.py) skips this entirely.
            if not self._warned_object_columns:
                self._warned_object_columns = True
                import warnings

                warnings.warn(
                    "fused device chain fed by a record-mode source: paying "
                    "a per-batch columnarization pass; switch the source to "
                    "columnar numeric batches to feed the device directly",
                    RuntimeWarning,
                )
            vals = _columnarize_records(vals, "fused device chain")
        else:
            vals = as_device_column(vals)
        if self.device_timer is not None:
            with self.device_timer.section():
                self.op.process_raw_batch(vals, timestamps)
        else:
            self.op.process_raw_batch(vals, timestamps)
        self._device_stats_tick()


class SharedWindowSiblingRunner(StepRunner):
    """Placeholder runner for a non-leader member of a shared-partial
    window group (graph/window_sharing.py): it owns the member's
    downstream edges, and the group leader pushes this member's resolved
    emissions, watermarks, and end-of-input into them. Its own input
    edges are never wired (the leader consumes the stream once — wiring
    them would double-ingest), so every on_* here is unreachable."""

    def __init__(self, step: Step, spec: int):
        self.uid = step.terminal.uid
        self.spec = spec
        self.sql_origin = bool(step.terminal.config.get("sql_origin"))

    def on_batch(self, values: np.ndarray, timestamps: np.ndarray) -> None:
        raise AssertionError(
            "shared-window sibling received a direct batch; its input "
            "edges must not be wired")


class SharedWindowRunner(DeviceChainRunner):
    """Shared-partials runner (graph/window_sharing.py): ONE traced device
    program serves N correlated window() siblings — gcd-granule partials
    ingest once, every member window fires its own slice run from the
    shared ring (Factor Windows), and each member's emissions route to
    its own downstream edges through its sibling runner. Construction
    mirrors DeviceChainRunner (the sharing bar equals the fusion bar);
    only emission routing and watermark/end fan-out differ."""

    def __init__(self, step: Step, shared_plan, config: Configuration):
        self.shared_plan = shared_plan
        self._init_fused(shared_plan.terminals[0], shared_plan.transforms,
                         config, assigners=shared_plan.assigners)
        # spec index -> the runner owning that member's downstream edges
        # (spec 0 = this leader); siblings register in build_runners
        self.member_runners: List[StepRunner] = [self]

    def _spec_fanouts(self):
        for spec, r in enumerate(self.member_runners):
            yield spec, r.downstream, (r.sides or None)

    def _drain(self) -> None:
        if self.device_timer is not None and self._drain_resolves_device:
            with self.device_timer.section():
                drained = [self.op.drain_spec_output(s)
                           for s in range(len(self.member_runners))]
        else:
            drained = [self.op.drain_spec_output(s)
                       for s in range(len(self.member_runners))]
        for spec, fan, _sides in self._spec_fanouts():
            out = drained[spec]
            if out and fan:
                # same record shape as the base _drain: columnar-output
                # entries (k is None) forward the bare device triple —
                # sharing must never change what downstream receives
                vals = obj_array([r if k is None else (k, r)
                                  for (k, _w, r, _t) in out])
                ts = np.asarray([t for (_k, _w, _r, t) in out],
                                dtype=np.int64)
                fan.on_batch(vals, ts)

    def _forward_watermark(self, watermark: int) -> None:
        for _spec, fan, sides in self._spec_fanouts():
            if fan:
                fan.on_watermark(watermark)
            if sides:
                for f in sides.values():
                    f.on_watermark(watermark)

    def on_marker(self, wall_ms: float) -> None:
        # markers fan out to EVERY member's downstream, like watermarks —
        # sharing must not blind the sibling sinks' latency histograms
        h = getattr(self, "_marker_hist", None)
        if h is not None:
            h.update(time.time() * 1000.0 - wall_ms)
        for _spec, fan, sides in self._spec_fanouts():
            if fan:
                fan.on_marker(wall_ms)
            if sides:
                for f in sides.values():
                    f.on_marker(wall_ms)

    def on_end(self) -> None:
        self._drain()
        for _spec, fan, sides in self._spec_fanouts():
            if fan:
                fan.on_end()
            if sides:
                for f in sides.values():
                    f.on_end()


class KeyedReduceRunner(StepRunner):
    """Rolling keyed reduce (KeyedStream.reduce): emits the running reduce
    per input record (reference: StreamGroupedReduceOperator semantics)."""

    def __init__(self, step: Step, config: Configuration):
        t = step.terminal
        self.key_selector = t.config["key_selector"]
        self.reduce_fn = t.config["reduce_fn"]
        max_par = config.get(PipelineOptions.MAX_PARALLELISM)
        self.state = HeapKeyedStateBackend(KeyGroupRange(0, max_par - 1), max_par)
        self.state.register(value_state("rolling"))
        self.uid = t.uid

    def on_batch(self, values: np.ndarray, timestamps: np.ndarray) -> None:
        out = []
        for v in values:
            key = self.key_selector(v)
            self.state.set_current_key(key)
            cur = self.state.get("rolling")
            nxt = v if cur is None else self.reduce_fn(cur, v)
            self.state.put("rolling", nxt)
            out.append(nxt)
        if out and self.downstream:
            self.downstream.on_batch(obj_array(out), timestamps)

    def snapshot(self) -> dict:
        return {"state": self.state.snapshot()}

    def restore(self, snap: dict) -> None:
        self.state.restore(snap["state"])


class KeyedProcessRunner(StepRunner):
    """KeyedProcessFunction with event-time timers (oracle path)."""

    def __init__(self, step: Step, config: Configuration):
        t = step.terminal
        self.key_selector = t.config["key_selector"]
        self._init_keyed(t, config)

    def _init_keyed(self, t: Transformation, config: Configuration) -> None:
        self.fn: ProcessFunction = t.config["process_fn"]
        max_par = config.get(PipelineOptions.MAX_PARALLELISM)
        self.state = HeapKeyedStateBackend(
            KeyGroupRange(0, max_par - 1), max_par, auto_register=True)
        self.timers = InternalTimerService(
            self._on_event_timer, self._on_proc_timer)  # both bind dynamically
        self._out: List = []
        self._out_ts: List[int] = []
        self._side_buf: Dict[str, tuple] = {}
        self.uid = t.uid

    class _TimerService:
        def __init__(self, runner, key):
            self._r = runner
            self._key = key

        def register_event_time_timer(self, time: int) -> None:
            self._r.timers.register_event_time_timer(self._key, None, time)

        def register_processing_time_timer(self, time: int) -> None:
            self._r.timers.register_processing_time_timer(self._key, None, time)

        def current_watermark(self) -> int:
            return self._r.timers.current_watermark

        def state(self):
            return self._r.state

    def _ctx(self, key, timestamp):
        def side(tag, value):
            tag_id = getattr(tag, "tag_id", tag)
            buf = self._side_buf.setdefault(tag_id, ([], []))
            buf[0].append(value)
            buf[1].append(timestamp)

        return ProcessFunction.Context(timestamp, self._TimerService(self, key), side)

    def _on_event_timer(self, time, key, _ns) -> None:
        self.state.set_current_key(key)
        on_timer = getattr(self.fn, "on_timer", None)
        if on_timer is None:
            return
        for out in on_timer(time, self._ctx(key, time)):
            self._out.append(out)
            self._out_ts.append(time)

    def _on_proc_timer(self, time, key, _ns) -> None:
        """Same user callback (onTimer), but outputs carry NO event
        timestamp (MIN_TIMESTAMP sentinel) — the reference erases
        timestamps on processing-time timer output rather than leaking
        wall-clock epochs into the event-time domain."""
        self.state.set_current_key(key)
        on_timer = getattr(self.fn, "on_timer", None)
        if on_timer is None:
            return
        for out in on_timer(time, self._ctx(key, time)):
            self._out.append(out)
            self._out_ts.append(MIN_TIMESTAMP)

    def on_processing_time(self, now_ms: int) -> None:
        self.timers.advance_processing_time(now_ms)
        self._flush()

    def on_batch(self, values: np.ndarray, timestamps: np.ndarray) -> None:
        for v, ts in zip(values, timestamps):
            key = self.key_selector(v)
            self.state.set_current_key(key)
            for out in self.fn.process_element(v, self._ctx(key, int(ts))):
                self._out.append(out)
                self._out_ts.append(int(ts))
        self._flush()

    def on_watermark(self, watermark: int) -> None:
        self.timers.advance_watermark(watermark)
        self._flush()
        super().on_watermark(watermark)

    def _flush(self):
        if self._out:
            if self.downstream:
                self.downstream.on_batch(
                    obj_array(self._out),
                    np.asarray(self._out_ts, dtype=np.int64))
            # clear even without a consumer (a step may be reachable only
            # through its side output) — unconsumed output must not pile up
            self._out, self._out_ts = [], []
        if self._side_buf:
            for tag_id, (vals, tss) in self._side_buf.items():
                if vals:
                    self.emit_side(
                        tag_id, obj_array(vals),
                        np.asarray(tss, dtype=np.int64))
            self._side_buf = {}

    def snapshot(self) -> dict:
        return {"state": self.state.snapshot(), "timers": self.timers.snapshot()}

    def restore(self, snap: dict) -> None:
        self.state.restore(snap["state"])
        self.timers.restore(snap["timers"])


class CepRunner(StepRunner):
    """Keyed CEP pattern-matching step (CepOperator.java:83 analogue)."""

    def __init__(self, step: Step, config: Configuration):
        from flink_tpu.cep.operator import CepOperator

        t = step.terminal
        self.key_selector = t.config["key_selector"]
        self.op = CepOperator(t.config["pattern"], t.config.get("select_fn"))
        self.uid = t.uid

    def on_batch(self, values: np.ndarray, timestamps: np.ndarray) -> None:
        for v, ts in zip(values, timestamps):
            self.op.process_record(self.key_selector(v), v, int(ts))

    def on_watermark(self, watermark: int) -> None:
        self.op.process_watermark(watermark)
        out = self.op.drain_output()
        if out and self.downstream:
            vals = obj_array([r for (_k, _w, r, _t) in out])
            ts = np.asarray([t for (_k, _w, _r, t) in out], dtype=np.int64)
            self.downstream.on_batch(vals, ts)
        super().on_watermark(watermark)

    def snapshot(self) -> dict:
        return {"operator": self.op.snapshot()}

    def restore(self, snap: dict) -> None:
        self.op.restore(snap["operator"])


class UnionRunner(StepRunner):
    """N-way stream union: batches pass through; the base-class valve
    min-combines the input watermarks (DataStream.union, UnionTransformation
    — the reference wires union as extra input edges; here an explicit
    pass-through gate keeps the valve bookkeeping in one place)."""

    def __init__(self, step: Step):
        self.num_inputs = len(step.inputs)
        self.uid = step.terminal.uid

    def on_batch(self, values: np.ndarray, timestamps: np.ndarray) -> None:
        if self.downstream:
            self.downstream.on_batch(values, timestamps)


class CoMapRunner(StepRunner):
    """Non-keyed connected-stream transform: fn1 on input 0, fn2 on input 1
    (ConnectedStreams.map/flatMap, CoStreamMap/CoStreamFlatMap analogue)."""

    num_inputs = 2

    def __init__(self, step: Step):
        t = step.terminal
        self.fns = (t.config["fn1"], t.config["fn2"])
        self.flat = t.kind == "co_flat_map"
        self.uid = t.uid

    def on_batch_n(self, ordinal: int, values, timestamps) -> None:
        fn = self.fns[ordinal]
        ts = np.asarray(timestamps, dtype=np.int64)
        if self.flat:
            out, out_ts = [], []
            for v, tt in zip(values, ts):
                for o in fn(v):
                    out.append(o)
                    out_ts.append(int(tt))
            if out and self.downstream:
                self.downstream.on_batch(
                    obj_array(out), np.asarray(out_ts, dtype=np.int64))
        else:
            if len(ts) and self.downstream:
                self.downstream.on_batch(obj_array([fn(v) for v in values]), ts)

    def on_batch(self, values, timestamps) -> None:  # pragma: no cover
        raise AssertionError("CoMapRunner consumes via input gates")


class KeyedCoProcessRunner(KeyedProcessRunner):
    """Keyed two-input process function with shared per-key state and
    event-time timers (KeyedCoProcessFunction / CoProcessOperator analogue:
    both inputs key into the SAME state backend, which is the whole point of
    connect() vs union()). Inherits context/timer/flush/snapshot machinery
    from KeyedProcessRunner; only the two-gate dispatch differs."""

    num_inputs = 2

    def __init__(self, step: Step, config: Configuration):
        t = step.terminal
        self.key_selectors = (t.config["key_selector1"], t.config["key_selector2"])
        self._init_keyed(t, config)

    def on_batch_n(self, ordinal: int, values, timestamps) -> None:
        ks = self.key_selectors[ordinal]
        process = (self.fn.process_element1 if ordinal == 0
                   else self.fn.process_element2)
        for v, ts in zip(values, np.asarray(timestamps, dtype=np.int64)):
            key = ks(v)
            self.state.set_current_key(key)
            for out in process(v, self._ctx(key, int(ts))):
                self._out.append(out)
                self._out_ts.append(int(ts))
        self._flush()

    def on_batch(self, values, timestamps) -> None:  # pragma: no cover
        raise AssertionError("KeyedCoProcessRunner consumes via input gates")


class BroadcastProcessRunner(StepRunner):
    """Broadcast state pattern (BroadcastConnectedStream.process /
    CoBroadcastWithNonKeyedOperator): input gate 1 carries the broadcast
    stream, whose elements update operator-wide broadcast state; gate 0
    elements read it through an immutable view — the reference's read-only
    non-broadcast side contract, enforced here with a mapping proxy."""

    num_inputs = 2

    def __init__(self, step: Step, config: Configuration):
        import types

        t = step.terminal
        self.fn = t.config["process_fn"]
        self.state: Dict[Any, Any] = {}
        self._view = types.MappingProxyType(self.state)  # live read-only view
        self._out: List = []
        self._out_ts: List[int] = []
        self.uid = t.uid

    def on_batch_n(self, ordinal: int, values, timestamps) -> None:
        ts = np.asarray(timestamps, dtype=np.int64)
        if ordinal == 1:
            for v in values:
                self.fn.process_broadcast_element(v, self.state)
            return
        view = self._view
        for v, tt in zip(values, ts):
            for out in self.fn.process_element(v, view):
                self._out.append(out)
                self._out_ts.append(int(tt))
        if self._out:
            if self.downstream:
                self.downstream.on_batch(
                    obj_array(self._out),
                    np.asarray(self._out_ts, dtype=np.int64))
            self._out, self._out_ts = [], []

    def on_batch(self, values, timestamps) -> None:  # pragma: no cover
        raise AssertionError("BroadcastProcessRunner consumes via input gates")

    def snapshot(self) -> dict:
        return {"broadcast": dict(self.state)}

    def restore(self, snap: dict) -> None:
        import types

        self.state = dict(snap["broadcast"])
        self._view = types.MappingProxyType(self.state)


class WindowJoinRunner(StepRunner):
    """Keyed event-time window join / coGroup.

    The reference implements join as coGroup over tagged inputs flowing into
    one WindowOperator (JoinedStreams.java:101 'Join is implemented on top
    of CoGroup', CoGroupedStreams.java WithWindow.apply): elements of both
    sides buffer per (key, window); when the watermark passes the window
    end, join emits one result per left x right pair, coGroup emits one
    result per window from both element lists. Late elements (window already
    fired) are dropped, matching WindowOperator.isWindowLate."""

    num_inputs = 2

    def __init__(self, step: Step, config: Configuration):
        t = step.terminal
        self.key_selectors = (t.config["key_selector1"], t.config["key_selector2"])
        self.assigner = t.config["assigner"]
        if not self.assigner.is_event_time:
            raise ValueError("window joins support event-time assigners")
        self.join_fn = t.config.get("join_fn")
        self.cogroup = t.kind == "co_group"
        # (key, window_start, window_end) -> ([left...], [right...])
        self._buf: Dict[tuple, tuple] = {}
        self._wm = MIN_WATERMARK
        self.num_late_dropped = 0
        self.uid = t.uid

    def on_batch_n(self, ordinal: int, values, timestamps) -> None:
        ks = self.key_selectors[ordinal]
        for v, ts in zip(values, np.asarray(timestamps, dtype=np.int64)):
            key = ks(v)
            for w in self.assigner.assign_windows(v, int(ts)):
                if w.end - 1 <= self._wm:
                    self.num_late_dropped += 1
                    continue
                sides = self._buf.get((key, w.start, w.end))
                if sides is None:
                    sides = ([], [])
                    self._buf[(key, w.start, w.end)] = sides
                sides[ordinal].append(v)

    def on_batch(self, values, timestamps) -> None:  # pragma: no cover
        raise AssertionError("WindowJoinRunner consumes via input gates")

    def on_watermark(self, watermark: int) -> None:
        self._wm = max(self._wm, watermark)
        out, out_ts = [], []
        # fire in (window end, key-insertion) order, mirroring the oracle's
        # timer ordering
        ripe = [k for k in self._buf if k[2] - 1 <= self._wm]
        ripe.sort(key=lambda k: k[2])
        for k in ripe:
            left, right = self._buf.pop(k)
            max_ts = k[2] - 1
            if self.cogroup:
                out.append(self.join_fn(left, right))
                out_ts.append(max_ts)
            else:
                for lv in left:
                    for rv in right:
                        out.append(self.join_fn(lv, rv))
                        out_ts.append(max_ts)
        if out and self.downstream:
            self.downstream.on_batch(
                obj_array(out), np.asarray(out_ts, dtype=np.int64))
        super().on_watermark(watermark)

    def snapshot(self) -> dict:
        return {
            "buf": {k: (list(l), list(r)) for k, (l, r) in self._buf.items()},
            "wm": self._wm,
            "late": self.num_late_dropped,
        }

    def restore(self, snap: dict) -> None:
        self._buf = {k: (list(l), list(r)) for k, (l, r) in snap["buf"].items()}
        self._wm = snap["wm"]
        self.num_late_dropped = snap["late"]


class SinkRunner(StepRunner):
    def __init__(self, step: Step):
        sink = step.terminal.config["sink"]
        self.writer = sink.create_writer()
        self.committer = sink.create_committer()
        self.uid = step.terminal.uid
        self._latency_hist = None

    def register_metrics(self, group) -> None:
        super().register_metrics(group)
        # O3: per-marker pipeline latency at the sink (source wall clock ->
        # sink arrival; the reference's LatencyMarker histogram)
        self._latency_hist = group.histogram("pipelineLatencyMs")

    def on_marker(self, wall_ms: float) -> None:
        if self._latency_hist is not None:
            self._latency_hist.update(time.time() * 1000.0 - wall_ms)
        super().on_marker(wall_ms)

    def on_batch(self, values: np.ndarray, timestamps: np.ndarray) -> None:
        self.writer.write_batch(values, timestamps)

    def commit_epoch(self, epoch_id: str = "final") -> None:
        if self.committer is not None:
            self.committer.commit(self.writer.prepare_commit(epoch_id))

    def on_end(self) -> None:
        self.commit_epoch("final")
        self.writer.close()

    def snapshot(self) -> dict:
        # collect-style sinks are stateful: emissions before the cut belong
        # to the checkpoint (post-cut emissions of a failed attempt are
        # discarded and re-fired on replay — the shard-task contract)
        store = getattr(self.writer, "store", None)
        return {"collected": list(store)} if store is not None else {}

    def restore(self, snap: dict) -> None:
        store = getattr(self.writer, "store", None)
        if store is not None and "collected" in snap:
            store[:] = snap["collected"]


class IterationHeadRunner(StepRunner):
    """Iteration head (StreamIterationHead.java analogue on the stepped
    executor): forwards the initial stream and re-injects feedback batches
    that its tail enqueues. Watermarks cross only the initial edge — as in
    the reference, feedback edges carry no watermarks — and the end-of-input
    signal is HELD until the run loop drains feedback to quiescence (the
    stepped analogue of the reference's iteration await-timeout
    termination: here bounded inputs terminate exactly when the loop body
    stops feeding records back)."""

    def __init__(self, step: Step):
        t = step.terminal
        self.uid = t.uid
        self.max_rounds = int(t.config.get("max_rounds", 10000))
        self._feedback: deque = deque()     # (values, timestamps) batches
        self._end_held = False
        self._held_wm: Optional[int] = None
        self._closed = False

    def on_batch(self, values: np.ndarray, timestamps: np.ndarray) -> None:
        if self.downstream:
            self.downstream.on_batch(values, timestamps)

    def on_watermark(self, watermark: int) -> None:
        if watermark >= MAX_WATERMARK - 1 and not self._closed:
            # the sources' final flush must not fire downstream windows while
            # feedback can still inject records for them
            self._held_wm = max(self._held_wm or MIN_WATERMARK, watermark)
            return
        super().on_watermark(watermark)

    def on_end(self) -> None:
        self._end_held = True   # released by finish_iteration()

    # -- feedback edge (called by the tail / the run loop) -----------------
    def enqueue_feedback(self, values, timestamps) -> None:
        if len(timestamps):
            self._feedback.append(
                (values, np.asarray(timestamps, dtype=np.int64))
            )

    def has_feedback(self) -> bool:
        return bool(self._feedback)

    def drain_round(self) -> int:
        """Re-inject the batches queued at round start; batches their
        processing enqueues belong to the next round. Returns records sent."""
        n_batches = len(self._feedback)
        sent = 0
        for _ in range(n_batches):
            values, ts = self._feedback.popleft()
            sent += len(ts)
            if self.downstream:
                self.downstream.on_batch(values, ts)
        return sent

    def finish_iteration(self) -> None:
        """Quiescence reached: release the held final watermark/end."""
        self._closed = True
        if self._held_wm is not None:
            StepRunner.on_watermark(self, self._held_wm)
            self._held_wm = None
        if self._end_held:
            StepRunner.on_end(self)

    def snapshot(self) -> dict:
        if not self._feedback:
            return {}
        return {
            "feedback": [(obj_array(list(v)), ts.copy())
                         for v, ts in self._feedback]
        }

    def restore(self, snap: dict) -> None:
        self._feedback = deque(
            (v, np.asarray(ts, dtype=np.int64))
            for v, ts in snap.get("feedback", ())
        )


class IterationTailRunner(StepRunner):
    """Iteration tail (StreamIterationTail.java analogue): every batch it
    receives is queued on its head's feedback edge. Watermarks and end
    signals stop here — they never cross a feedback edge."""

    def __init__(self, step: Step):
        t = step.terminal
        self.uid = t.uid
        self.head_transform_id = t.config["head"].id
        self.head: Optional[IterationHeadRunner] = None  # wired in build_runners

    def on_batch(self, values: np.ndarray, timestamps: np.ndarray) -> None:
        self.head.enqueue_feedback(values, timestamps)

    def on_watermark(self, watermark: int) -> None:
        pass

    def on_end(self) -> None:
        pass


def _make_runner(step: Step, config: Configuration) -> StepRunner:
    if step.terminal is None:
        return ChainRunner(step.chain)
    kind = step.terminal.kind
    if kind == "window_aggregate":
        return WindowStepRunner(step, config)
    if kind == "reduce":
        return KeyedReduceRunner(step, config)
    if kind == "process_keyed":
        return KeyedProcessRunner(step, config)
    if kind == "async_map":
        from flink_tpu.runtime.async_io import AsyncMapRunner

        return AsyncMapRunner(step.terminal, config)
    if kind == "cep":
        return CepRunner(step, config)
    if kind == "sink":
        return SinkRunner(step)
    if kind == "union":
        return UnionRunner(step)
    if kind in ("co_map", "co_flat_map"):
        return CoMapRunner(step)
    if kind == "co_process":
        return KeyedCoProcessRunner(step, config)
    if kind == "broadcast_process":
        return BroadcastProcessRunner(step, config)
    if kind in ("window_join", "co_group"):
        # device reroute: eligible event-time window equi-joins run on the
        # bucketed-ring pipeline; every refusal is a catalogued
        # JoinUnsupported reason, and the host runner stays the oracle
        if kind == "window_join":
            from flink_tpu.joins.spec import JoinUnsupported
            from flink_tpu.runtime.device_join_operator import DeviceJoinRunner

            try:
                return DeviceJoinRunner(step, config)
            except JoinUnsupported:
                pass
        return WindowJoinRunner(step, config)
    if kind == "group_agg":
        from flink_tpu.runtime.group_agg_operator import GroupAggRunner

        return GroupAggRunner(step, config)
    if kind == "regular_join":
        from flink_tpu.runtime.stream_join_operator import StreamingJoinRunner

        return StreamingJoinRunner(step, config)
    if kind == "iteration_head":
        return IterationHeadRunner(step)
    if kind == "iteration_tail":
        return IterationTailRunner(step)
    if kind == "stage_output":
        from flink_tpu.runtime.stages import StageOutputRunner

        return StageOutputRunner(step)
    raise NotImplementedError(kind)


def build_runners(graph: StepGraph, config: Configuration):
    """Build the runner DAG: one runner per step, fan-out edges wired by
    input ordinal. Returns (runners in topo order, source feed map
    {source_transformation_id: [(entry_runner, ordinal)]}).

    Whole-graph fusion (graph/fusion.py) happens here: eligible window
    steps get a DeviceChainRunner that absorbs the pure traceable chain
    step feeding them — the absorbed step gets no runner, and the fused
    runner consumes the absorbed step's input edges directly."""
    from flink_tpu.graph.fusion import plan_device_chains

    plans, absorbed = {}, set()
    if config.get(ExecutionOptions.CHAIN_FUSION) and \
            config.get(ExecutionOptions.FUSED_WINDOWS):
        plans, absorbed = plan_device_chains(graph)

    # sharing optimizer (graph/window_sharing.py): correlated window
    # siblings collapse into ONE shared-partial runner; non-leader members
    # get placeholder runners whose downstream edges the leader feeds, and
    # their input edges are NOT wired (the leader consumes the stream once)
    shared_of: Dict[int, tuple] = {}    # id(step) -> (plan, spec)
    edge_silent: set = set()            # member steps with unwired inputs
    if plans and config.get(ExecutionOptions.SHARED_PARTIALS):
        from flink_tpu.graph.window_sharing import plan_shared_windows

        for sw in plan_shared_windows(graph, plans):
            for spec, member in enumerate(sw.members):
                shared_of[id(member)] = (sw, spec)
                plans.pop(id(member), None)
                if spec > 0:
                    edge_silent.add(id(member))
            if sw.absorbed is not None:
                absorbed.add(id(sw.absorbed))

    runner_of: Dict[int, StepRunner] = {}
    runners: List[StepRunner] = []
    for step in graph.steps:
        if id(step) in absorbed:
            continue
        if id(step) in shared_of:
            sw, spec = shared_of[id(step)]
            if spec == 0:
                r = SharedWindowRunner(step, sw, config)
            else:
                r = SharedWindowSiblingRunner(step, spec)
        elif id(step) in plans:
            r = DeviceChainRunner(step, plans[id(step)], config)
        else:
            r = _make_runner(step, config)
        if len(step.inputs) > 1:
            r.num_inputs = len(step.inputs)
        runner_of[id(step)] = r
        runners.append(r)
    # leaders learn their members' runners (spec order) for emission fanout
    for step in graph.steps:
        ent = shared_of.get(id(step))
        if ent is not None and ent[1] == 0:
            sw, _spec = ent
            leader = runner_of[id(step)]
            leader.member_runners = [runner_of[id(m)] for m in sw.members]

    feeds: Dict[int, List] = {}
    for step in graph.steps:
        if id(step) in absorbed or id(step) in edge_silent:
            continue
        r = runner_of[id(step)]
        if id(step) in shared_of:
            step_inputs = shared_of[id(step)][0].inputs
        elif id(step) in plans:
            step_inputs = plans[id(step)].inputs
        else:
            step_inputs = step.inputs
        for edge in step_inputs:
            entity, ordinal = edge[0], edge[1]
            tag = edge[2] if len(edge) > 2 else None
            if isinstance(entity, Transformation):       # a source feeds this
                if tag is not None:
                    raise ValueError("sources have no side-output channels")
                feeds.setdefault(entity.id, []).append((r, ordinal))
            elif tag is not None:
                runner_of[id(entity)].side_channel(tag).add(r, ordinal)
            else:
                up = runner_of[id(entity)]
                if up.downstream is None:
                    up.downstream = _FanOut()
                up.downstream.add(r, ordinal)
    for r in runners:
        if r.downstream is None:
            r.downstream = _FanOut()
    # feedback edges: tail -> head, matched by the head transformation the
    # tail's closeWith recorded (the runtime-only cycle)
    heads = {
        step.terminal.id: runner_of[id(step)]
        for step in graph.steps
        if step.terminal is not None and step.terminal.kind == "iteration_head"
    }
    for r in runners:
        if isinstance(r, IterationTailRunner):
            if r.head_transform_id not in heads:
                raise ValueError(
                    "iteration tail closed with a head that is not part of "
                    "this pipeline"
                )
            r.head = heads[r.head_transform_id]
    return runners, feeds


def register_runner_metrics(runners: List[StepRunner], registry: MetricRegistry) -> None:
    for i, r in enumerate(runners):
        r.register_metrics(
            registry.group("job", "operator", getattr(r, "uid", f"chain-{i}"))
        )


class JobCancelledException(Exception):
    pass


class JobRuntime:
    """One running attempt of a job: the stepped loop plus the
    checkpoint-capture/restore surface (task-side checkpointing, §3.4
    analogue — here capture happens between steps so alignment is free)."""

    class _SourceDriver:
        """One source's read state: enumerator/reader/watermark generator
        plus the entry gates it feeds (SourceOperator analogue)."""

        def __init__(self, transform: Transformation, feeds: List):
            cfg = transform.config
            self.uid = transform.uid
            self.source = cfg["source"]
            strategy: Optional[WatermarkStrategy] = cfg.get("watermark_strategy")
            self.generator = strategy.create_generator() if strategy else None
            self.assigner = strategy.timestamp_assigner if strategy else None
            self.enumerator = self.source.create_enumerator()
            self.reader = self.source.create_reader()
            self.current_split = None
            self.done = False
            self.finished_signalled = False
            self.feeds = feeds              # [(runner, ordinal)]
            self.last_marker_wall = 0.0     # marker-interval throttle state

        def emit_batch(self, values, ts) -> None:
            for r, o in self.feeds:
                r.on_batch_n(o, values, ts)

        def emit_watermark(self, wm: int) -> None:
            for r, o in self.feeds:
                r.on_watermark_n(o, wm)

        def emit_marker(self, wall_ms: float) -> None:
            for r, _o in self.feeds:
                r.on_marker(wall_ms)

        def finish(self) -> None:
            """End of this source: flush its contribution to every valve and
            close its gates (idempotent)."""
            if self.finished_signalled:
                return
            self.finished_signalled = True
            self.emit_watermark(MAX_WATERMARK - 1)
            for r, o in self.feeds:
                r.on_end_n(o)

        def snapshot(self) -> dict:
            return {
                "pending_splits": self.enumerator.snapshot(),
                "current_split": self.current_split,
                "reader_position": self.reader.snapshot_position(),
                "done": self.done,
                "generator": self.generator.snapshot() if self.generator else None,
            }

        def restore(self, snap: dict) -> None:
            self.enumerator.restore(snap["pending_splits"])
            self.current_split = snap["current_split"]
            self.done = snap["done"]
            if self.current_split is not None:
                self.reader.add_split(self.current_split)
                self.reader.restore_position(snap["reader_position"])
            if self.generator is not None and snap.get("generator") is not None:
                self.generator.restore(snap["generator"])

    def __init__(self, graph: StepGraph, config: Configuration,
                 registry: Optional[MetricRegistry] = None,
                 traces=None):
        self.graph = graph
        self.config = config
        self.traces = traces    # optional TraceRegistry for device spans
        self.runners, feeds = build_runners(graph, config)
        self.sources = [
            JobRuntime._SourceDriver(t, feeds.get(t.id, []))
            for t in graph.sources
        ]
        # OperatorCoordinator SPI (D15): operator functions declaring
        # create_coordinator() get a job-scope coordinator + event bus.
        # Candidates: terminal runners' fn, chain transforms' fns, and both
        # sides of co-transforms; keys are deterministic across rebuilds so
        # coordinator state survives restore.
        from flink_tpu.runtime.coordination import wire as _wire_coordinator

        self.coordinators = {}
        for idx, r in enumerate(self.runners):
            candidates = []
            if getattr(r, "fn", None) is not None:
                candidates.append((getattr(r, "uid", f"coordinator@{idx}"),
                                   r.fn))
            for j, f in enumerate(getattr(r, "fns", ()) or ()):
                candidates.append(
                    (f"{getattr(r, 'uid', f'coordinator@{idx}')}#{j}", f))
            for t in getattr(r, "transforms", ()) or ():
                f = t.config.get("fn")
                if f is not None:
                    candidates.append((t.uid, f))
            for uid, f in candidates:
                coord = _wire_coordinator(f)
                if coord is not None:
                    self.coordinators[uid] = coord
        self.iteration_heads = [
            r for r in self.runners if isinstance(r, IterationHeadRunner)
        ]
        self.records_in = 0
        # observability: job-scope throughput, busy/idle/backpressure
        # ratios (TaskIOMetricGroup analogue), step latency, device time
        self.registry = registry or MetricRegistry()
        register_runner_metrics(self.runners, self.registry)
        job_group = self.registry.group("job")
        self.records_meter = job_group.meter("numRecordsInPerSecond")
        self.step_latency = job_group.histogram("stepLatencyMs")
        self._last_pt_tick = 0.0
        self.io = TaskIOMetrics()
        for r in self.runners:
            bp = getattr(r, "backpressure_seconds", None)
            if bp is not None:   # stage-output senders blocked on credits
                self.io.add_backpressure_source(bp)
        self.io.register(job_group)
        job_group.gauge("numRecordsIn", lambda: self.records_in,
                        fold="sum", kind="counter")
        # mesh-as-slot-resource visibility: 1 on the single-chip path, the
        # actual shard count when parallel.mesh.enabled promoted the job —
        # dashboards and the autoscaler read THIS, not the requested config
        # (fold MAX: each shard reports ITS mesh size — summing would
        # misreport a plain 2-shard job as a 2-device mesh)
        job_group.gauge("meshDevices", self.mesh_devices, fold="max")
        # SQL front-door visibility: present only for SQL-originated jobs
        # (planner-lowered window terminals carry sql_origin). 1 when every
        # SQL window step selected the fused DeviceChainRunner — the
        # reroute gate dashboards and the sql_path bench read; 0 means the
        # planner fell back (or translation rerouted) to interpreted-style
        # execution for at least one of them.
        sql_runners = [r for r in self.runners
                       if getattr(r, "sql_origin", False)]
        if sql_runners:
            from flink_tpu.runtime.device_join_operator import DeviceJoinRunner

            # fold MIN: the job is "fully fused" only when EVERY shard is
            job_group.gauge(
                "sqlFusedSelected",
                lambda rs=tuple(sql_runners): int(all(
                    isinstance(r, (DeviceChainRunner, DeviceJoinRunner))
                    for r in rs)),
                fold="min")
        job_group.gauge("deviceTimeMsTotal", lambda: sum(
            r.device_timer.total_s * 1000.0
            for r in self.runners
            if getattr(r, "device_timer", None) is not None),
            fold="sum", kind="counter")
        # device plane: job-level compile/roofline/skew gauges — these are
        # the keys the TM heartbeat ships and the autoscaler's signal
        # extractor reads (job.device.*, job.keySkew); compile events also
        # ride the TraceRegistry as 'device'-scope spans when one is bound
        trackers = [r.device_stats for r in self.runners
                    if getattr(r, "device_stats", None) is not None]
        collectors = [r.key_stats for r in self.runners
                      if getattr(r, "key_stats", None) is not None]
        if trackers:
            dg = job_group.add_group("device")
            dg.gauge("numCompiles",
                     lambda: sum(t.num_compiles for t in trackers),
                     fold="sum", kind="counter")
            dg.gauge("numRecompiles",
                     lambda: sum(t.num_recompiles for t in trackers),
                     fold="sum", kind="counter")
            dg.gauge("compileTimeMsTotal", lambda: round(
                sum(t.compile_ms_total for t in trackers), 3),
                fold="sum", kind="counter")
            dg.gauge("recompileStorm",
                     lambda: max(t.recompile_storm() for t in trackers),
                     fold="max")
            dg.gauge("hbmUtilizationPct", lambda: max(
                (r.device_roofline()["hbmUtilizationPct"]
                 for r in self.runners
                 if getattr(r, "device_stats", None) is not None),
                default=0.0), fold="mean")
            dg.gauge("flopsUtilizationPct", lambda: max(
                (r.device_roofline()["flopsUtilizationPct"]
                 for r in self.runners
                 if getattr(r, "device_stats", None) is not None),
                default=0.0), fold="mean")
        if collectors:
            def _job_skew(cs=collectors):
                skews = [s for s in (c.skew() for c in cs) if s is not None]
                return max(skews) if skews else None

            job_group.gauge("keySkew", _job_skew, fold="max")
        if traces is not None and trackers:
            from flink_tpu.metrics.device_stats import compile_event_span

            for t in trackers:
                if t.on_event is None:
                    t.on_event = (lambda ev, _tr=traces:
                                  _tr.report(compile_event_span(ev)))
        # emission-latency plane (observability.emission-latency.*): the
        # job-level p99 gauge is the bench/autoscaler surface (folds MAX
        # across shards), and outlier EmissionStall spans ride the same
        # trace plane as checkpoint/recovery spans — the MiniCluster's
        # TraceRegistry here; the TM heartbeat span buffer wires its own
        # sink in cluster.py before any fire can happen
        em_trackers = tuple(
            r.emission_tracker for r in self.runners
            if getattr(r, "emission_tracker", None) is not None)
        if em_trackers:
            job_group.gauge(
                "p99EmissionLatencyMs",
                lambda ts=em_trackers: _merge_emission_snapshots(
                    [t.snapshot() for t in ts]).get("p99", 0.0),
                fold="max")
            if traces is not None:
                from flink_tpu.metrics.traces import Span

                for t in em_trackers:
                    if t.span_sink is None:
                        t.span_sink = (
                            lambda scope, name, s, e, a, _tr=traces:
                            _tr.report(Span(scope, name, s, e, a)))
        # profiler capture surface (observability.profiler.*): the REST
        # /jobs/:id/device payload reports where captures landed — the
        # per-attempt jax.profiler trace used to be write-only
        self.profiler_captures = 0
        self.last_profiler_capture_dir: Optional[str] = None
        self._marker_interval = config.get(ObservabilityOptions.MARKER_INTERVAL_MS)
        self._sampling_interval = config.get(ObservabilityOptions.SAMPLING_INTERVAL_MS)

    # -- checkpoint surface ----------------------------------------------
    def capture(self) -> dict:
        runner_snaps = {}
        for r in self.runners:
            snap = r.snapshot()
            if snap:
                runner_snaps[getattr(r, "uid", f"runner-{id(r)}")] = snap
        return {
            "sources": {d.uid: d.snapshot() for d in self.sources},
            "runners": runner_snaps,
            "coordinators": {
                uid: c.checkpoint() for uid, c in self.coordinators.items()
            },
            "records_in": self.records_in,
        }

    def restore(self, snap: dict) -> None:
        if "sources" in snap:
            for d in self.sources:
                if d.uid in snap["sources"]:
                    d.restore(snap["sources"][d.uid])
        else:
            # single-source snapshot from the pre-DAG layout
            legacy = dict(snap["source"])
            legacy["generator"] = snap.get("generator")
            self.sources[0].restore(legacy)
        for r in self.runners:
            uid = getattr(r, "uid", None)
            if uid is not None and uid in snap["runners"]:
                r.restore(snap["runners"][uid])
        for uid, c in self.coordinators.items():
            if uid in snap.get("coordinators", {}):
                c.restore(snap["coordinators"][uid])
        self.records_in = snap["records_in"]

    def commit_sinks(self, checkpoint_id: int) -> None:
        for r in self.runners:
            if isinstance(r, SinkRunner):
                r.commit_epoch(str(checkpoint_id))

    def mesh_devices(self) -> int:
        """Devices this attempt's keyed state is sharded over (worst
        operator; 1 = single-chip)."""
        return max(
            (int(fn()) for fn in (
                getattr(getattr(r, "op", None), "mesh_devices", None)
                for r in self.runners) if fn is not None),
            default=1,
        )

    # -- skew-aware key-group routing (parallel.mesh.skew-rebalance) ----
    def _routed_ops(self):
        for r in self.runners:
            op = getattr(r, "op", None)
            if op is not None and callable(
                    getattr(op, "routing_version", None)) \
                    and op.routing_version() is not None:
                yield op

    def mesh_routing_version(self) -> Optional[int]:
        """Highest routing-table version across mesh operators (None when
        no operator carries a table)."""
        versions = [op.routing_version() for op in self._routed_ops()]
        return max(versions) if versions else None

    def mesh_group_loads(self):
        """(group_loads [G], current assignment [G], mesh size) of the
        first routed operator — the skew rebalancer's decision input;
        None when no operator carries a routing table or no data has
        landed on device yet."""
        for op in self._routed_ops():
            loads = op.mesh_group_loads()
            if loads is not None and loads.sum() > 0:
                return loads, op.pipe.routing.assign, op.mesh_devices()
        return None

    def set_mesh_routing(self, assign) -> None:
        """Apply a key-group assignment to every routed operator (the
        rebuilt attempt of a rebalance, AFTER restore — restore may adopt
        a grown snapshot K and rebuild the table for the new capacity).
        An assignment sized for a DIFFERENT group count is skipped, not
        an error: the geometry changed between decision and application
        (capacity growth mid-flight), and the rebalancer simply
        re-decides from live skew under the new table."""
        assign = np.asarray(assign)
        for op in self._routed_ops():
            if assign.shape[0] != op.pipe.routing.G:
                continue
            op.set_routing_assignment(assign)

    def operator_state_bytes(self) -> Dict[str, int]:
        """Per-operator state footprint from the operators' own
        state_bytes() (the same source as the stateBytes gauges) — the
        per-operator breakdown attached to completed checkpoint records."""
        out: Dict[str, int] = {}
        for idx, r in enumerate(self.runners):
            fn = getattr(getattr(r, "op", None), "state_bytes", None)
            if fn is None:
                continue
            try:
                out[getattr(r, "uid", f"runner-{idx}")] = int(fn())
            except Exception:   # a torn-down operator must not fail a
                continue        # checkpoint's bookkeeping
        return out

    def device_snapshot(self) -> Dict[str, Any]:
        """The device-plane payload (/jobs/:id/device): merged compile
        block, per-operator cost/roofline/phase/key telemetry, and the
        profiler capture surface. Plain data, JSON-safe."""
        from flink_tpu.metrics.device_stats import (
            empty_device_payload,
            merge_compile_payloads,
        )

        payload = empty_device_payload()
        ops: Dict[str, Any] = {}
        compile_payloads = []
        for idx, r in enumerate(self.runners):
            tracker = getattr(r, "device_stats", None)
            ks = getattr(r, "key_stats", None)
            timer = getattr(r, "device_timer", None)
            tier_fn = getattr(getattr(r, "op", None), "tier_payload", None)
            has_tier = callable(tier_fn) and tier_fn() is not None
            routing_fn = getattr(getattr(r, "op", None), "routing_payload",
                                 None)
            has_routing = callable(routing_fn) and routing_fn() is not None
            if tracker is None and ks is None and not has_tier \
                    and not has_routing:
                continue
            entry: Dict[str, Any] = {}
            if timer is not None:
                entry["deviceTimeMsTotal"] = round(timer.total_s * 1000.0, 3)
                entry["deviceDispatches"] = timer.dispatches
            if tracker is not None:
                cp = tracker.payload()
                compile_payloads.append(cp)
                entry["compile"] = cp
                entry.update(r.device_roofline())
            phases = getattr(getattr(r, "op", None), "phase_totals", None)
            if callable(phases):
                entry["phases"] = phases()
            if ks is not None:
                entry["keys"] = ks.payload()
            tier_payload = getattr(getattr(r, "op", None), "tier_payload",
                                   None)
            if callable(tier_payload):
                tp = tier_payload()
                if tp is not None:
                    entry["tier"] = tp
            # skew-aware key-group routing (parallel.mesh.skew-rebalance):
            # table version + assignment, next to the per-device skew it
            # exists to fix
            routing_payload = getattr(getattr(r, "op", None),
                                      "routing_payload", None)
            if callable(routing_payload):
                rp = routing_payload()
                if rp is not None:
                    entry["routing"] = rp
            ops[getattr(r, "uid", f"runner-{idx}")] = entry
        payload["operators"] = ops
        payload["compile"] = merge_compile_payloads(
            compile_payloads,
            history_size=self.config.get(
                ObservabilityOptions.DEVICE_RECOMPILE_HISTORY_SIZE))
        payload["enabled"] = bool(ops)
        payload["profiler"] = {
            "enabled": self.config.get(ObservabilityOptions.PROFILER_ENABLED),
            "captures": self.profiler_captures,
            "last_capture_dir": self.last_profiler_capture_dir,
        }
        return payload

    # -- the loop ---------------------------------------------------------
    def run(
        self,
        coordinator=None,
        cancel_check: Optional[Callable[[], bool]] = None,
        savepoint_request: Optional[Callable[[], Optional[str]]] = None,
        rescale_request: Optional[Callable[[], Optional[int]]] = None,
        rebalance_request: Optional[Callable[[], Optional[Any]]] = None,
    ) -> None:
        batch_size = self.config.get(ExecutionOptions.BATCH_SIZE)
        if coordinator is not None:
            coordinator.register_on_complete(self.commit_sinks)
        profiling = False
        profile_dir = self.config.get(ObservabilityOptions.PROFILER_DIR)
        if self.config.get(ObservabilityOptions.PROFILER_ENABLED):
            try:
                import jax.profiler

                jax.profiler.start_trace(profile_dir)
                profiling = True
            except Exception as e:  # noqa: BLE001 — observability never
                import warnings      # fails the job

                warnings.warn(f"jax.profiler trace capture unavailable: {e!r}",
                              RuntimeWarning)
        try:
            self._run_loop(batch_size, coordinator, cancel_check,
                           savepoint_request, rescale_request,
                           rebalance_request)
        finally:
            if profiling:
                try:
                    import jax.profiler

                    jax.profiler.stop_trace()
                    # the capture is no longer write-only: count it and
                    # remember where it landed, for /jobs/:id/device
                    self.profiler_captures += 1
                    self.last_profiler_capture_dir = profile_dir
                except Exception as e:   # observability never fails the job
                    logging.getLogger(__name__).debug(
                        "jax.profiler stop_trace failed: %r", e)

    def _run_loop(
        self,
        batch_size: int,
        coordinator,
        cancel_check: Optional[Callable[[], bool]],
        savepoint_request: Optional[Callable[[], Optional[str]]],
        rescale_request: Optional[Callable[[], Optional[int]]] = None,
        rebalance_request: Optional[Callable[[], Optional[Any]]] = None,
    ) -> None:
        for d in self.sources:
            if d.current_split is None and not d.done:
                d.current_split = d.enumerator.next_split()
                if d.current_split is not None:
                    d.reader.add_split(d.current_split)
                else:
                    d.done = True
            if d.done:
                # zero-split or restored-as-done sources must still flush
                # their watermark/end contribution, or every multi-input
                # valve downstream stalls for the whole run
                d.finish()

        # round-robin over sources, one batch per turn; checkpoints align at
        # batch boundaries regardless of which source produced the batch
        while any(not d.done for d in self.sources):
            for d in self.sources:
                if d.done:
                    continue
                loop_t0 = time.perf_counter()
                if cancel_check is not None and cancel_check():
                    raise JobCancelledException()
                batch = d.reader.poll_batch(batch_size)
                if batch is None:
                    d.current_split = d.enumerator.next_split()
                    busy_dt = 0.0
                    if d.current_split is None:
                        d.done = True
                        # a finished source must not hold back the combined
                        # watermark of still-running inputs
                        busy_t0 = time.perf_counter()
                        d.finish()
                        busy_dt = time.perf_counter() - busy_t0
                    else:
                        d.reader.add_split(d.current_split)
                    self.io.record_step(busy_dt, time.perf_counter() - loop_t0)
                    continue
                values = batch.values
                ts = batch.timestamps
                if d.assigner is not None:
                    ts = np.asarray(
                        [d.assigner(v, int(t)) for v, t in zip(values, ts)],
                        dtype=np.int64,
                    )
                self.records_in += len(batch)
                self.records_meter.mark(len(batch))
                busy_t0 = time.perf_counter()
                # latency marker stamped BEFORE the synchronous push so the
                # sink's (now - stamp) measures this batch's real transit.
                # A stage-input reader forwards the UPSTREAM stage's marker
                # (take_marker) so transit accumulates across the dataplane
                # instead of resetting at every stage boundary; fresh stamps
                # honor observability.latency-markers.interval-ms.
                t_mark = None
                take = getattr(d.reader, "take_marker", None)
                if take is not None:
                    t_mark = take()
                elif self._marker_interval >= 0:
                    now_wall = time.time() * 1000.0
                    if now_wall - d.last_marker_wall >= self._marker_interval:
                        d.last_marker_wall = now_wall
                        t_mark = now_wall
                d.emit_batch(values, ts)
                if t_mark is not None:
                    d.emit_marker(t_mark)
                if d.generator is not None:
                    wm = (
                        d.generator.on_batch_np(ts)
                        if hasattr(d.generator, "on_batch_np")
                        else None
                    )
                    if wm is None:
                        for v, t in zip(values, ts):
                            d.generator.on_event(v, int(t))
                        wm = d.generator.on_periodic_emit()
                    if wm is not None and wm > MIN_WATERMARK:
                        d.emit_watermark(wm)
                if self.iteration_heads:
                    # run feedback to quiescence at the step boundary so
                    # checkpoints capture (almost) no in-flight feedback
                    self._drain_iterations()
                step_dt = time.perf_counter() - busy_t0
                self.step_latency.update(step_dt * 1000)
                # step boundary: checkpoints/savepoints align here for free
                if coordinator is not None:
                    coordinator.maybe_trigger(self.capture)
                if savepoint_request is not None:
                    path = savepoint_request()
                    if path is not None:
                        self._write_savepoint(path)
                if rescale_request is not None:
                    target = rescale_request()
                    if target is not None and target != self.mesh_devices():
                        # mesh rescale: hand a step-aligned capture to the
                        # job master, which rebuilds this runtime over the
                        # new device count and restores — checkpoint rewind
                        # across mesh sizes, exactly-once by construction
                        # (the capture IS the checkpoint path's capture)
                        raise MeshRescaleRequested(target, self.capture())
                if rebalance_request is not None:
                    assign = rebalance_request()
                    if assign is not None:
                        # skew rebalance: same capture/restore machinery as
                        # a rescale, same mesh size, new key-group routing
                        # — the rebuilt attempt applies the table, then
                        # restores the canonical capture (placement never
                        # changes a result)
                        raise MeshRescaleRequested(
                            self.mesh_devices(), self.capture(),
                            routing=assign)
                now_ms = time.time() * 1000.0
                if now_ms - self._last_pt_tick >= 50.0:
                    # ProcessingTimeService tick: drive wall-clock timers
                    # and sample the busy/idle/backpressure window
                    self._last_pt_tick = now_ms
                    for r in self.runners:
                        r.on_processing_time(int(now_ms))
                    self.io.maybe_sample(self._sampling_interval)
                self.io.record_step(step_dt, time.perf_counter() - loop_t0)

        # end of input: every source's final watermark + end signal has been
        # (or is now) delivered, firing all remaining windows downstream
        for d in self.sources:
            d.finish()
        if self.iteration_heads:
            # iteration heads held the final watermark/end; drain remaining
            # feedback to quiescence, then release them
            self._drain_iterations()
            for h in self.iteration_heads:
                h.finish_iteration()

    def _drain_iterations(self) -> None:
        """Round-robin feedback rounds across iteration heads until every
        feedback queue is empty (termination = the loop body stopped feeding
        records back). Each head's own max_rounds bounds the rounds in which
        IT still had feedback, so one non-converging loop trips its own
        (possibly tight) bound regardless of other loops in the job."""
        rounds = {id(h): 0 for h in self.iteration_heads}
        while any(h.has_feedback() for h in self.iteration_heads):
            for h in self.iteration_heads:
                if not h.has_feedback():
                    continue
                rounds[id(h)] += 1
                if rounds[id(h)] > h.max_rounds:
                    raise RuntimeError(
                        f"iteration '{h.uid}' did not reach quiescence "
                        f"within max_rounds={h.max_rounds}; the loop body "
                        "must eventually stop emitting feedback records "
                        "(or raise iterate(max_rounds=...))"
                    )
                h.drain_round()

    def _write_savepoint(self, path: str) -> None:
        from flink_tpu.checkpoint.storage import FsCheckpointStorage

        data = self.capture()
        data["savepoint"] = True
        FsCheckpointStorage(path).save(0, data)


class LocalPipelineExecutor:
    """Single-host execution (LocalExecutor/MiniCluster analogue,
    flink-clients LocalExecutor.java:49); one synchronous attempt, no
    recovery — fault tolerance lives in runtime/minicluster.py."""

    def __init__(self, config: Optional[Configuration] = None):
        self.config = config or Configuration()

    def execute(self, graph: StepGraph, job_name: str = "job") -> JobExecutionResult:
        runtime = JobRuntime(graph, self.config)
        t0 = time.perf_counter()
        runtime.run()
        runtime_ms = (time.perf_counter() - t0) * 1000
        return JobExecutionResult(
            job_name=job_name,
            runtime_ms=runtime_ms,
            records_in=runtime.records_in,
            metrics={"records_in": runtime.records_in},
        )
