"""External resource framework: accelerator discovery SPI (Y4).

Analogue of flink-core/.../externalresource/ExternalResourceDriver.java +
the GPU driver (flink-external-resources/flink-external-resource-gpu/...
GPUDriver.java), surfaced to operators via
RuntimeContext.getExternalResourceInfos. The first-class driver here is the
TPU one: it reports the chips jax sees (id, platform, kind, memory stats,
process/slice indices) — the information a task needs to pin itself to an
accelerator.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List


class ExternalResourceInfo:
    def __init__(self, properties: Dict[str, Any]):
        self._props = dict(properties)

    def get_property(self, key: str, default=None):
        return self._props.get(key, default)

    @property
    def properties(self) -> Dict[str, Any]:
        return dict(self._props)

    def __repr__(self):
        return f"ExternalResourceInfo({self._props})"


class ExternalResourceDriver:
    name: str = ""

    def retrieve_resource_info(self, amount: int) -> List[ExternalResourceInfo]:
        raise NotImplementedError


class TpuDriver(ExternalResourceDriver):
    """Discovers TPU (or whatever accelerator jax is bound to) chips."""

    name = "tpu"

    def retrieve_resource_info(self, amount: int) -> List[ExternalResourceInfo]:
        import jax

        out = []
        for d in jax.devices()[: amount if amount > 0 else None]:
            props: Dict[str, Any] = {
                "id": d.id,
                "platform": d.platform,
                "device_kind": getattr(d, "device_kind", "unknown"),
                "process_index": getattr(d, "process_index", 0),
            }
            try:
                stats = d.memory_stats() or {}
                if "bytes_limit" in stats:
                    props["memory_bytes"] = stats["bytes_limit"]
            except Exception as e:
                logging.getLogger(__name__).debug(
                    "device memory_stats unavailable: %r", e)
            out.append(ExternalResourceInfo(props))
        return out


class GpuDriver(ExternalResourceDriver):
    """GPU discovery stub (GPUDriver.java analogue): reads indices from
    CUDA_VISIBLE_DEVICES when present; this image has no GPUs."""

    name = "gpu"

    def retrieve_resource_info(self, amount: int) -> List[ExternalResourceInfo]:
        import os

        visible = os.environ.get("CUDA_VISIBLE_DEVICES", "")
        ids = [v for v in visible.split(",") if v.strip()]
        return [ExternalResourceInfo({"index": v}) for v in ids[:amount or None]]


_DRIVERS: Dict[str, ExternalResourceDriver] = {}


def register_driver(driver: ExternalResourceDriver) -> None:
    _DRIVERS[driver.name] = driver


def get_external_resource_infos(name: str, amount: int = 0) -> List[ExternalResourceInfo]:
    """RuntimeContext.getExternalResourceInfos analogue."""
    driver = _DRIVERS.get(name)
    if driver is None:
        raise KeyError(f"no external resource driver {name!r} (have {sorted(_DRIVERS)})")
    return driver.retrieve_resource_info(amount)


register_driver(TpuDriver())
register_driver(GpuDriver())
