"""FusedWindowOperator: the product-path driver of FusedWindowPipeline.

Round 1 left the fused superscan as a bench-only side-car; this adapter
makes it the operator the executor actually selects (the swap boundary the
reference models as WindowOperatorBuilder.java:79 choosing
buildAsyncWindowOperator :472). It presents the same operator surface as
TpuWindowOperator — process_batch / process_watermark / drain_output /
snapshot / restore — while internally buffering steps and dispatching one
compiled T-step superscan per superbatch.

Two host-side layers de-brittle the raw pipeline (whose planner rejects
batches spanning > nsb slices, > fires_per_step fires per step, > out_rows
fires per dispatch, or slices beyond the ring):

- StepNormalizer splits raw (batch, watermark) steps into planner-safe
  steps: slice-span splitting (adds commute, so splitting a batch at the
  same watermark is semantics-preserving), intermediate-watermark
  insertion so no step fires more than fires_per_step windows (the
  watermark is a lower bound; staging its advance is always safe), and
  ring-overflow hold-back (records too far in the future wait on host
  until the purge frontier opens ring space — the fused sibling of
  TpuWindowOperator._future).
- The dispatch grouper packs normalized steps into fixed-T superbatches
  (padding with empty steps so ONE executable serves every dispatch) and
  cuts a dispatch early before planned fires exceed out_rows.

Watermark visibility: emissions materialize when a superbatch resolves, so
the operator exposes `emitted_watermark` — the watermark downstream may
safely observe (everything at or below it has been emitted). The step
runner forwards min(watermark, emitted_watermark), which preserves the
no-late-data contract downstream (a delayed watermark is always correct).
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.api.windowing.assigners import WindowAssigner
from flink_tpu.core.time import MAX_WATERMARK, MIN_WATERMARK
from flink_tpu.lint.contracts import inflight_ring
from flink_tpu.ops.aggregators import ONE, VALUE, resolve
from flink_tpu.runtime.fused_window_pipeline import FusedWindowPipeline
from flink_tpu.scheduler.latency_controller import (
    LatencySpec,
    SuperbatchController,
)
from flink_tpu.state.columnar import KeyDictionary


def _ceil_div(a: int, b: int) -> int:
    return -((-a) // b)


@dataclasses.dataclass
class _Step:
    """One planner-safe step: a (possibly empty) batch plus the watermark in
    effect after it, annotated with how many windows its advance fires."""

    kid: np.ndarray
    vals: Optional[np.ndarray]
    ts: np.ndarray
    wm: int
    n_fires: int
    # slice ids of ts, when the normalizer already computed them (staging
    # reuses them instead of re-dividing the whole timestamp column)
    s_abs: Optional[np.ndarray] = None


class StepNormalizer:
    """Host-side simulation of the fused planner's frontier state, used to
    pre-split raw steps so `stage_superbatch` never raises. Mirrors the
    geometry formulas of FusedWindowPipeline exactly (same fire/purge
    frontier math); divergence would be a planner error, so the pipeline's
    own checks stay on as assertions."""

    def __init__(self, pipe: FusedWindowPipeline, raw_payload: bool = False):
        self.p = pipe
        # payload column type: dense int32 key ids (classic), or the raw
        # record columns of a traced chain (whole-graph fusion) — the
        # normalizer only ever row-indexes the payload, so the frontier
        # math is identical; the cast is the single dtype-touching point
        self._cast = (
            (lambda a: np.asarray(a)) if raw_payload
            else (lambda a: np.asarray(a, np.int32))
        )
        self.wm = MIN_WATERMARK
        self.fire_cursor: Optional[int] = None
        self.max_seen: Optional[int] = None
        self.min_used: Optional[int] = None
        self.purged_to: Optional[int] = None
        # far-future records held until the ring can take them
        self._future: List[Tuple[np.ndarray, Optional[np.ndarray], np.ndarray]] = []
        self.num_future_held = 0

    # geometry delegates (identical formulas; single source of truth)
    def _j_fired_upto(self, wm: int) -> int:
        return self.p._j_fired_upto(wm)

    def _min_live_slice(self, wm: int) -> int:
        return self.p._min_live_slice(wm)

    def _slice_of(self, ts: np.ndarray) -> np.ndarray:
        return self.p._slice_of(np.asarray(ts, dtype=np.int64))

    def _fire_wm(self, j: int) -> int:
        """Smallest watermark at which window j fires."""
        return self.p.offset + j * self.p.slide_ms + self.p.size_ms - 1

    # ------------------------------------------------------------------
    def push(self, kid: np.ndarray, vals: Optional[np.ndarray], ts: np.ndarray) -> List[_Step]:
        """Normalize one data batch (no watermark advance)."""
        out: List[_Step] = []
        self._append_data(out, kid, vals, ts)
        return out

    def advance(self, wm: int) -> List[_Step]:
        """Normalize one watermark advance into fire-bounded steps.

        Held-back future records are re-injected BETWEEN staged fire steps,
        not after the loop: each staged step's watermark is additionally
        capped so it never passes a held record's slice lifetime before the
        purge frontier has opened ring space and the record was re-ingested
        (a watermark jump past a held slice would reclassify on-time records
        as late — the reference only drops records late on arrival,
        WindowOperator.java:440-446)."""
        out: List[_Step] = []
        if wm <= self.wm:
            return out
        while True:
            target = wm
            held_floor = self._held_min_slice()
            if held_floor is not None:
                # largest watermark at which slice `held_floor` is still
                # live (single-sourced with the pipeline; the shared-
                # partial pipeline widens it to its longest member window)
                cap_wm = self.p._wm_keeping_slice_live(held_floor)
                target = min(wm, max(cap_wm, self.wm))
            step_wm, n_fires = self._stage_fire_step(target)
            out.append(_Step(
                np.empty(0, np.int32), None, np.empty(0, np.int64), step_wm, n_fires
            ))
            self._commit_wm(step_wm, n_fires)
            held_before = self.num_future_held
            self._drain_future(out)
            if step_wm >= wm:
                break
            if step_wm >= target and target < wm:
                # the held-record cap is the binding constraint; progress
                # requires the drain to have re-ingested something. With
                # S - NSB >= slide_slices (guaranteed by the default ring
                # sizing) the drain always succeeds at the cap; the guard
                # below only trips on pathological geometry, where the old
                # behavior (advance past; records counted late) resumes.
                if self.num_future_held >= held_before and \
                        self._held_min_slice() == held_floor:
                    out.extend(self._advance_uncapped(wm))
                    break
        return out

    def _advance_uncapped(self, wm: int) -> List[_Step]:
        """Fallback staged advance without the held-record cap."""
        out: List[_Step] = []
        while self.wm < wm:
            step_wm, n_fires = self._stage_fire_step(wm)
            out.append(_Step(
                np.empty(0, np.int32), None, np.empty(0, np.int64), step_wm, n_fires
            ))
            self._commit_wm(step_wm, n_fires)
            self._drain_future(out)
        return out

    def _stage_fire_step(self, target: int):
        """(step_wm, n_fires) of the next staged advance toward `target`:
        the largest watermark whose fire load fits one step's fire slots.
        The shared-partial normalizer overrides this with the per-spec
        form (each member window's slot budget binds independently)."""
        p = self.p
        n_fires = 0
        step_wm = target
        if self.fire_cursor is not None and self.max_seen is not None:
            cap = min(self._j_fired_upto(target), p._j_newest(self.max_seen))
            n_fires = max(0, cap - self.fire_cursor + 1)
            if n_fires > p.F:
                # stage the advance: fire exactly F windows this step
                cap = self.fire_cursor + p.F - 1
                step_wm = min(target, self._fire_wm(cap))
                n_fires = p.F
        return step_wm, n_fires

    def _held_min_slice(self) -> Optional[int]:
        if not self._future:
            return None
        return min(int(self._slice_of(t).min()) for _, _, t in self._future)

    def pad_step(self, wm: Optional[int] = None) -> _Step:
        """An empty no-op step. `wm` defaults to the normalizer's committed
        watermark but MUST be the enclosing group's last real step watermark
        when steps remain queued behind the group (a pad stamped with a
        future watermark would perform the whole jump in one step and
        exceed fires_per_step)."""
        w = self.wm if wm is None else wm
        return _Step(np.empty(0, np.int32), None, np.empty(0, np.int64), w, 0)

    def end_steps(self) -> List[_Step]:
        """End of input: fire everything still buffered (MAX_WATERMARK)."""
        return self.advance(MAX_WATERMARK - 1)

    # ------------------------------------------------------------------
    def _commit_wm(self, wm: int, n_fires: int) -> None:
        if wm <= self.wm:
            return
        j_hi = self._j_fired_upto(wm)
        if self.fire_cursor is not None and j_hi >= self.fire_cursor:
            self.fire_cursor = j_hi + 1
        new_min_live = self._min_live_slice(wm)
        self.purged_to = (
            new_min_live if self.purged_to is None else max(self.purged_to, new_min_live)
        )
        self.wm = wm

    def _append_data(self, out: List[_Step], kid, vals, ts) -> None:
        p = self.p
        n = len(ts)
        if n == 0:
            return
        s_abs = self._slice_of(ts)
        keep = np.ones(n, dtype=bool)
        if self.wm > MIN_WATERMARK:
            keep = s_abs >= self._min_live_slice(self.wm)  # late records: the
            # pipeline drops/counts them itself; they must not affect splits
        if not keep.any():
            out.append(_Step(self._cast(kid), vals, np.asarray(ts, np.int64),
                             self.wm, 0))
            return

        # ring-overflow hold-back: a record at slice s needs the full span
        # [oldest-live-slice, s] resident. Before the first watermark the
        # oldest live slice is the smallest slice ever ACCEPTED (min_used),
        # not this batch's min — otherwise a far-future batch would alias
        # cells still owned by earlier data (TpuWindowOperator._ring_floor)
        floor = int(s_abs[keep].min())
        if self.min_used is not None:
            floor = min(floor, self.min_used)
        if self.wm > MIN_WATERMARK:
            floor = max(floor, self._min_live_slice(self.wm))
        if self.purged_to is not None:
            floor = max(floor, self.purged_to)
        limit = floor + p.S - p.NSB
        over = keep & (s_abs >= limit)
        if over.any():
            idx = np.flatnonzero(over)
            self._future.append((
                np.asarray(kid)[idx],
                None if vals is None else np.asarray(vals)[idx],
                np.asarray(ts)[idx],
            ))
            self.num_future_held += len(idx)
            sel = ~over
            kid, ts = np.asarray(kid)[sel], np.asarray(ts)[sel]
            vals = None if vals is None else np.asarray(vals)[sel]
            s_abs, keep = s_abs[sel], keep[sel]
            if len(ts) == 0:
                return
            if not keep.any():
                # only late rows survived the hold-back filter: ship them as
                # a zero-fire step (the pipeline drops+counts them itself)
                out.append(_Step(self._cast(kid), vals,
                                 np.asarray(ts, np.int64), self.wm, 0))
                return

        # slice-span splitting: sub-steps each touching < nsb distinct slices
        smin = int(s_abs[keep].min())
        smax = int(s_abs[keep].max())
        if smax - smin < p.NSB and bool(keep.all()):
            # hot path (in-order stream, batch within one slice block):
            # single step, NO column copy and no group sort — on the fused
            # chain path this forwards the raw source column untouched
            out.append(_Step(
                self._cast(kid),
                None if vals is None else np.asarray(vals),
                np.asarray(ts, np.int64),
                self.wm, 0,
                s_abs=s_abs,
            ))
        else:
            group = np.where(keep, (s_abs - smin) // p.NSB, 0)
            for gval in np.unique(group):
                sel = group == gval
                out.append(_Step(
                    self._cast(np.asarray(kid)[sel]),
                    None if vals is None else np.asarray(vals)[sel],
                    np.asarray(ts)[sel].astype(np.int64),
                    self.wm, 0,
                ))
        self._note_data(smin, smax)

    def _drain_future(self, out: List[_Step]) -> None:
        if not self._future:
            return
        fut, self._future = self._future, []
        self.num_future_held = 0
        for kid, vals, ts in fut:
            self._append_data(out, kid, vals, ts)  # still-unfit rows re-buffer

    def note_slices(self, smin: int, smax: int) -> None:
        """Tier-promotion sibling of the pipeline's note_external_slices:
        rows written into the ring outside a pushed step must count as
        resident data for the normalizer's fire capping and ring-floor
        math too, or the two frontier mirrors diverge."""
        self._note_data(smin, smax)

    def _note_data(self, smin: int, smax: int) -> None:
        """Frontier + fire-cursor updates for newly-resident slices (the
        shared-partial normalizer substitutes per-spec cursors)."""
        self.max_seen = smax if self.max_seen is None else max(self.max_seen, smax)
        self.min_used = smin if self.min_used is None else min(self.min_used, smin)
        cand = self.p._j_oldest(smin)
        if self.wm > MIN_WATERMARK:
            cand = max(cand, self._j_fired_upto(self.wm) + 1)
        self.fire_cursor = cand if self.fire_cursor is None else min(self.fire_cursor, cand)

    # ------------------------------------------------------------------
    def snapshot(self) -> dict:
        return {
            "wm": self.wm,
            "fire_cursor": self.fire_cursor,
            "max_seen": self.max_seen,
            "min_used": self.min_used,
            "purged_to": self.purged_to,
            "future": [
                (k.tolist(), None if v is None else v.tolist(), t.tolist())
                for k, v, t in self._future
            ],
            # payload dtypes of the held columns: the raw-payload cast is
            # dtype-free np.asarray, and a tolist() round-trip would promote
            # float32 columns to float64 — tripping the fused pipeline's
            # fixed-geometry check on the first post-restore dispatch
            "future_kdt": [str(np.asarray(k).dtype) for k, _v, _t in self._future],
        }

    def restore(self, snap: dict) -> None:
        self.wm = snap["wm"]
        self.fire_cursor = snap["fire_cursor"]
        self.max_seen = snap["max_seen"]
        self.min_used = snap.get("min_used")
        self.purged_to = snap["purged_to"]
        kdts = snap.get("future_kdt")  # absent in pre-fusion snapshots
        self._future = [
            (self._cast(k) if kdts is None
             else np.asarray(k, np.dtype(kdts[i])),
             None if v is None else np.asarray(v, np.float32),
             np.asarray(t, np.int64))
            for i, (k, v, t) in enumerate(snap["future"])
        ]
        self.num_future_held = sum(len(t) for _, _, t in self._future)


class SharedStepNormalizer(StepNormalizer):
    """StepNormalizer over a SharedWindowPipeline (shared partials): one
    shared ingest/ring frontier, per-window-spec fire cursors, each member
    window's fire-slot budget binding the staged advance independently."""

    def __init__(self, pipe, raw_payload: bool = False):
        super().__init__(pipe, raw_payload)
        self.fire_cursors: List[Optional[int]] = [None] * len(pipe.specs)

    def _note_data(self, smin: int, smax: int) -> None:
        p = self.p
        self.max_seen = smax if self.max_seen is None else max(self.max_seen, smax)
        self.min_used = smin if self.min_used is None else min(self.min_used, smin)
        for i in range(len(p.specs)):
            cand = p._spec_j_oldest(i, smin)
            if self.wm > MIN_WATERMARK:
                cand = max(cand, p._spec_j_fired_upto(i, self.wm) + 1)
            cur = self.fire_cursors[i]
            self.fire_cursors[i] = cand if cur is None else min(cur, cand)

    def _stage_fire_step(self, target: int):
        p = self.p
        if self.max_seen is None:
            return target, 0
        step_wm = target
        Fp = p.F_per_spec
        for i, spec in enumerate(p.specs):
            cur = self.fire_cursors[i]
            if cur is None:
                continue
            cap = min(p._spec_j_fired_upto(i, target),
                      self.max_seen // spec.sl)
            if cap - cur + 1 > Fp:
                step_wm = min(step_wm, p._spec_fire_wm(i, cur + Fp - 1))
        # fire counts settle AFTER the binding spec lowered step_wm
        # (n_i(wm) is monotone in wm, so every spec fits its budget there)
        total = 0
        for i, spec in enumerate(p.specs):
            cur = self.fire_cursors[i]
            if cur is None:
                continue
            cap = min(p._spec_j_fired_upto(i, step_wm),
                      self.max_seen // spec.sl)
            total += max(0, cap - cur + 1)
        return step_wm, total

    def _commit_wm(self, wm: int, n_fires: int) -> None:
        if wm <= self.wm:
            return
        p = self.p
        for i in range(len(p.specs)):
            j_hi = p._spec_j_fired_upto(i, wm)
            cur = self.fire_cursors[i]
            if cur is not None and j_hi >= cur:
                self.fire_cursors[i] = j_hi + 1
        new_min_live = p._min_live_slice(wm)   # min over specs: the
        # longest member window holds every slice it still needs
        self.purged_to = (
            new_min_live if self.purged_to is None
            else max(self.purged_to, new_min_live)
        )
        self.wm = wm

    def snapshot(self) -> dict:
        snap = super().snapshot()
        snap["fire_cursors"] = list(self.fire_cursors)
        return snap

    def restore(self, snap: dict) -> None:
        super().restore(snap)
        self.fire_cursors = list(snap["fire_cursors"])


@inflight_ring("_inflight", drained_by="_resolve_inflight")
class FusedWindowOperator:
    """Operator-boundary adapter: same surface as TpuWindowOperator, fused
    superbatch execution underneath. One outstanding dispatch is kept in
    flight (resolve of dispatch i overlaps device execution of i+1).

    With `assigners` (shared partials, graph/window_sharing.py) the
    operator runs N correlated window shapes over ONE shared-granule ring
    and routes each member's emissions into its own output lane
    (`drain_spec_output`); requires the traced-chain prologue (dense
    device keying), and the state tier does not apply."""

    def __init__(
        self,
        assigner: Optional[WindowAssigner],
        aggregate,
        *,
        key_capacity: int = 1 << 12,
        superbatch_steps: int = 32,
        dense_int_keys: bool = False,
        num_slices: Optional[int] = None,
        nsb: int = 4,
        fires_per_step: int = 4,
        out_rows: int = 256,
        chunk: int = 4096,
        columnar_output: bool = False,
        prologue=None,
        mesh=None,
        tier=None,
        assigners=None,
        mesh_local_combine: bool = False,
        mesh_skew_routing: bool = False,
        mesh_key_groups: int = 0,
        latency: Optional[LatencySpec] = None,
    ):
        self.agg = resolve(aggregate)
        if self.agg is None:
            raise ValueError(f"aggregate {aggregate!r} has no device form")
        # million-key state plane (state/tier_manager.py): a TierConfig
        # bounds the RESIDENT key set to hot_key_capacity HBM rows; the
        # vocabulary demotes/promotes rows through the cold tier and the
        # emission merges both tiers. Host-keyed path only — a traced
        # chain's dense device keying has no host vocabulary to evict from.
        if tier is not None:
            if prologue is not None:
                raise ValueError(
                    "state.tier.enabled needs the host key dictionary; "
                    "a traced device chain keys on device (dense ids)")
            key_capacity = tier.hot_key_capacity
            dense_int_keys = False
            # dense ids are RECYCLED under eviction: packed columnar
            # output would alias keys downstream
            columnar_output = False
        # whole-graph fusion (graph/fusion.py): with a TracedPrologue the
        # pipeline compiles chain transforms + key/value extraction into the
        # superscan itself; steps then carry RAW source columns and keying
        # is dense-int on device (no host key dictionary on the hot path)
        self.prologue = prologue
        self.mesh = mesh
        self._construction_key_capacity = key_capacity
        self.spec_outputs = None
        if assigners is not None:
            if prologue is None:
                raise ValueError(
                    "shared-partial windows run the traced-chain path "
                    "(dense device keying); a prologue is required")
            if tier is not None:
                raise ValueError(
                    "state.tier does not apply to the shared-partial path")
            self.spec_outputs = [[] for _ in assigners]
        if mesh is not None:
            # multichip SPMD (parallel.mesh.*): same operator surface, the
            # dispatch runs sharded over the mesh with the keyBy shuffle as
            # an in-scan all-to-all; snapshots stay canonical [K, S], so
            # this operator checkpoints/restores across mesh sizes
            from flink_tpu.parallel.sharded_superscan import (
                ShardedFusedPipeline,
            )

            self.pipe = ShardedFusedPipeline(
                mesh, assigner, self.agg,
                key_capacity=key_capacity, num_slices=num_slices, nsb=nsb,
                fires_per_step=fires_per_step, out_rows=out_rows,
                chunk=chunk, prologue=prologue, assigners=assigners,
                # skew-adaptive exchange (parallel.mesh.local-combine /
                # .skew-rebalance): pure perf switches over the same exact
                # results — see docs/multichip.md
                local_combine=mesh_local_combine,
                skew_routing=mesh_skew_routing,
                num_key_groups=mesh_key_groups,
            )
        elif assigners is not None:
            from flink_tpu.runtime.fused_window_pipeline import (
                SharedWindowPipeline,
            )

            self.pipe = SharedWindowPipeline(
                assigners, self.agg,
                key_capacity=key_capacity, num_slices=num_slices, nsb=nsb,
                fires_per_step=fires_per_step, out_rows=out_rows, chunk=chunk,
                prologue=prologue,
            )
        else:
            self.pipe = FusedWindowPipeline(
                assigner, self.agg,
                key_capacity=key_capacity, num_slices=num_slices, nsb=nsb,
                fires_per_step=fires_per_step, out_rows=out_rows, chunk=chunk,
                prologue=prologue,
            )
        self.T = superbatch_steps
        self.keydict = KeyDictionary(dense_int_keys or prologue is not None)
        self.tier = None
        if tier is not None:
            from flink_tpu.state.tier_manager import TieredStateManager

            self.tier = TieredStateManager(self.agg, self.pipe.S, tier)
            self.tier.attach_device(self.pipe.gather_key_rows,
                                    self.pipe.clear_key_rows,
                                    self.pipe.write_cells)
        self.norm = (
            SharedStepNormalizer(self.pipe, raw_payload=True)
            if assigners is not None
            else StepNormalizer(self.pipe, raw_payload=prologue is not None)
        )
        self._steps: List[_Step] = []
        # bounded in-flight dispatch ring: (DeferredEmissions, wm,
        # purged_to) entries, resolved FIFO. Depth 1 (the default) is
        # byte-identical to the historical single `_inflight` slot —
        # dispatch N+1 enqueues, THEN N resolves; latency mode deepens the
        # ring so N+1 stages and launches while N's copies land.
        self._inflight: Deque[tuple] = deque()
        self._max_inflight = 1
        # latency mode (execution.latency.target-ms): the adaptive rung
        # controller + donated carries + streaming readback. None keeps
        # every hot-path decision identical to throughput mode.
        self.latency = latency
        self._controller: Optional[SuperbatchController] = None
        self._ladder_geoms: set = set()   # distinct dispatch depths seen
        if latency is not None and latency.target_ms > 0:
            self._controller = SuperbatchController(
                full_steps=superbatch_steps,
                target_ms=latency.target_ms,
                floor_steps=latency.floor_steps,
                min_dwell_ms=latency.min_dwell_ms,
                hysteresis_pct=latency.hysteresis_pct,
            )
            self._max_inflight = max(int(latency.max_inflight), 1)
            self.pipe.donate_carry = True
            if mesh is None and latency.readback_steps > 0:
                # streaming fire readback is single-chip XLA only:
                # splitting the mesh dispatch would multiply the per-step
                # all-to-all collective count, so the mesh keeps
                # span-granular readback (docs/latency.md)
                self.pipe.readback_steps = int(latency.readback_steps)
        self.output: List[Tuple[Any, Any, Any, int]] = []
        self.emitted_watermark = MIN_WATERMARK
        self.current_watermark = MIN_WATERMARK
        self.columnar_output = columnar_output
        self._needs_value = any(f.source == VALUE for f in self.agg.fields)

    # ------------------------------------------------------------------
    def process_record(self, key, value, timestamp: int) -> None:
        self.process_batch(
            np.asarray([key]),
            np.asarray([0.0 if value is None else value], np.float32),
            np.asarray([timestamp], np.int64),
        )

    def process_batch(self, keys: np.ndarray, values: np.ndarray,
                      timestamps: np.ndarray) -> None:
        if self.prologue is not None:
            raise RuntimeError(
                "this operator runs a traced chain prologue; feed it raw "
                "columns via process_raw_batch"
            )
        if len(timestamps) == 0:
            return
        if self.tier is not None:
            self._process_batch_tiered(np.asarray(keys), values,
                                       np.asarray(timestamps, np.int64))
            return
        ids, required = self.keydict.lookup_or_insert(np.asarray(keys))
        self.pipe.ensure_key_capacity(required)
        vals = np.asarray(values, np.float32) if self._needs_value else None
        self._push_steps(
            self.norm.push(ids.astype(np.int32), vals,
                           np.asarray(timestamps, np.int64))
        )
        self._maybe_dispatch()

    # ------------------------------------------------------------------
    # tiered-state path (state/tier_manager.py)
    # ------------------------------------------------------------------
    def _tier_span(self):
        """(floor, device_hi, ring_limit): the live slice span the tier
        may move rows within. floor mirrors the normalizer's ring-floor
        math (min ever used, clamped by the purge frontier, cold touches
        included); ring_limit = floor + S - NSB is the hold-back bound —
        a promotion writing past it would alias ring positions earlier
        data still owns."""
        p = self.pipe
        touched = self.tier._touched
        cands = [x for x in (p.min_used_slice,
                             min(touched) if touched else None)
                 if x is not None]
        if not cands:
            return None, None, None
        lo = min(cands)
        if p.purged_to is not None:
            lo = max(lo, p.purged_to)
        hi = p.max_seen_slice if p.max_seen_slice is not None else lo
        return lo, hi, lo + p.S - p.NSB

    def _process_batch_tiered(self, keys: np.ndarray, values,
                              ts: np.ndarray) -> None:
        tier = self.tier
        s_abs = np.asarray(self.pipe._slice_of(ts))
        wm = self.norm.wm
        late = (s_abs < self.norm._min_live_slice(wm)
                if wm > MIN_WATERMARK else np.zeros(len(ts), bool))
        # an eviction reassigns dense ids — every buffered/in-flight step
        # (and its pending emissions, which map ids back to keys at
        # resolve) must land BEFORE the vocabulary moves; the check
        # over-approximates, so a flush can be spurious but never missed
        if tier.vocab.would_evict(keys):
            self.flush_all()
        vals = (np.asarray(values, np.float32)
                if self._needs_value and values is not None else None)
        routed = tier.route(keys, s_abs, vals, np.asarray(late, bool))
        if routed.demotions or routed.promotions:
            lo, hi, limit = self._tier_span()
            tier.apply_demotions(routed.demotions, lo, hi)
            span = tier.apply_promotions(routed.promotions, lo,
                                         None if limit is None
                                         else limit - 1, limit)
            if span is not None:
                # promoted rows are resident data the planner never saw
                # as steps: both frontier mirrors must account for them
                # or windows covering only promoted slices never fire
                self.pipe.note_external_slices(*span)
                self.norm.note_slices(*span)
        tier.journal_vocab_ops()
        ids = routed.ids
        live_hot = (ids >= 0) & ~np.asarray(late, bool)
        if live_hot.any():
            tier.note_hot_cells(ids[live_hot].astype(np.int64),
                                s_abs[live_hot])
        self._push_steps(self.norm.push(ids.astype(np.int32), vals, ts))
        self._maybe_dispatch()

    def process_raw_batch(self, values: np.ndarray,
                          timestamps: np.ndarray) -> None:
        """Whole-graph fusion ingest: raw source columns, untouched by any
        host transform — the traced prologue (chain + key/value extraction)
        runs inside the compiled dispatch."""
        if len(timestamps) == 0:
            return
        self._push_steps(
            self.norm.push(values, None, np.asarray(timestamps, np.int64))
        )
        self._maybe_dispatch()

    def process_watermark(self, watermark: int) -> None:
        if watermark <= self.current_watermark:
            return
        self.current_watermark = watermark
        steps = self.norm.advance(watermark)
        # a single-step advance rides the preceding data step (the pipeline
        # fires after ingesting step t's batch, so batch-then-advance in one
        # step is exactly the executor's batch-then-watermark order)
        if (
            steps
            and self._steps
            and self._steps[-1].n_fires == 0
            and len(steps[0].ts) == 0
        ):
            self._steps[-1].wm = steps[0].wm
            self._steps[-1].n_fires = steps[0].n_fires
            steps = steps[1:]
        self._push_steps(steps)
        if watermark >= MAX_WATERMARK - 1:
            self.flush_all()
        else:
            self._maybe_dispatch()

    def advance_processing_time(self, time: int) -> None:
        pass  # event-time only

    # ------------------------------------------------------------------
    def _push_steps(self, steps: List[_Step]) -> None:
        """Append planner-safe steps + feed the latency controller's
        windowed arrival estimate (watermark-only steps count: they occupy
        superbatch slots, so they are part of the fill rate)."""
        self._steps.extend(steps)
        if self._controller is not None and steps:
            self._controller.observe(len(steps))

    def _dispatch_target(self) -> int:
        """Steps a full dispatch cuts at: the adaptive rung under latency
        mode, the fixed span otherwise."""
        if self._controller is None:
            return self.T
        return self._controller.steps()

    def _maybe_dispatch(self) -> None:
        target = self._dispatch_target()
        while len(self._steps) >= target:
            self._dispatch(self._take_group(target=target))
            target = self._dispatch_target()

    def flush_all(self) -> None:
        """Dispatch every buffered step and resolve all in-flight output.
        Tail groups pad to the next power of two instead of T, so snapshots
        mid-superbatch compile at most log2(T) extra executable shapes
        instead of paying a full T-step dispatch per checkpoint."""
        while len(self._steps) >= self.T:
            self._dispatch(self._take_group())
        while self._steps:
            self._dispatch(self._take_group(tail=True))
        self._resolve_inflight()

    def _take_group(self, tail: bool = False,
                    target: Optional[int] = None) -> List[_Step]:
        limit = self.T if target is None else target
        group: List[_Step] = []
        fires = 0
        while self._steps and len(group) < limit:
            s = self._steps[0]
            if fires + s.n_fires > self.pipe.R and group:
                break  # out_rows budget: cut the dispatch early
            fires += s.n_fires
            group.append(self._steps.pop(0))
        target = (1 << max(len(group) - 1, 0).bit_length()) if tail else limit
        # pads carry the LAST REAL step's watermark, not the normalizer's
        # committed one — steps still queued behind an early cut have lower
        # watermarks, and a future-stamped pad would do the whole jump in
        # one step and blow fires_per_step
        pad_wm = group[-1].wm if group else None
        while len(group) < target:
            group.append(self.norm.pad_step(pad_wm))  # bounded executable shapes
        return group

    def _dispatch(self, group: List[_Step]) -> None:
        wms = [s.wm for s in group]
        if self.prologue is not None:
            d = self.pipe.process_superbatch_raw(
                [(s.kid, s.ts, s.s_abs) for s in group], wms, defer=True)
        else:
            d = self.pipe.process_superbatch(
                [(s.kid, s.vals, s.ts) for s in group], wms, defer=True)
        if self._controller is not None:
            self._ladder_geoms.add(len(group))
        # the purge frontier as of THIS dispatch's staging: cold-tier rows
        # below it may only be deleted after this dispatch's emissions
        # have resolved (they read the cold rows of the windows that just
        # fired) — a lagged frontier each ring entry carries to its own
        # resolve, so purge_below always advances with resolution order
        self._inflight.append((d, group[-1].wm, self.pipe.purged_to))
        # depth 1 reproduces the historical slot byte-for-byte: the new
        # dispatch enqueues first, THEN the previous one resolves
        while len(self._inflight) > self._max_inflight:
            self._resolve_oldest()

    # emission-latency plane: set by the runner when the plane is on;
    # stamped at the DEFERRED RESOLVE below — the only point where a
    # fired window's rows become host-visible — never at dispatch
    emission_tracker = None

    def _resolve_inflight(self) -> None:
        """Drain the whole in-flight ring (FIFO). Every barrier that needs
        the operator quiescent — flush_all (and thus snapshot), routing
        swaps, tier evictions — lands here, so exactly-once capture points
        see an empty ring regardless of its configured depth."""
        while self._inflight:
            self._resolve_oldest()

    def _resolve_oldest(self) -> None:
        d, wm, purged_to = self._inflight.popleft()
        tracker = self.emission_tracker
        for window, counts, fields in d.resolve():
            if tracker is not None:
                w = window[1] if type(window) is tuple else window
                tracker.record_fire(w.end)
            self._emit(window, counts, fields)
        if wm > self.emitted_watermark:
            self.emitted_watermark = wm
        if self.tier is not None:
            self.tier.purge_below(purged_to)

    def _emit(self, window, counts, fields) -> None:
        if self.spec_outputs is not None:
            # shared partials: the pipeline tags each fire with its member
            # window spec; route the emission to that member's output lane
            spec, win = window
            self._emit_dense_rows(win, counts, fields,
                                  self.spec_outputs[spec])
            return
        if self.tier is not None:
            self._emit_tiered(window, counts, fields)
            return
        if self.prologue is not None:
            self._emit_dense_rows(window, counts, fields, self.output)
            return
        counts = np.asarray(counts)[: len(self.keydict)]
        live = np.flatnonzero(counts > 0)
        if live.size == 0:
            return
        self._emit_keydict_rows(window, counts, fields, live)

    def _emit_dense_rows(self, window, counts, fields, sink: list) -> None:
        """Dense-device-keying emission (traced prologue): the emitted key
        IS the id the traced selector produced — every capacity row may be
        live. `sink` selects the output lane (shared partials route per
        member window spec)."""
        counts = np.asarray(counts)
        live = np.flatnonzero(counts > 0)
        if live.size == 0:
            return
        fdict: Dict[str, Any] = {
            f.name: (counts if f.source == ONE
                     else np.asarray(fields[f.name]))
            for f in self.agg.fields
        }
        result = np.asarray(self.agg.extract(fdict))
        ts = window.max_timestamp()
        if self.columnar_output:
            sink.append((None, window, (window, live, result[live]), ts))
            return
        for i in live:
            sink.append((int(i), window, result[i].item(), ts))

    def _emit_keydict_rows(self, window, counts, fields, live) -> None:
        fdict: Dict[str, Any] = {}
        for f in self.agg.fields:
            if f.source == ONE:
                fdict[f.name] = counts
            else:
                fdict[f.name] = np.asarray(fields[f.name])[: len(self.keydict)]
        result = np.asarray(self.agg.extract(fdict))
        ts = window.max_timestamp()
        if self.columnar_output:
            # one packed row per fire: (window, dense key ids, values) —
            # emission cost stays O(1) rows regardless of key cardinality
            # (map ids back through .keydict when raw keys are needed)
            self.output.append((None, window, (window, live, result[live]), ts))
            return
        keys = self.keydict.keys_for(live)
        for k, i in zip(keys, live):
            self.output.append((k, window, result[i].item(), ts))

    def _emit_tiered(self, window, counts, fields) -> None:
        """Row-mode emission merging both tiers: resident keys fire from
        the device rows, cold keys from the cold store. A key whose data
        is SPLIT across tiers for this window (partial promotion left
        far-future rows cold) combines per the field scatter ops before
        extraction, so placement can never change a result."""
        p = self.pipe
        j = (window.start - p.offset) // p.slide_ms
        slice_range = range(j * p.sl, j * p.sl + p.spw)
        counts = np.asarray(counts).astype(np.int64).copy()
        vals = {f.name: np.asarray(fields[f.name]).copy()
                for f in self.agg.fields if f.source != ONE}
        cold = self.tier.cold_fire(slice_range)
        combine = {"add": lambda a, b: a + b, "min": min, "max": max}
        extras: List[tuple] = []   # (key, counts, {field: value}) cold-only
        if cold is not None:
            ckids, cfields, ccounts = cold
            vocab = self.tier.vocab
            for i, cid in enumerate(ckids):
                key = vocab.key_of_cold_id(int(cid))
                hid = None if key is None else vocab.resident_id(key)
                if hid is not None:
                    counts[hid] += int(ccounts[i])
                    for f in self.agg.fields:
                        if f.source == ONE:
                            continue
                        vals[f.name][hid] = combine[f.scatter](
                            vals[f.name][hid].item(),
                            cfields[f.name][i].item())
                elif key is not None:
                    extras.append((key, int(ccounts[i]),
                                   {n: cfields[n][i] for n in cfields}))
        ts = window.max_timestamp()
        live = np.flatnonzero(counts > 0)
        if live.size:
            fdict = {f.name: (counts if f.source == ONE else vals[f.name])
                     for f in self.agg.fields}
            result = np.asarray(self.agg.extract(fdict))
            vocab = self.tier.vocab
            for i in live:
                self.output.append((vocab.key_of_id(int(i)), window,
                                    result[i].item(), ts))
        if extras:
            e_counts = np.asarray([e[1] for e in extras], np.int64)
            fdict_e = {
                f.name: (e_counts if f.source == ONE
                         else np.asarray([e[2][f.name] for e in extras],
                                         np.dtype(f.dtype)))
                for f in self.agg.fields
            }
            result_e = np.asarray(self.agg.extract(fdict_e))
            for i, (key, _c, _f) in enumerate(extras):
                self.output.append((key, window, result_e[i].item(), ts))

    def drain_output(self) -> List[Tuple[Any, Any, Any, int]]:
        out = self.output
        self.output = []
        return out

    def drain_spec_output(self, spec: int) -> List[Tuple[Any, Any, Any, int]]:
        """Shared partials: drain one member window's output lane (the
        shared runner routes lane i to member i's downstream edges)."""
        out = self.spec_outputs[spec]
        self.spec_outputs[spec] = []
        return out

    def query_state_for(self, key) -> Dict[int, Dict[str, Any]]:
        """Point lookup (queryable state): {abs_slice: {field..., count}}
        for one key, folding device ring cells, buffered steps, and
        held-back future records into one consistent view."""
        if self.prologue is not None:
            raise RuntimeError(
                "queryable state is unavailable on the fused chain path: "
                "buffered steps hold raw pre-transform columns, so a "
                "consistent per-key view would need the traced UDFs on host"
            )
        if self.tier is not None:
            raise RuntimeError(
                "queryable state is unavailable on the tiered path: a "
                "key's cells may be split across the HBM ring and the "
                "cold store mid-movement; read the window emissions "
                "instead"
            )
        kid = self.keydict.lookup(key)
        if kid is None:
            return {}
        pipe = self.pipe
        # canonical [K, S] view: the sharded pipeline holds [n, K_local, S]
        # and the contiguous key ranges make the reshape exact (a no-op on
        # the single-chip layout)
        count = np.asarray(pipe._count).reshape(pipe.K, pipe.S)[kid]
        acc = {k: np.asarray(v).reshape(pipe.K, pipe.S)[kid]
               for k, v in pipe._state.items()}
        slices: Dict[int, Dict[str, Any]] = {}
        lo = pipe.purged_to if pipe.purged_to is not None else pipe.min_used_slice
        hi = pipe.max_seen_slice
        if lo is not None and hi is not None:
            for s in range(lo, hi + 1):
                pos = s % pipe.S
                if count[pos] > 0:
                    entry = {name: arr[pos].item() for name, arr in acc.items()}
                    entry["count"] = int(count[pos])
                    slices[s] = entry
        combine = {"add": lambda a, b: a + b, "min": min, "max": max}
        pending = [(s.kid, s.vals, s.ts) for s in self._steps] + self.norm._future
        for kid_arr, val_arr, ts_arr in pending:
            sel = np.flatnonzero(np.asarray(kid_arr) == kid)
            for i in sel:
                s = int((int(ts_arr[i]) - pipe.offset) // pipe.g)
                entry = slices.setdefault(s, {"count": 0})
                entry["count"] = entry.get("count", 0) + 1
                for f in self.agg.fields:
                    if f.source != VALUE:
                        continue
                    v = float(val_arr[i]) if val_arr is not None else 1.0
                    entry[f.name] = combine[f.scatter](entry.get(f.name, f.identity), v)
        return slices

    # ------------------------------------------------------------------
    @property
    def num_late_records_dropped(self) -> int:
        return self.pipe.num_late_records_dropped

    # -- device-plane observability ------------------------------------
    def attach_device_stats(self, tracker, phase_counters: bool = True) -> None:
        """Wire a CompileTracker (metrics/device_stats.py) around every
        superscan dispatch and thread the ingest/fire/purge phase counters
        through the compiled scan carry. Must be called before the first
        batch — the phase flag is part of the executable cache key."""
        self.pipe.attach_device_stats(tracker, phase_counters=phase_counters)

    def phase_totals(self) -> Dict[str, int]:
        """Cumulative per-phase superscan step counters (resolved
        dispatches only): records ingested, fire slots executed, steps
        that purged — where a laggard kernel's device time goes."""
        t = self.pipe.phase_totals
        return {"ingestRecords": int(t[0]), "fireSteps": int(t[1]),
                "purgeSteps": int(t[2])}

    def key_loads(self):
        """Device-resident per-key record counts for the key-stats fold."""
        return self.pipe.key_loads()

    def per_device_key_loads(self):
        """[n, K_local] per-device local loads on the mesh path (None on a
        single chip): the per-device skew fold's input — a globally even
        key histogram can still pile every hot key-group on one device."""
        fn = getattr(self.pipe, "per_device_key_loads", None)
        return fn() if fn is not None else None

    def mesh_devices(self) -> int:
        """Devices this operator's state is sharded over (1 = single chip)."""
        return int(getattr(self.pipe, "n", 1))

    # -- skew-aware key-group routing (parallel.mesh.skew-rebalance) ----
    def routing_version(self):
        """Version of the mesh routing table (None off the mesh or with
        static routing)."""
        fn = getattr(self.pipe, "routing_version", None)
        return fn() if callable(fn) else None

    def routing_payload(self):
        """/jobs/:id/device routing block (None without a table)."""
        fn = getattr(self.pipe, "routing_payload", None)
        return fn() if callable(fn) else None

    def mesh_group_loads(self):
        """Per-key-group resident loads [G] — the rebalancer's decision
        input; None without a routing table."""
        fn = getattr(self.pipe, "mesh_group_loads", None)
        return fn() if callable(fn) else None

    def set_routing_assignment(self, assign) -> int:
        """Apply a new key-group -> device map at an operator-quiescent
        point: any in-flight dispatch resolves FIRST (its fire rows were
        produced under the old table and must canonicalize under it), then
        the table swaps and the device rows re-lay. Exactly-once by
        construction — canonical state and cursors never change."""
        self._resolve_inflight()
        return self.pipe.set_routing_assignment(assign)

    def mesh_capacity(self) -> int:
        """The key capacity the mesh clamp used at CONSTRUCTION time — a
        rescale-target pre-check must clamp against this, not the grown
        pipe.K: a rebuilt operator starts from this capacity again (the
        grown snapshot re-adopts K at restore), so a target reachable only
        under the grown K would tear the job down for a no-op rebuild."""
        return int(self._construction_key_capacity)

    def key_stats_ready(self) -> bool:
        """O(1) host probe: has any superbatch dispatch landed data in the
        device ring yet? (Steps buffer host-side first — a key-stats fold
        before the first dispatch would read an empty ring.)"""
        return self.pipe.max_seen_slice is not None

    def state_row_bytes(self) -> int:
        return self.pipe.state_row_bytes()

    # -- observability gauges ------------------------------------------
    def state_bytes(self) -> int:
        """HBM footprint of the slice-ring arrays (0 until the pipeline's
        first dispatch materializes them)."""
        state = getattr(self.pipe, "_state", None) or {}
        n = sum(int(getattr(a, "nbytes", 0)) for a in state.values())
        n += int(getattr(getattr(self.pipe, "_count", None), "nbytes", 0) or 0)
        return n

    def state_key_count(self) -> int:
        if self.tier is not None:
            return self.tier.vocab.vocab_size
        return len(self.keydict)

    # -- state-tier observability --------------------------------------
    def tier_gauges(self):
        """The tier gauge family (vocabSize/residentKeys/evictions/
        promotions/spilledBytes/changelogBytes/tierHotFillRatio), or None
        when tiering is off — the runner registers one gauge per key."""
        return None if self.tier is None else self.tier.gauges()

    def tier_payload(self):
        """/jobs/:id/device tier block (None when tiering is off)."""
        return None if self.tier is None else self.tier.payload()

    # -- latency-mode observability ------------------------------------
    def latency_gauges(self):
        """The latency-mode controller gauge family, or None when the mode
        is off — registered by the runner next to the tier family, folded
        MAX across shards (cluster._LATENCY_CONTROLLER_GAUGES), surfaced
        in /jobs/:id/device and the /jobs/:id/latency report."""
        if self._controller is None:
            return None
        return {
            "latencyModeActive": 1,
            "currentBatchRung": int(self._controller.current_steps()),
            "inflightDepth": len(self._inflight),
            "ladderRecompiles": len(self._ladder_geoms),
        }

    def _reset_dispatch_ring(self) -> None:
        """Restore/rebuild quiescence: discard unresolved in-flight
        handles (their fires re-run from the restored state) and re-hold
        the controller's full-span rung — pre-failure arrival samples
        describe a stream position that no longer exists."""
        self._inflight.clear()
        if self._controller is not None:
            self._controller.reset()

    def _pack_output(self):
        """Undrained emissions ride every checkpoint; in the tiered
        incremental path they dominate the per-interval delta, so scalar
        numeric rows pack columnar (~3x smaller pickled than a list of
        (key, TimeWindow, value, ts) tuples). Non-scalar rows fall back
        to the raw list."""
        rows = self.output
        from flink_tpu.core.time import TimeWindow as _TW

        if rows and all(
                isinstance(r[1], _TW) and np.isscalar(r[2]) for r in rows):
            return {
                "packed": True,
                "keys": [r[0] for r in rows],
                "starts": np.asarray([r[1].start for r in rows], np.int64),
                "ends": np.asarray([r[1].end for r in rows], np.int64),
                "vals": np.asarray([r[2] for r in rows]),
                "ts": np.asarray([r[3] for r in rows], np.int64),
            }
        return {"packed": False, "rows": list(rows)}

    @staticmethod
    def _unpack_output(packed) -> list:
        if not packed.get("packed"):
            return list(packed["rows"])
        from flink_tpu.core.time import TimeWindow as _TW

        return [
            (k, _TW(int(s), int(e)), v.item(), int(t))
            for k, s, e, v, t in zip(
                packed["keys"], packed["starts"], packed["ends"],
                packed["vals"], packed["ts"])
        ]

    def _tier_meta(self) -> dict:
        """Host-side stream position + operator state that rides every
        tiered checkpoint (full or incremental): what restore_changelog
        overlays on the reconstructed arrays."""
        p = self.pipe
        return {
            "watermark": p.watermark,
            "fire_cursor": p.fire_cursor,
            "purged_to": p.purged_to,
            "min_used_slice": p.min_used_slice,
            "max_seen_slice": p.max_seen_slice,
            "num_late_dropped": p.num_late_records_dropped,
            "norm": self.norm.snapshot(),
        }

    def _envelope(self) -> dict:
        """The transient operator surface that rides the checkpoint
        ENVELOPE, not the state changelog: resolved-but-undrained
        emissions are output, not keyed state — journaling them would
        charge every interval delta for rows the pre-checkpoint flush
        regenerates wholesale."""
        return {
            "output": self._pack_output(),
            "emitted_watermark": self.emitted_watermark,
            "current_watermark": self.current_watermark,
        }

    def _apply_tier_meta(self, meta: dict, envelope: dict) -> None:
        self.norm.restore(meta["norm"])
        self._steps = []
        self._reset_dispatch_ring()
        self.output = self._unpack_output(envelope["output"])
        self.emitted_watermark = envelope["emitted_watermark"]
        self.current_watermark = envelope["current_watermark"]

    def snapshot(self) -> dict:
        # flush buffered steps so keyed state lives in exactly one place
        # (the device arrays); fires this triggers land in "output" below
        # and ride the checkpoint, so they are re-emitted after restore
        # rather than lost (their fire_cursor has already advanced)
        self.flush_all()
        if self.tier is not None:
            meta = self._tier_meta()
            if self.tier.log is not None:
                # incremental: ONE cells entry + a (base, offset) handle —
                # checkpoint bytes scale with the interval delta
                return {"tier_changelog": self.tier.checkpoint(
                    meta, self.pipe.gather_cells,
                    lambda: self.pipe.snapshot()),
                    **self._envelope()}
            return {"pipe": self.pipe.snapshot(),
                    "tier": self.tier.full_snapshot(),
                    "meta": meta, **self._envelope()}
        snap_extra = {}
        if self.spec_outputs is not None:
            # shared partials: undrained per-member lanes ride the
            # checkpoint like the plain output list
            snap_extra["spec_outputs"] = [list(x) for x in self.spec_outputs]
        return {
            **snap_extra,
            "pipe": self.pipe.snapshot(),
            "keydict": self.keydict.snapshot(),
            "normalizer": self.norm.snapshot(),
            # self-describing metadata so offline tools (state processor)
            # can fold not-yet-dispatched steps into (key, slice) cells
            "fields": [
                (f.name, f.scatter, f.identity, f.source, np.dtype(f.dtype).str)
                for f in self.agg.fields
            ],
            "geometry": {"g": self.pipe.g, "offset": self.pipe.offset},
            # resolved-but-undrained emissions: their fires are already
            # committed in device state, so dropping them at restore would
            # lose output — they ride the checkpoint instead
            "output": list(self.output),
            "emitted_watermark": self.emitted_watermark,
            "current_watermark": self.current_watermark,
        }

    def restore(self, snap: dict) -> None:
        if "tier_changelog" in snap:
            if self.tier is None:
                raise RuntimeError(
                    "this checkpoint is an incremental (changelog) tiered "
                    "snapshot; the restoring operator has state.tier "
                    "disabled")
            out = self.tier.restore_changelog(snap["tier_changelog"])
            self.pipe.restore(out["pipe"])
            self._apply_tier_meta(out["meta"], snap)
            return
        if "tier" in snap:
            if self.tier is None:
                raise RuntimeError(
                    "this checkpoint is a tiered snapshot; the restoring "
                    "operator has state.tier disabled")
            self.pipe.restore(snap["pipe"])
            self.tier.restore_full(snap["tier"])
            self._apply_tier_meta(snap["meta"], snap)
            return
        if self.tier is not None:
            # the reverse direction must fail as loudly as the forward
            # one: restoring a classic (grow-only keydict) snapshot into
            # a tiered operator would route new keys through an EMPTY
            # vocabulary whose recycled dense ids alias the restored
            # rows' old keys — silent misattribution, never an error
            raise RuntimeError(
                "this checkpoint is a classic (untired) snapshot; the "
                "restoring operator has state.tier enabled — restore it "
                "with tiering off, or take a fresh tiered checkpoint")
        self.pipe.restore(snap["pipe"])
        self.keydict = KeyDictionary.restore(snap["keydict"])
        self.norm.restore(snap["normalizer"])
        self._steps = []
        self.emitted_watermark = snap["emitted_watermark"]
        self.current_watermark = snap["current_watermark"]
        self._reset_dispatch_ring()
        self.output = list(snap["output"])
        if self.spec_outputs is not None:
            self.spec_outputs = [list(x) for x in snap["spec_outputs"]]
