"""FusedWindowPipeline: whole-stream windowed aggregation, N steps per dispatch.

The throughput sibling of TpuWindowOperator (same semantic contracts,
different execution granularity). TpuWindowOperator dispatches one device
program per batch and syncs per fire; over a high-latency host<->device link
every interaction costs a fixed round trip, so this pipeline compiles a
`lax.scan` over T steps — ingest, fire, purge fused — into ONE device
program, with all per-step control decisions (ring columns, fire slots,
purge masks) precomputed on host from the watermark schedule and staged as
device arrays. Outputs land in a compact [R, K] on-device buffer read back
once per dispatch.

This is the moral analogue of the reference's record batching across the
network boundary (RecordWriter flushes buffers, not records:
flink-runtime/.../api/writer/RecordWriter.java:105): amortize the fixed
per-interaction cost, keep the semantics per-element.

Semantics preserved (parity-tested against OracleWindowOperator):
- slice-decomposed window assignment (TimeWindow.getWindowStartWithOffset),
- EventTimeTrigger firing: window j fires when wm >= end(j)-1, in j order,
  after the batch that advanced the watermark was ingested,
- fire-then-purge ordering at the same watermark (WindowOperator.onEventTime
  fires the trigger before cleanup at the same timestamp),
- too-late records (newest containing window already cleaned) dropped and
  counted, matching isWindowLate (WindowOperator.java:609).

Restrictions of the fused path (callers fall back to TpuWindowOperator):
event-time only, add-combining aggregates (sum/count/mean-style),
allowed_lateness == 0, dense int keys or pre-densified key ids.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from flink_tpu.api.windowing.assigners import WindowAssigner
from flink_tpu.core.time import MIN_WATERMARK, TimeWindow
from flink_tpu.ops.aggregators import DeviceAggregator, ONE, VALUE, resolve
from flink_tpu.utils.arrays import canonical_column


def _ceil_div(a: int, b: int) -> int:
    return -((-a) // b)


@dataclasses.dataclass
class _PlannedFire:
    row: int          # output-buffer row
    j: int            # window index
    step: int         # step within the dispatch
    spec: int = 0     # window spec (shared-partial pipelines; 0 otherwise)


@dataclasses.dataclass(frozen=True)
class TracedPrologue:
    """The traced pre-stage of a fused device chain (whole-graph fusion,
    graph/fusion.py): chain transforms applied to the raw value column
    INSIDE the compiled superscan, then key/value extraction. All callables
    must be pure jax-traceable column functions; `key_fn` must return
    non-negative int keys < the pipeline's key capacity (checked against a
    max-key reduction carried through the scan and raised at resolve time —
    an out-of-range key must never silently alias another key's row)."""

    transforms: Tuple[Tuple[str, Any], ...]   # ('map'|'filter'|'map_ts', fn)
    key_fn: Any
    value_fn: Optional[Any] = None            # None: the column IS the value

    @property
    def needs_ts(self) -> bool:
        return any(kind == "map_ts" for kind, _fn in self.transforms)


#: compiled chained-superscan executables, shared across pipeline instances
#: (FIFO-bounded; entries keep the user fns alive, which is what makes
#: identity-keyed caching safe)
_CHAINED_CACHE: Dict[tuple, Any] = {}
_CHAINED_CACHE_MAX = 128


class DeferredEmissions:
    """Handle for fires of one dispatch; the device->host copy runs async."""

    def __init__(self, pipe: "FusedWindowPipeline", fires, count_out, outs,
                 key_bounds=None, key_capacity: Optional[int] = None,
                 phase_counts=None):
        self._pipe = pipe
        self._fires = fires
        self._count_out = count_out
        self._outs = outs
        self._key_bounds = key_bounds    # int32[2]: [max_seen, min_seen]
        self._key_capacity = key_capacity
        # int32[3] per-phase step counters of this dispatch (device-plane
        # observability); folded into the pipeline's totals at resolve so
        # the readback rides the same async copy as the fire rows
        self._phase_counts = phase_counts
        try:
            count_out.copy_to_host_async()
            for v in outs.values():
                v.copy_to_host_async()
            if key_bounds is not None:
                key_bounds.copy_to_host_async()
            if phase_counts is not None:
                phase_counts.copy_to_host_async()
        except AttributeError:
            pass

    def resolve(self):
        if self._phase_counts is not None:
            self._pipe.phase_totals += np.asarray(
                self._phase_counts, dtype=np.int64)
            self._phase_counts = None
        if self._key_bounds is not None:
            hi, lo = (int(v) for v in np.asarray(self._key_bounds))
            if hi >= self._key_capacity or lo < 0:
                raise ValueError(
                    f"traced key selector produced keys in [{lo}, {hi}] "
                    f"outside [0, {self._key_capacity}): the fused device "
                    "chain uses dense integer keys and cannot grow capacity "
                    "mid-dispatch. Raise 'execution.state.key-capacity' "
                    "above the largest key the selector can emit (and keep "
                    "keys non-negative), or drop traceable=True on key_by "
                    "to use the host key dictionary."
                )
        count_np = np.asarray(self._count_out)
        outs_np = {k: np.asarray(v) for k, v in self._outs.items()}
        return [
            (
                self._pipe._window_of_fire(pf),
                count_np[pf.row],
                {k: v[pf.row] for k, v in outs_np.items()},
            )
            for pf in self._fires
        ]


class _StreamedEmissions:
    """Composite deferred handle for a step-group streamed dispatch
    (latency mode): one DeferredEmissions per (readback_steps, B) group,
    each of which started its async device->host copy the moment its
    group's scan was enqueued — fires from early step groups become
    host-visible while later groups are still computing. resolve()
    concatenates the per-group resolutions in group order, reproducing
    the whole-span handle's emission order and payloads exactly."""

    def __init__(self, parts: List[DeferredEmissions]):
        self._parts = parts

    def resolve(self):
        out = []
        for p in self._parts:
            out.extend(p.resolve())
        return out


class _PlanCursor:
    """The fire/purge planning state machine for one dispatch.

    Both stage_superbatch (data-driven) and plan_superbatch (bounds-driven)
    drive this cursor; the plans they produce must be bit-identical for
    identical streams, so the per-step logic lives only here.
    """

    def __init__(self, pipe: "FusedWindowPipeline"):
        self.p = pipe
        self.wm = pipe.watermark
        self.fire_cursor = pipe.fire_cursor
        self.purged_to = pipe.purged_to
        self.min_used = pipe.min_used_slice
        self.max_seen = pipe.max_seen_slice

    def observe(self, smin: int, smax: int) -> None:
        """Account for a step whose live records occupy slices [smin, smax]."""
        p = self.p
        if smax - smin >= p.NSB:
            raise ValueError(
                f"batch spans {smax - smin + 1} slices > nsb={p.NSB}; "
                "raise nsb or shrink batches"
            )
        if self.purged_to is not None and smin < self.purged_to:
            raise AssertionError("late-drop check should bound smin")
        if self.max_seen is not None and self.max_seen - smin >= p.S:
            # Pre-watermark inverted skew: this batch's slices lie >= S
            # slices BELOW data already resident. Hold-back (StepNormalizer)
            # only bounds the future direction — past-direction space never
            # reopens (the purge frontier moves forward), so this is a
            # configuration limit, not a transient: the resident span must
            # fit the ring.
            raise ValueError(
                f"slice ring too small for this skew: batch slice "
                f"{smin} is {self.max_seen - smin} slices below the "
                f"newest resident slice {self.max_seen}, but the ring "
                f"holds only num_slices={p.S}. Raise "
                f"'execution.window.num-slices' above the expected "
                f"pre-watermark timestamp skew (in slices), or "
                f"advance the watermark sooner so old slices purge."
            )
        self.min_used = smin if self.min_used is None else min(self.min_used, smin)
        self.max_seen = smax if self.max_seen is None else max(self.max_seen, smax)
        self._note_fire_candidate(smin)

    def _note_fire_candidate(self, smin: int) -> None:
        p = self.p
        cand = p._j_oldest(smin)
        if self.wm > MIN_WATERMARK:
            cand = max(cand, p._j_fired_upto(self.wm) + 1)
        self.fire_cursor = cand if self.fire_cursor is None else min(self.fire_cursor, cand)

    def advance(self, t: int, new_wm: int, fire_pos, fire_valid, fire_row,
                purge_mask, fires: list) -> None:
        """Watermark advance after step t: plan fires (window order) + purge."""
        p = self.p
        if new_wm <= self.wm:
            return
        self._plan_fires(t, new_wm, fire_pos, fire_valid, fire_row, fires)
        # purge columns whose slices expired
        new_min_live = p._min_live_slice(new_wm)
        if self.min_used is not None:
            lo = self.min_used if self.purged_to is None else max(self.purged_to, self.min_used)
            hi_p = min(new_min_live, self.max_seen + 1)
            if hi_p - lo >= p.S:
                purge_mask[t, :] = 0
            elif hi_p > lo:
                dead = (np.arange(lo, hi_p) % p.S).astype(np.int64)
                purge_mask[t, dead] = 0
        self.purged_to = new_min_live if self.purged_to is None else max(self.purged_to, new_min_live)
        self.wm = new_wm

    def _plan_fires(self, t: int, new_wm: int, fire_pos, fire_valid,
                    fire_row, fires: list) -> None:
        p = self.p
        if self.fire_cursor is not None and self.max_seen is not None:
            hi = min(p._j_fired_upto(new_wm), p._j_newest(self.max_seen))
            slot = 0
            for j in range(self.fire_cursor, hi + 1):
                if slot >= p.F:
                    raise ValueError(
                        f"{hi + 1 - self.fire_cursor} windows fire in one step "
                        f"> fires_per_step={p.F}"
                    )
                if len(fires) >= p.R:
                    raise ValueError(f"more than out_rows={p.R} fires per dispatch")
                row = len(fires)
                fires.append(_PlannedFire(row, j, t))
                fire_pos[t, slot] = (j * p.sl) % p.S
                fire_valid[t, slot] = 1
                fire_row[t, slot] = row
                slot += 1
            if p._j_fired_upto(new_wm) >= self.fire_cursor:
                self.fire_cursor = p._j_fired_upto(new_wm) + 1

    def commit(self) -> None:
        p = self.p
        p.watermark = self.wm
        p.fire_cursor = self.fire_cursor
        p.purged_to = self.purged_to
        p.min_used_slice = self.min_used
        p.max_seen_slice = self.max_seen


class FusedWindowPipeline:
    """One shard's keyed window aggregation, executed T steps per dispatch."""

    def __init__(
        self,
        assigner: WindowAssigner,
        aggregate,
        *,
        key_capacity: int,
        num_slices: Optional[int] = None,
        nsb: int = 4,                 # max distinct slices touched per batch
        fires_per_step: int = 2,
        out_rows: int = 64,           # max fires per dispatch
        chunk: int = 8192,
        exact_sums: bool = True,
        backend: str = "auto",        # 'auto' | 'xla' | 'pallas'
        pallas_interpret: bool = False,
        plan_only: bool = False,      # host planner/cursors only, no device state
        prologue: Optional[TracedPrologue] = None,
    ):
        agg = resolve(aggregate)
        if agg is None:
            raise ValueError(f"aggregate {aggregate!r} has no device form")
        for f in agg.fields:
            if f.scatter not in ("add", "min", "max"):
                raise ValueError(
                    f"fused pipeline supports add/min/max-combining fields; "
                    f"{f.name!r} uses {f.scatter!r} (use TpuWindowOperator)"
                )
        if assigner.slice_ms is None or not assigner.is_event_time:
            raise ValueError(f"{assigner!r} is not a sliceable event-time assigner")
        self.agg = agg
        self.K = key_capacity
        self.NSB = nsb
        self.F = fires_per_step
        self.R = out_rows
        self.chunk = chunk
        self.exact_sums = exact_sums
        self.prologue = prologue
        if prologue is not None:
            # the traced chain prologue runs inside the XLA superscan; the
            # pallas kernel consumes prebuilt idx streams and has no
            # prologue slot (on TPU the XLA superscan still runs on device)
            backend = "xla"
        self.backend = backend
        self.pallas_interpret = pallas_interpret
        # traced-chain state: fixed raw-column geometry (the compiled chain
        # executables live in the module-level _CHAINED_CACHE, keyed on the
        # prologue + aggregate + geometry, so a re-built pipeline for the
        # same program re-uses the jitted program instead of recompiling)
        self._raw_shape: Optional[tuple] = None
        self._raw_dtype = None
        self._pallas: Optional[bool] = None   # decided at first dispatch
        self._kernel_layout = False           # states in pallas slice-major form
        # device-plane observability (metrics/device_stats.py): an attached
        # CompileTracker wraps every dispatch; phase_counters threads the
        # ingest/fire/purge counters through the XLA superscan carry
        # (accumulated into phase_totals at resolve). Both are wired by
        # attach_device_stats BEFORE the first dispatch — phase_counters is
        # part of the executable cache key.
        self.compile_tracker = None
        self.phase_counters = False
        self.phase_totals = np.zeros(3, np.int64)  # [ingest, fire, purge]
        # latency-mode dispatch shape (scheduler/latency_controller.py),
        # flipped by the operator when execution.latency.target-ms is on:
        # donate_carry donates the [K, S] scan carry to the executable
        # (kills the state copy on the hot path — part of every executable
        # cache key, so flag-off jobs never share a donated program);
        # readback_steps > 0 splits a T-step dispatch into (T/readback_steps)
        # chained step-group programs so fired rows start their async
        # device->host copy per group instead of at span completion.
        self.donate_carry = False
        self.readback_steps = 0

        self.g = assigner.slice_ms
        self.sl = assigner.slide_slices
        self.spw = assigner.slices_per_window
        self.offset = assigner.offset_ms
        self.size_ms = self.spw * self.g
        self.slide_ms = self.sl * self.g
        # shared-partials (SharedWindowPipeline): per-fire-slot slice-run
        # lengths; None = the classic uniform-SPW program
        self._fire_spws: Optional[Tuple[int, ...]] = None
        if num_slices is None:
            num_slices = 1 << (self.spw + nsb + 8 - 1).bit_length()
        self.S = num_slices

        self._value_fields = [f for f in agg.fields if f.source == VALUE]
        self._needs_vals = bool(self._value_fields)

        self.plan_only = plan_only
        if plan_only:
            # pure host planner (e.g. the sharded pipeline's control plane):
            # never allocate the [K, S] device arrays
            self._state = {}
            self._count = None
        else:
            import jax.numpy as jnp

            self._state = {
                f.name: jnp.full((self.K, self.S), f.identity, jnp.dtype(f.dtype))
                for f in agg.fields
                if f.source == VALUE
            }
            self._count = jnp.zeros((self.K, self.S), jnp.int32)

        # host-side stream position
        self.watermark = MIN_WATERMARK
        self.fire_cursor: Optional[int] = None
        self.purged_to: Optional[int] = None
        self.min_used_slice: Optional[int] = None
        self.max_seen_slice: Optional[int] = None
        self.num_late_records_dropped = 0

        self._fn_cache: Dict[Tuple[int, int], Any] = {}

    # ------------------------------------------------------------------
    # backend selection + state layout
    # ------------------------------------------------------------------
    def _use_pallas(self) -> bool:
        """Decide (once) whether dispatches run on the fused pallas kernel.

        'auto' picks pallas on a real TPU backend when the aggregate has a
        matmul form (add-combining fields only) and the geometry fits VMEM;
        everything else stays on the XLA superscan (which also serves the
        shard_map/multi-chip path and CPU CI).
        """
        if self._pallas is None:
            from flink_tpu.ops import pallas_superscan

            if self.backend == "xla":
                self._pallas = False
            else:
                ok = pallas_superscan.supports(
                    self.agg, self.K, self.R, self.S, self.NSB, self.chunk
                )
                if self.backend == "pallas":
                    if not ok:
                        raise ValueError(
                            "pallas superscan does not support this "
                            "aggregate/geometry (need add-combining or "
                            "bounded-domain max fields, K%128==0, "
                            "VMEM-sized state)"
                        )
                    self._pallas = True
                else:
                    import jax

                    self._pallas = ok and jax.default_backend() == "tpu"
        return self._pallas

    def _to_kernel_layout(self) -> None:
        if self._kernel_layout:
            return
        from flink_tpu.ops import pallas_superscan as ps

        self._count = ps.to_kernel_layout(self._count, self.K, self.S)
        self._state = {
            k: ps.to_kernel_layout(v, self.K, self.S)
            for k, v in self._state.items()
        }
        self._kernel_layout = True

    def _to_canonical(self) -> None:
        if not self._kernel_layout:
            return
        from flink_tpu.ops import pallas_superscan as ps

        self._count = ps.from_kernel_layout(self._count, self.K, self.S)
        self._state = {
            k: ps.from_kernel_layout(v, self.K, self.S)
            for k, v in self._state.items()
        }
        self._kernel_layout = False

    def _require_state(self) -> None:
        if getattr(self, "plan_only", False):
            raise RuntimeError(
                "this FusedWindowPipeline is plan_only (host planner); it "
                "has no device state to snapshot/restore/grow"
            )

    def ensure_key_capacity(self, required: int) -> None:
        """Grow the key dimension (next pow2) when the dictionary outgrows K;
        existing rows keep their accumulators, new rows start at identity.
        The superscan executable is per-K (cache-keyed), so growth costs one
        recompile — amortized by doubling, like the columnar backend's
        ensure_key_capacity."""
        if required <= self.K:
            return
        self._require_state()
        self._to_canonical()
        import jax.numpy as jnp

        new_k = 1 << (required - 1).bit_length()
        pad = new_k - self.K
        self._state = {
            f.name: jnp.concatenate(
                [self._state[f.name],
                 jnp.full((pad, self.S), f.identity, jnp.dtype(f.dtype))]
            )
            for f in self.agg.fields
            if f.source == VALUE
        }
        self._count = jnp.concatenate(
            [self._count, jnp.zeros((pad, self.S), jnp.int32)]
        )
        self.K = new_k
        self._pallas = None  # geometry changed; re-decide backend

    # ------------------------------------------------------------------
    # tiered-state row surface (state/tier_manager.py): the tier manager
    # moves whole key rows between the HBM ring and the cold tier through
    # these accessors. All of them run OFF the dispatch hot path
    # (demotion/promotion happens between superbatches, cell gathers at
    # checkpoint time), so they use eager device ops, canonical layout.
    # ------------------------------------------------------------------
    def gather_key_rows(self, kids: np.ndarray):
        """Read whole rows: (counts np[m, S], {field: np[m, S]})."""
        self._require_state()
        self._to_canonical()
        import jax.numpy as jnp

        k = jnp.asarray(np.asarray(kids, np.int32))
        counts = np.asarray(self._count[k])
        fields = {n: np.asarray(a[k]) for n, a in self._state.items()}
        return counts, fields

    def clear_key_rows(self, kids: np.ndarray) -> None:
        """Reset rows to identity (the demotion cut)."""
        self._require_state()
        self._to_canonical()
        import jax.numpy as jnp

        k = jnp.asarray(np.asarray(kids, np.int32))
        self._count = self._count.at[k].set(0)
        idents = {f.name: f.identity for f in self.agg.fields
                  if f.source == VALUE}
        self._state = {
            n: a.at[k].set(jnp.asarray(idents[n], a.dtype))
            for n, a in self._state.items()
        }

    def write_cells(self, kids: np.ndarray, spos: np.ndarray,
                    counts: np.ndarray, fields: Dict[str, np.ndarray]) -> None:
        """Set individual ring cells (the promotion scatter). Target rows
        must hold identity at the written positions (fresh or cleared) —
        the caller's tier invariant, so .set never clobbers live data."""
        self._require_state()
        self._to_canonical()
        import jax.numpy as jnp

        k = jnp.asarray(np.asarray(kids, np.int32))
        s = jnp.asarray(np.asarray(spos, np.int32))
        self._count = self._count.at[k, s].set(
            jnp.asarray(np.asarray(counts, np.int32)))
        self._state = {
            n: a.at[k, s].set(jnp.asarray(
                np.asarray(fields[n]), a.dtype))
            for n, a in self._state.items()
        }

    def gather_cells(self, kids: np.ndarray, spos: np.ndarray):
        """Point-read cells: (counts np[m], {field: np[m]}) — the
        changelog delta's checkpoint-time value source."""
        self._require_state()
        self._to_canonical()
        import jax.numpy as jnp

        k = jnp.asarray(np.asarray(kids, np.int32))
        s = jnp.asarray(np.asarray(spos, np.int32))
        counts = np.asarray(self._count[k, s])
        fields = {n: np.asarray(a[k, s]) for n, a in self._state.items()}
        return counts, fields

    def note_external_slices(self, smin: int, smax: int) -> None:
        """Account for rows placed into the ring OUTSIDE a planned step
        (tier promotion): the fire planner must treat the span as
        resident data or windows covering only promoted slices would
        never fire. Mirrors _PlanCursor.observe's frontier updates; the
        fire-cursor candidate clamps to already-fired windows so a
        promotion can never re-fire."""
        self.min_used_slice = (smin if self.min_used_slice is None
                               else min(self.min_used_slice, smin))
        self.max_seen_slice = (smax if self.max_seen_slice is None
                               else max(self.max_seen_slice, smax))
        cand = self._j_oldest(smin)
        if self.watermark > MIN_WATERMARK:
            cand = max(cand, self._j_fired_upto(self.watermark) + 1)
        self.fire_cursor = (cand if self.fire_cursor is None
                            else min(self.fire_cursor, cand))

    # ------------------------------------------------------------------
    # window geometry (identical formulas to TpuWindowOperator)
    # ------------------------------------------------------------------
    def _slice_of(self, ts: np.ndarray) -> np.ndarray:
        return (ts - np.int64(self.offset)) // np.int64(self.g)

    def _j_fired_upto(self, wm: int) -> int:
        return (wm + 1 - self.offset - self.size_ms) // self.slide_ms

    def _min_live_slice(self, wm: int) -> int:
        return (self._j_fired_upto(wm) + 1) * self.sl

    def _j_newest(self, s: int) -> int:
        return s // self.sl

    def _j_oldest(self, s: int) -> int:
        return _ceil_div(s - self.spw + 1, self.sl)

    def _window_of(self, j: int) -> TimeWindow:
        start = self.offset + j * self.slide_ms
        return TimeWindow(start, start + self.size_ms)

    def _window_of_fire(self, pf: "_PlannedFire") -> TimeWindow:
        """Window of a planned fire (shared-partial pipelines dispatch on
        pf.spec; the single-window pipeline ignores it)."""
        return self._window_of(pf.j)

    def _wm_keeping_slice_live(self, s: int) -> int:
        """Largest watermark at which slice `s` has not been purged
        (_min_live_slice(wm) <= s) — the held-record watermark cap the
        StepNormalizer stages against. Single source for the formula so
        the shared-partial pipeline can widen it to its longest window."""
        return self.offset + (s // self.sl) * self.slide_ms + self.size_ms - 1 - 1

    def _cursor(self) -> "_PlanCursor":
        """Fire/purge planning state machine factory (the shared-partial
        pipeline substitutes its multi-spec cursor)."""
        return _PlanCursor(self)

    # ------------------------------------------------------------------
    # compiled superscan
    # ------------------------------------------------------------------
    def _superscan(self, T: int, B: int):
        return _build_superscan(
            self.agg, self.K, self.S, self.NSB, self.F, self.R,
            self.spw, self.chunk, self.exact_sums, T, B,
            phases=self.phase_counters, fire_spws=self._fire_spws,
            donate=self.donate_carry,
        )

    # ------------------------------------------------------------------
    # device-plane observability (metrics/device_stats.py)
    # ------------------------------------------------------------------
    def attach_device_stats(self, tracker, phase_counters: bool = True) -> None:
        """Attach a CompileTracker (and opt into the per-phase superscan
        counters). Must run before the first dispatch: the phase flag is
        part of the executable cache key."""
        self.compile_tracker = tracker
        self.phase_counters = bool(phase_counters)

    def _signature(self, program_extra: Dict[str, Any]) -> Dict[str, Any]:
        """Shape signature of the next dispatch — the key the tracker
        diffs for recompile cause attribution (K change = ring doubling,
        T/B change = batch-geometry churn, dtype change = dtype change)."""
        sig: Dict[str, Any] = {
            "K": self.K, "S": self.S, "NSB": self.NSB, "F": self.F,
            "R": self.R,
            "dtype": "+".join(str(np.dtype(f.dtype))
                              for f in self._value_fields) or "count",
        }
        sig.update(program_extra)
        return sig

    def _tracked(self, program: str, fn, args: tuple, extra: Dict[str, Any]):
        """Dispatch through the attached CompileTracker (or directly)."""
        if self.compile_tracker is None:
            return fn(*args)
        return self.compile_tracker.call(
            program, fn, args, self._signature(extra))

    def key_loads(self):
        """Device-resident per-key record counts ([K] int32): the input of
        the key-stats fold (metrics/key_stats.py) — one segment-sum over
        the count ring that is already in HBM. None before the first
        dispatch materializes state (or on a plan-only planner)."""
        count = getattr(self, "_count", None)
        if count is None:
            return None
        if self._kernel_layout:
            from flink_tpu.ops import pallas_superscan as ps

            count = ps.from_kernel_layout(count, self.K, self.S)
        return count.sum(axis=1)

    def state_row_bytes(self) -> int:
        """HBM bytes per key row (all slice cells of one key across count
        + value fields) — the key-stats state-bytes histogram scale."""
        n = 4 * self.S  # int32 count ring
        for f in self._value_fields:
            n += np.dtype(f.dtype).itemsize * self.S
        return n

    # ------------------------------------------------------------------
    # host planner + dispatch
    # ------------------------------------------------------------------
    def process_superbatch(
        self,
        batches: Sequence[Tuple[np.ndarray, Optional[np.ndarray], np.ndarray]],
        watermarks: Sequence[int],
        *,
        staged: Optional[tuple] = None,
        defer: bool = False,
    ):
        """Run T = len(batches) steps in one dispatch.

        batches: (key_ids int32[B], values f32[B] | None, timestamps int64[B]);
        watermarks[i] is the watermark after batch i. Returns one
        (window, count_row[K], {field: row[K]}) per fired window, in fire
        order; row entries for keys with count 0 are meaningless.

        defer=True returns a DeferredEmissions handle immediately after
        enqueuing the dispatch and starting the async device->host copy;
        call .resolve() later. The next process_superbatch may be enqueued
        before resolving (the state carry stays on device).
        """
        import jax
        import jax.numpy as jnp

        if staged is not None:
            idx_d, vals_d, plan = staged
        else:
            idx_d, vals_d, plan = self.stage_superbatch(batches, watermarks)
        T = len(batches) if batches is not None else int(plan[0].shape[0])
        if watermarks is not None:
            assert T == len(watermarks)
        (smin_pos, fire_pos, fire_valid, fire_row, purge_mask, fires) = plan

        B = idx_d.shape[1] if idx_d.ndim == 2 else idx_d.shape[0] // T
        if self._use_pallas():
            from flink_tpu.ops import pallas_superscan as ps

            self._to_kernel_layout()
            run = ps.build_superscan(
                self.agg, self.K, self.S, self.NSB, self.F, self.spw,
                self.R, T, B, self.chunk, self.exact_sums,
                self.pallas_interpret, fire_spws=self._fire_spws,
            )
            names = [f.name for f in self._value_fields]
            idx_flat = idx_d if idx_d.ndim == 1 else idx_d.reshape(-1)
            vals_flat = None
            if self._needs_vals:
                vals_flat = vals_d if vals_d.ndim == 1 else vals_d.reshape(-1)
            count_state, field_states, count_out, field_outs = self._tracked(
                "pallas_superscan", run,
                (smin_pos, fire_pos, fire_valid, fire_row, purge_mask,
                 self._count, tuple(self._state[n] for n in names),
                 idx_flat, vals_flat),
                {"T": T, "B": B},
            )
            self._count = count_state
            self._state = dict(zip(names, field_states))
            count_out = ps.rows_to_keys(count_out, self.R, self.K)
            outs = {
                n: ps.rows_to_keys(o, self.R, self.K)
                for n, o in zip(names, field_outs)
            }
        else:
            self._to_canonical()
            # the backend decision can legitimately flip between staging and
            # dispatch (ensure_key_capacity growth, restore); re-shape staged
            # inputs to the layout this backend expects
            if idx_d.ndim == 1:
                idx_d = idx_d.reshape(T, B)
            if self._needs_vals and vals_d.ndim == 1:
                vals_d = vals_d.reshape(T, B)
            Tg = self.readback_steps
            if 0 < Tg < T and T % Tg == 0:
                deferred = self._process_grouped(
                    T, B, Tg, idx_d, vals_d, smin_pos, fire_pos,
                    fire_valid, fire_row, purge_mask, fires)
                return deferred if defer else deferred.resolve()
            run = self._superscan(T, B)
            outs0 = {
                f.name: jnp.zeros((self.R, self.K), jnp.dtype(f.dtype))
                for f in self._value_fields
            }
            count_out0 = jnp.zeros((self.R, self.K), jnp.int32)
            out = self._tracked(
                "fused_superscan", run,
                (self._state, self._count, outs0, count_out0,
                 idx_d, vals_d, smin_pos, fire_pos, fire_valid, fire_row,
                 purge_mask),
                {"T": T, "B": B},
            )
            if self.phase_counters:
                self._state, self._count, outs, count_out, pc = out
            else:
                self._state, self._count, outs, count_out = out

        # read back only the rows actually fired (padded to a few stable
        # shapes so the slice executable is reused across dispatches)
        used = -(-max(len(fires), 1) // 16) * 16
        if used < self.R:
            count_out = _slice_rows(count_out, used)
            outs = {k: _slice_rows(v, used) for k, v in outs.items()}

        deferred = DeferredEmissions(
            self, fires, count_out, outs,
            phase_counts=(pc if self.phase_counters and not self._use_pallas()
                          else None))
        return deferred if defer else deferred.resolve()

    def _process_grouped(self, T, B, Tg, idx_d, vals_d, smin_pos, fire_pos,
                         fire_valid, fire_row, purge_mask, fires):
        """Streaming fire readback (latency mode): run one T-step dispatch
        as G = T/Tg chained (Tg, B) programs carrying state on device, so
        each group's fired rows start their async device->host copy when
        the group's scan is enqueued instead of at span completion. Fire
        rows are planned with GLOBAL output-buffer indices across the span
        — each group's fresh output buffer populates only its own fires'
        rows — so resolving the per-group handles in order reproduces the
        whole-span emission order and payloads byte-for-byte. Pow2 ladder
        rungs make T % Tg == 0 whenever Tg fits; geometries that do not
        divide fall through to the whole-span readback."""
        import jax.numpy as jnp

        run = self._superscan(Tg, B)
        parts: List[DeferredEmissions] = []
        done = 0
        for g in range(T // Tg):
            lo, hi = g * Tg, (g + 1) * Tg
            outs0 = {
                f.name: jnp.zeros((self.R, self.K), jnp.dtype(f.dtype))
                for f in self._value_fields
            }
            count_out0 = jnp.zeros((self.R, self.K), jnp.int32)
            out = self._tracked(
                "fused_superscan", run,
                (self._state, self._count, outs0, count_out0,
                 idx_d[lo:hi], vals_d[lo:hi], smin_pos[lo:hi],
                 fire_pos[lo:hi], fire_valid[lo:hi], fire_row[lo:hi],
                 purge_mask[lo:hi]),
                {"T": Tg, "B": B},
            )
            pc = None
            if self.phase_counters:
                self._state, self._count, outs, count_out, pc = out
            else:
                self._state, self._count, outs, count_out = out
            g_fires = [pf for pf in fires if lo <= pf.step < hi]
            done += len(g_fires)
            # rows are assigned in fire order across the WHOLE span: the
            # highest row this group can populate is the cumulative count
            used = -(-max(done, 1) // 16) * 16
            if used < self.R:
                count_out = _slice_rows(count_out, used)
                outs = {k: _slice_rows(v, used) for k, v in outs.items()}
            parts.append(DeferredEmissions(
                self, g_fires, count_out, outs, phase_counts=pc))
        return _StreamedEmissions(parts)

    def stage_superbatch(self, batches, watermarks):
        """Host planning + device staging for one dispatch (separable so
        callers can overlap staging of superbatch i+1 with running i)."""
        import jax
        import jax.numpy as jnp

        T = len(batches)
        B = max(max((len(b[2]) for b in batches), default=0), 1)
        B = -(-B // self.chunk) * self.chunk

        idx_h = np.full((T, B), -1, dtype=np.int32)
        # value-less aggregates (count) carry a [T,1] placeholder instead of
        # shipping a dead [T,B] f32 column to the device
        vals_h = np.zeros((T, B if self._needs_vals else 1), dtype=np.float32)
        smin_pos = np.zeros(T, dtype=np.int32)
        fire_pos = np.zeros((T, self.F), dtype=np.int32)
        fire_valid = np.zeros((T, self.F), dtype=np.int32)
        fire_row = np.zeros((T, self.F), dtype=np.int32)
        purge_mask = np.ones((T, self.S), dtype=np.int32)
        fires: List[_PlannedFire] = []

        cur = self._cursor()
        for t, (kid, vals, ts) in enumerate(batches):
            n = len(ts)
            s_abs = self._slice_of(np.asarray(ts, dtype=np.int64))
            keep = np.ones(n, dtype=bool)
            if cur.wm > MIN_WATERMARK:
                keep = s_abs >= self._min_live_slice(cur.wm)
                self.num_late_records_dropped += int(n - keep.sum())
            if keep.any():
                live = s_abs[keep]
                smin = int(live.min())
                cur.observe(smin, int(live.max()))
                srel = (s_abs - smin).astype(np.int32)
                # kid -1 = a cold-routed record (state/tier_manager.py):
                # it rides the step so fires over its slices get PLANNED,
                # but it must never scatter into a hot row — mask to the
                # same -1 the ingest drops (pad-row semantics)
                kid64 = np.asarray(kid, dtype=np.int64)
                idx_h[t, :n] = np.where(
                    keep & (kid64 >= 0), kid64 * self.NSB + srel, -1
                ).astype(np.int32)
                if vals is not None and self._needs_vals:
                    vals_h[t, :n] = np.where(keep, vals, 0.0)
                smin_pos[t] = smin % self.S
            cur.advance(t, watermarks[t], fire_pos, fire_valid, fire_row,
                        purge_mask, fires)
        cur.commit()

        if self._use_pallas():
            # the fused kernel consumes flat [T*B] chunk streams; flatten on
            # host (free: idx_h is contiguous) so no device reshape is needed
            idx_d = jax.device_put(idx_h.reshape(-1))
            vals_d = jax.device_put(
                vals_h.reshape(-1) if self._needs_vals else vals_h
            )
        else:
            idx_d = jax.device_put(idx_h)
            vals_d = jax.device_put(vals_h)
        plan = (
            jax.device_put(smin_pos),
            jax.device_put(fire_pos),
            jax.device_put(fire_valid),
            jax.device_put(fire_row),
            jax.device_put(purge_mask),
            fires,
        )
        return idx_d, vals_d, plan

    def plan_superbatch(self, slice_bounds, watermarks):
        """Host planning from per-step slice BOUNDS only — for callers that
        stage the record stream themselves (e.g. the benchmark's on-device
        generator, which synthesizes `idx = key_id * NSB + (slice - smin)`
        directly in HBM and never ships per-record data over the host link).

        slice_bounds: [(smin_abs, smax_abs)] per step — inclusive bounds on
        the absolute slices the step's records can occupy. The caller must
        guarantee no record falls outside its step's bounds and no record is
        late (bounds below the live frontier raise here).

        Returns (plan, smin_abs[int32 T]) where plan is staged-plan
        compatible: pass `staged=(idx_dev, vals_dev, plan)` to
        process_superbatch.
        """
        import jax

        T = len(slice_bounds)
        assert T == len(watermarks)
        smin_pos = np.zeros(T, dtype=np.int32)
        smin_abs = np.zeros(T, dtype=np.int32)
        fire_pos = np.zeros((T, self.F), dtype=np.int32)
        fire_valid = np.zeros((T, self.F), dtype=np.int32)
        fire_row = np.zeros((T, self.F), dtype=np.int32)
        purge_mask = np.ones((T, self.S), dtype=np.int32)
        fires: List[_PlannedFire] = []

        cur = self._cursor()
        for t, (smin, smax) in enumerate(slice_bounds):
            if cur.wm > MIN_WATERMARK and smin < self._min_live_slice(cur.wm):
                raise ValueError(
                    "plan_superbatch requires a late-free schedule: step "
                    f"{t} smin={smin} is below the live frontier "
                    f"{self._min_live_slice(cur.wm)}"
                )
            cur.observe(smin, smax)
            smin_pos[t] = smin % self.S
            smin_abs[t] = smin
            cur.advance(t, watermarks[t], fire_pos, fire_valid, fire_row,
                        purge_mask, fires)
        cur.commit()

        plan = (
            jax.device_put(smin_pos),
            jax.device_put(fire_pos),
            jax.device_put(fire_valid),
            jax.device_put(fire_row),
            jax.device_put(purge_mask),
            fires,
        )
        return plan, smin_abs

    # ------------------------------------------------------------------
    # traced-chain path (whole-graph fusion): the chain prologue runs
    # INSIDE the compiled superscan — raw source columns go to the device,
    # filter/projection/key/value extraction never materialize on host
    # ------------------------------------------------------------------
    def stage_superbatch_raw(self, steps, watermarks):
        """Host planning + device staging for one traced-chain dispatch.

        steps: [(raw_column [n, ...], timestamps int64 [n][, slice_ids])] —
        raw source values BEFORE any chain transform (slice_ids optional:
        the normalizer's precomputed `_slice_of(ts)`). The host plans
        fires/purges from
        the timestamps alone (the chain never changes timestamps, and a
        filter only removes records, so timestamp-derived slice bounds stay
        valid upper bounds; windows planned over filtered-out slices fire
        empty rows, which emission drops). Late records are masked to
        srel -1 here (and counted), so the traced program never sees them
        as live."""
        import jax

        raw_h, srel_h, ts_h, plan_np, fires = self._stage_raw_host(
            steps, watermarks)
        plan = tuple(jax.device_put(a) for a in plan_np) + (fires,)
        ts_d = jax.device_put(ts_h) if ts_h is not None else None
        return jax.device_put(raw_h), jax.device_put(srel_h), ts_d, plan

    def _stage_raw_host(self, steps, watermarks):
        """The host half of stage_superbatch_raw: plan + fill the staging
        buffers, but leave device placement to the caller — the sharded
        pipeline (parallel/sharded_superscan.py) re-shapes the same buffers
        onto mesh lanes and device_puts them with a NamedSharding instead.
        Returns (raw_h, srel_h, ts_h|None, plan_arrays, fires)."""
        if self.prologue is None:
            raise RuntimeError("stage_superbatch_raw requires a prologue")
        T = len(steps)
        B = max(max((len(step[1]) for step in steps), default=0), 1)
        # staged width quantized to power-of-two multiples of the chunk:
        # ragged last batches and watermark-only tail groups land on a few
        # bounded shapes (log2 many) instead of compiling a fresh (T, B)
        # executable per width, while tiny tails keep tiny staging buffers
        # — pad rows are srel -1 and never touch state
        B = self.chunk * (1 << max(0, -(-B // self.chunk) - 1).bit_length())

        for raw, ts, *_rest in steps:
            if not len(ts):
                continue
            arr = np.asarray(raw)
            if self._raw_shape is None:
                if arr.dtype == object:
                    raise TypeError(
                        "the fused device chain needs numeric record "
                        "columns; this source yields Python objects — use a "
                        "columnar source (numeric ndarray batches) or drop "
                        "traceable=True to stay on the host chain"
                    )
                self._raw_shape, self._raw_dtype = arr.shape[1:], arr.dtype
            elif arr.shape[1:] != self._raw_shape or arr.dtype != self._raw_dtype:
                raise ValueError(
                    f"record column geometry changed mid-stream: "
                    f"{arr.dtype}{list(arr.shape[1:])} after "
                    f"{self._raw_dtype}{list(self._raw_shape)} — the fused "
                    "chain executable is shaped on a fixed column layout"
                )
        raw_shape, raw_dtype = self._raw_shape, self._raw_dtype
        if raw_shape is None:
            # all-empty superbatch before any data: a scalar placeholder
            # column for THIS dispatch only — pinning it on the instance
            # would make the first real batch afterwards (e.g. a watermark
            # arriving right after restore) read as a mid-stream geometry
            # change and crash a healthy job
            raw_shape, raw_dtype = (), np.dtype(np.float32)

        # np.empty, not zeros: pad rows are srel -1 — every traced consumer
        # masks on that before touching raw/ts, so the 16MB+ staging memset
        # per dispatch would be pure waste. Buffers are allocated in jax's
        # CANONICAL dtype (x64-off: float64→float32, int64→int32): device_put
        # of a non-canonical array re-casts the whole buffer host-side every
        # dispatch — a full extra copy, and the garbage pad bytes overflow
        # the narrowing float cast (RuntimeWarning). Real rows cast at fill.
        from jax import dtypes as _jdt
        raw_h = np.empty((T, B) + raw_shape,
                         dtype=_jdt.canonicalize_dtype(raw_dtype))
        srel_h = np.full((T, B), -1, dtype=np.int32)
        ts_h = (np.empty((T, B), dtype=_jdt.canonicalize_dtype(np.int64))
                if self.prologue.needs_ts else None)
        smin_pos = np.zeros(T, dtype=np.int32)
        fire_pos = np.zeros((T, self.F), dtype=np.int32)
        fire_valid = np.zeros((T, self.F), dtype=np.int32)
        fire_row = np.zeros((T, self.F), dtype=np.int32)
        purge_mask = np.ones((T, self.S), dtype=np.int32)
        fires: List[_PlannedFire] = []

        cur = self._cursor()
        for t, step in enumerate(steps):
            raw, ts = step[0], step[1]
            pre_s_abs = step[2] if len(step) > 2 else None
            n = len(ts)
            if n:
                ts_arr = np.asarray(ts, dtype=np.int64)
                s_abs = (pre_s_abs if pre_s_abs is not None
                         else self._slice_of(ts_arr))
                keep = np.ones(n, dtype=bool)
                if cur.wm > MIN_WATERMARK:
                    keep = s_abs >= self._min_live_slice(cur.wm)
                    self.num_late_records_dropped += int(n - keep.sum())
                if keep.any():
                    live = s_abs[keep]
                    smin = int(live.min())
                    cur.observe(smin, int(live.max()))
                    srel_h[t, :n] = np.where(keep, s_abs - smin, -1).astype(np.int32)
                    smin_pos[t] = smin % self.S
                # checked canonical cast: an int64/float64 source column
                # narrowing into the staging dtype must not silently wrap
                # (same contract as the timestamp guard below); the host
                # fallback casts through the same helper, so both paths
                # compute on identical canonical inputs
                raw_h[t, :n] = canonical_column(
                    raw, "fused chain record column")
                if ts_h is not None:
                    if ts_h.dtype.itemsize < 8 and (
                        int(ts_arr.max()) > np.iinfo(ts_h.dtype).max
                        or int(ts_arr.min()) < np.iinfo(ts_h.dtype).min
                    ):
                        raise TypeError(
                            "traceable map_with_timestamp under the fused "
                            "chain stages timestamps in the backend's "
                            f"canonical {ts_h.dtype} (jax x64 is disabled) "
                            "and these event timestamps do not fit — they "
                            "would silently wrap inside the traced UDF. "
                            "Rebase event time near zero, enable jax x64, "
                            "or drop traceable=True to run the host chain."
                        )
                    ts_h[t, :n] = ts_arr
            cur.advance(t, watermarks[t], fire_pos, fire_valid, fire_row,
                        purge_mask, fires)
        cur.commit()

        return (raw_h, srel_h, ts_h,
                (smin_pos, fire_pos, fire_valid, fire_row, purge_mask), fires)

    def process_superbatch_raw(self, steps, watermarks, *,
                               staged: Optional[tuple] = None,
                               defer: bool = False):
        """Run T traced-chain steps in one dispatch (the prologue-bearing
        sibling of process_superbatch; same defer contract)."""
        import jax.numpy as jnp

        if staged is None and all(len(step[1]) == 0 for step in steps):
            # watermark-only dispatch: with zero rows the prologue is
            # irrelevant, so run the classic (prologue-free) fire/purge
            # program over the same device state — tracing the chained
            # program would apply the user's column fns to a placeholder
            # scalar column (crashing any 2-D selector), and this also
            # covers the restore-then-watermark ordering where the record
            # geometry is still unknown but restored state must fire
            empty = [(np.empty(0, np.int32), None, np.empty(0, np.int64))
                     for _ in steps]
            return self.process_superbatch(empty, watermarks, defer=defer)
        if staged is None:
            staged = self.stage_superbatch_raw(steps, watermarks)
        raw_d, srel_d, ts_d, plan = staged
        (smin_pos, fire_pos, fire_valid, fire_row, purge_mask, fires) = plan
        T, B = srel_d.shape

        self._to_canonical()
        Tg = self.readback_steps
        if 0 < Tg < T and T % Tg == 0:
            deferred = self._process_grouped_raw(
                T, B, Tg, raw_d, srel_d, ts_d, smin_pos, fire_pos,
                fire_valid, fire_row, purge_mask, fires)
            return deferred if defer else deferred.resolve()
        run = self._chained_superscan(T, B)
        outs0 = {
            f.name: jnp.zeros((self.R, self.K), jnp.dtype(f.dtype))
            for f in self._value_fields
        }
        count_out0 = jnp.zeros((self.R, self.K), jnp.int32)
        xs = (raw_d, srel_d)
        if self.prologue.needs_ts:
            xs = xs + (ts_d,)
        xs = xs + (smin_pos, fire_pos, fire_valid, fire_row, purge_mask)
        out = self._tracked(
            "fused_chained_superscan", run,
            (self._state, self._count, outs0, count_out0) + xs,
            {"T": T, "B": B, "raw_dtype": str(raw_d.dtype)},
        )
        pc = None
        if self.phase_counters:
            self._state, self._count, outs, count_out, key_bounds, pc = out
        else:
            self._state, self._count, outs, count_out, key_bounds = out

        used = -(-max(len(fires), 1) // 16) * 16
        if used < self.R:
            count_out = _slice_rows(count_out, used)
            outs = {k: _slice_rows(v, used) for k, v in outs.items()}
        deferred = DeferredEmissions(self, fires, count_out, outs,
                                     key_bounds=key_bounds,
                                     key_capacity=self.K,
                                     phase_counts=pc)
        return deferred if defer else deferred.resolve()

    def _process_grouped_raw(self, T, B, Tg, raw_d, srel_d, ts_d, smin_pos,
                             fire_pos, fire_valid, fire_row, purge_mask,
                             fires):
        """Streaming fire readback for the traced-chain path — the
        _process_grouped contract (global fire rows, per-group async copy,
        byte-identical resolution order) over the chained executable; the
        per-group key_bounds check still covers every surviving record
        because the groups partition the span's steps."""
        import jax.numpy as jnp

        run = self._chained_superscan(Tg, B)
        needs_ts = self.prologue.needs_ts
        parts: List[DeferredEmissions] = []
        done = 0
        for g in range(T // Tg):
            lo, hi = g * Tg, (g + 1) * Tg
            outs0 = {
                f.name: jnp.zeros((self.R, self.K), jnp.dtype(f.dtype))
                for f in self._value_fields
            }
            count_out0 = jnp.zeros((self.R, self.K), jnp.int32)
            xs = (raw_d[lo:hi], srel_d[lo:hi])
            if needs_ts:
                xs = xs + (ts_d[lo:hi],)
            xs = xs + (smin_pos[lo:hi], fire_pos[lo:hi], fire_valid[lo:hi],
                       fire_row[lo:hi], purge_mask[lo:hi])
            out = self._tracked(
                "fused_chained_superscan", run,
                (self._state, self._count, outs0, count_out0) + xs,
                {"T": Tg, "B": B, "raw_dtype": str(raw_d.dtype)},
            )
            pc = None
            if self.phase_counters:
                self._state, self._count, outs, count_out, key_bounds, pc = out
            else:
                self._state, self._count, outs, count_out, key_bounds = out
            g_fires = [pf for pf in fires if lo <= pf.step < hi]
            done += len(g_fires)
            used = -(-max(done, 1) // 16) * 16
            if used < self.R:
                count_out = _slice_rows(count_out, used)
                outs = {k: _slice_rows(v, used) for k, v in outs.items()}
            parts.append(DeferredEmissions(
                self, g_fires, count_out, outs, key_bounds=key_bounds,
                key_capacity=self.K, phase_counts=pc))
        return _StreamedEmissions(parts)

    def _chained_superscan(self, T: int, B: int):
        # module-level memo: the key holds STRONG references to the user
        # fns (via the frozen TracedPrologue), so identity-hashed entries
        # can never collide with a recycled id; builtin DeviceAggregators
        # are memoized singletons, custom ones identity-hash conservatively
        key = (self.prologue, self.agg, self.K, self.S, self.NSB, self.F,
               self.R, self.spw, self.chunk, self.exact_sums, T, B,
               self.phase_counters, self._fire_spws, self.donate_carry)
        fn = _CHAINED_CACHE.get(key)
        if fn is None:
            while len(_CHAINED_CACHE) >= _CHAINED_CACHE_MAX:
                _CHAINED_CACHE.pop(next(iter(_CHAINED_CACHE)))
            fn = _CHAINED_CACHE[key] = self._build_chained_superscan(T, B)
        return fn

    def _build_chained_superscan(self, T: int, B: int):
        """Compile prologue + T-step superscan into one program. On CPU
        backends ingest uses direct scatter-adds ([K, S] is cache-resident
        and the MXU one-hot matmuls that win on TPU lose badly on a scalar
        core); on TPU the matmul-histogram ingest is kept."""
        import jax
        import jax.numpy as jnp

        from flink_tpu.ops.superscan import default_ingest

        pro = self.prologue
        ingest = default_ingest()
        phases = self.phase_counters
        step = make_superscan_step(
            self.agg, self.K, self.S, self.NSB, self.F, self.R,
            self.spw, self.chunk, self.exact_sums, ingest=ingest,
            phase_counters=phases, fire_spws=self._fire_spws,
        )
        K, NSB = self.K, self.NSB
        needs_vals = self._needs_vals
        needs_ts = pro.needs_ts
        transforms = tuple(pro.transforms)
        key_fn, value_fn = pro.key_fn, pro.value_fn

        def body(carry, args):
            inner, key_bounds = carry
            if needs_ts:
                raw, srel, ts = args[0], args[1], args[2]
                rest = args[3:]
            else:
                raw, srel = args[0], args[1]
                ts = None
                rest = args[2:]
            col = raw
            mask = srel >= 0
            for kind, fn in transforms:
                if kind == "map":
                    col = fn(col)
                elif kind == "map_ts":
                    col = fn(col, ts)
                else:  # filter
                    mask = mask & jnp.asarray(fn(col)).astype(bool)
            keys = jnp.asarray(key_fn(col)).astype(jnp.int32)
            live = mask & (keys >= 0) & (keys < K)
            idx = jnp.where(live, keys * NSB + srel, jnp.int32(-1))
            idx = idx.astype(jnp.int32)
            if needs_vals:
                vcol = value_fn(col) if value_fn is not None else col
                # dead/pad rows hold uninitialized staging bytes that can
                # decode as NaN/inf; zero them BEFORE ingest — the matmul
                # histogram multiplies the zero one-hot by the raw value,
                # and 0 * NaN = NaN would poison every sum in the chunk
                # (the scatter path drops by index, but identical inputs
                # keep both ingest forms bit-identical)
                vals = jnp.where(
                    live, jnp.asarray(vcol).astype(jnp.float32), 0.0)
            else:
                vals = jnp.zeros((1,), jnp.float32)
            # key range observed over every SURVIVING record (pre range
            # clamp): an out-of-range key is a hard error at resolve, never
            # a silent drop or a silent alias of another key's row
            key_bounds = jnp.stack([
                jnp.maximum(key_bounds[0],
                            jnp.max(jnp.where(mask, keys, jnp.int32(-1)))),
                jnp.minimum(key_bounds[1],
                            jnp.min(jnp.where(mask, keys, jnp.int32(0)))),
            ])
            inner, _ = step(inner, (idx, vals) + rest)
            return (inner, key_bounds), None

        def run(state, count, outs, count_out, *xs):
            kb0 = jnp.asarray([-1, 0], jnp.int32)
            inner0 = (state, count, outs, count_out)
            if phases:
                inner0 = inner0 + (jnp.zeros((3,), jnp.int32),)
            (inner, key_bounds), _ = jax.lax.scan(body, (inner0, kb0), xs)
            if phases:
                state, count, outs, count_out, pc = inner
                return state, count, outs, count_out, key_bounds, pc
            state, count, outs, count_out = inner
            return state, count, outs, count_out, key_bounds

        if self.donate_carry:
            # latency mode: the [K, S] carry buffers are dead the moment
            # the dispatch is enqueued (the pipeline rebinds to the outputs
            # unconditionally), so hand them to XLA for in-place reuse —
            # the deferred handles hold OUTPUT buffers, never the carry
            return jax.jit(run, donate_argnums=(0, 1))
        return jax.jit(run)

    # ------------------------------------------------------------------
    def snapshot(self) -> dict:
        self._require_state()
        self._to_canonical()  # snapshots use the [K, S] layout across backends
        return {
            "state": {k: np.asarray(v) for k, v in self._state.items()},
            "count": np.asarray(self._count),
            "watermark": self.watermark,
            "fire_cursor": self.fire_cursor,
            "purged_to": self.purged_to,
            "min_used_slice": self.min_used_slice,
            "max_seen_slice": self.max_seen_slice,
            "num_late_dropped": self.num_late_records_dropped,
        }

    def restore(self, snap: dict) -> None:
        self._require_state()
        import jax.numpy as jnp

        self._state = {k: jnp.asarray(v) for k, v in snap["state"].items()}
        self._count = jnp.asarray(snap["count"])
        self._kernel_layout = False
        self.K = int(self._count.shape[0])  # capacity may have grown pre-snapshot
        self._pallas = None
        self.watermark = snap["watermark"]
        self.fire_cursor = snap["fire_cursor"]
        self.purged_to = snap["purged_to"]
        self.min_used_slice = snap["min_used_slice"]
        self.max_seen_slice = snap["max_seen_slice"]
        self.num_late_records_dropped = snap["num_late_dropped"]


import functools


@functools.lru_cache(maxsize=None)
def _row_slicer(n: int):
    import jax

    return jax.jit(lambda b: b[:n])


def _slice_rows(buf, n: int):
    return _row_slicer(n)(buf)


#: the per-step ingest/fire/purge body now lives in ops/superscan.py (a
#: pure device-kernel builder, importable from `parallel/` without a
#: runtime edge — ARCH001); re-exported here for existing callers
from flink_tpu.ops.superscan import make_superscan_step  # noqa: E402,F401


@functools.lru_cache(maxsize=None)
def _build_superscan(agg, K, S, NSB, F, R, SPW, chunk, exact, T, B,
                     phases: bool = False, fire_spws=None,
                     donate: bool = False):
    """Compiled T-step superscan; module-level cache so every pipeline with
    identical geometry (incl. warmup instances) shares one executable.
    With `phases` the program additionally returns the int32[3] per-phase
    step counters threaded through the scan carry (device-plane
    observability); the flag is part of the cache key, so gated jobs and
    ungated jobs never share an executable shape. `fire_spws` (shared
    partials) is likewise part of the key: per-slot slice-run lengths.
    `donate` (latency mode) donates the [K, S] state/count carry inputs to
    XLA for in-place reuse — callers rebind to the outputs unconditionally,
    so the old buffers are dead at enqueue; keyed so throughput jobs never
    share a donated executable."""
    import jax
    import jax.numpy as jnp

    step = make_superscan_step(agg, K, S, NSB, F, R, SPW, chunk, exact,
                               phase_counters=phases, fire_spws=fire_spws)
    jit = (functools.partial(jax.jit, donate_argnums=(0, 1)) if donate
           else jax.jit)

    if phases:
        @jit
        def run(state, count, outs, count_out, idx, vals, smin_pos,
                fire_pos, fire_valid, fire_row, purge_mask):
            carry0 = (state, count, outs, count_out,
                      jnp.zeros((3,), jnp.int32))
            (state, count, outs, count_out, pc), _ = jax.lax.scan(
                step, carry0,
                (idx, vals, smin_pos, fire_pos, fire_valid, fire_row,
                 purge_mask),
            )
            return state, count, outs, count_out, pc

        return run

    @jit
    def run(state, count, outs, count_out, idx, vals, smin_pos, fire_pos, fire_valid, fire_row, purge_mask):
        (state, count, outs, count_out), _ = jax.lax.scan(
            step,
            (state, count, outs, count_out),
            (idx, vals, smin_pos, fire_pos, fire_valid, fire_row, purge_mask),
        )
        return state, count, outs, count_out

    return run


# ---------------------------------------------------------------------------
# shared-partial multi-window pipeline (Factor Windows, PAPERS.md
# arXiv 2008.12379): correlated window shapes over ONE gcd-granule ring
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class _WindowSpec:
    """One member window of a shared-partial group, in shared-granule
    units: window j of this spec covers slices [j*sl, j*sl + spw)."""

    spw: int
    sl: int
    size_ms: int
    slide_ms: int


@dataclasses.dataclass(frozen=True)
class _SharedGridView:
    """Synthetic sliceable-assigner view the base pipeline initializes
    from: granule = the group gcd, spw = the LONGEST member (ring sizing,
    ring-floor math), sl = the SHORTEST slide (conservative frontier)."""

    slice_ms: int
    slices_per_window: int
    slide_slices: int
    offset_ms: int
    is_event_time: bool = True


class _SharedPlanCursor(_PlanCursor):
    """The multi-spec fire planner: one shared ingest/purge frontier,
    per-window-spec fire cursors, fire slots partitioned per spec."""

    def __init__(self, pipe: "SharedWindowPipeline"):
        super().__init__(pipe)
        self.fire_cursors = list(pipe.fire_cursors)

    def _note_fire_candidate(self, smin: int) -> None:
        p = self.p
        for i in range(len(p.specs)):
            cand = p._spec_j_oldest(i, smin)
            if self.wm > MIN_WATERMARK:
                cand = max(cand, p._spec_j_fired_upto(i, self.wm) + 1)
            cur = self.fire_cursors[i]
            self.fire_cursors[i] = cand if cur is None else min(cur, cand)

    def _plan_fires(self, t: int, new_wm: int, fire_pos, fire_valid,
                    fire_row, fires: list) -> None:
        p = self.p
        if self.max_seen is None:
            return
        Fp = p.F_per_spec
        for i, spec in enumerate(p.specs):
            cur = self.fire_cursors[i]
            if cur is None:
                continue
            hi = min(p._spec_j_fired_upto(i, new_wm),
                     self.max_seen // spec.sl)
            slot = i * Fp
            n = 0
            for j in range(cur, hi + 1):
                if n >= Fp:
                    raise ValueError(
                        f"window spec {i}: {hi + 1 - cur} windows fire in "
                        f"one step > fires_per_step={Fp}")
                if len(fires) >= p.R:
                    raise ValueError(
                        f"more than out_rows={p.R} fires per dispatch")
                row = len(fires)
                fires.append(_PlannedFire(row, j, t, spec=i))
                fire_pos[t, slot + n] = (j * spec.sl) % p.S
                fire_valid[t, slot + n] = 1
                fire_row[t, slot + n] = row
                n += 1
            if p._spec_j_fired_upto(i, new_wm) >= cur:
                self.fire_cursors[i] = p._spec_j_fired_upto(i, new_wm) + 1

    def commit(self) -> None:
        super().commit()
        self.p.fire_cursors = list(self.fire_cursors)


class SharedWindowPipeline(FusedWindowPipeline):
    """N correlated window shapes over ONE shared slice ring.

    The Factor-Windows execution form: a job computing several windows
    over the same keyed stream (1m/5m/1h dashboards) pays for ONE scan —
    ingest lands gcd-granule partials once, and every member window
    derives its result from those shared partials at fire time (its own
    slice-run length per fire slot, `fire_spws` in the superscan step).
    Against N independent fused runs this saves (N-1) full ingest scans —
    the dominant cost — which is the sharing factor the planner
    (graph/window_sharing.py) estimates.

    Differences from the base pipeline, all planner-side:
    - per-spec fire cursors (`fire_cursors`); the fire slot space is
      partitioned F_per_spec slots per member;
    - the purge frontier is the MIN over members' live frontiers (a slice
      purges only when the LONGEST window is done with it);
    - `_window_of_fire` returns `(spec_index, TimeWindow)` — ONLY the
      shared-partial operator consumes these deferred handles, and it
      routes each emission to its member window's output.

    All member assigners must be sliceable, event-time, and share one
    offset; the shared granule is the gcd of their slice granules, and
    each member's decomposition onto it must be exact
    (WindowAssigner.slices_on — the degenerate-shape contract)."""

    def __init__(self, assigners, aggregate, *, key_capacity: int,
                 num_slices: Optional[int] = None, nsb: int = 4,
                 fires_per_step: int = 4, out_rows: int = 256,
                 chunk: int = 4096, exact_sums: bool = True,
                 backend: str = "auto", pallas_interpret: bool = False,
                 plan_only: bool = False, prologue=None):
        import math

        if len(assigners) < 2:
            raise ValueError("shared partials need >= 2 window shapes")
        offs = {a.offset_ms for a in assigners}
        if len(offs) != 1:
            raise ValueError(
                f"shared partials need one common window offset, got {offs}")
        for a in assigners:
            if a.slice_ms is None or not a.is_event_time:
                raise ValueError(f"{a!r} is not a sliceable event-time "
                                 "assigner")
        g = 0
        for a in assigners:
            g = math.gcd(g, a.slice_ms)
        specs = []
        for a in assigners:
            spw, sl = a.slices_on(g)   # exact or ValueError
            specs.append(_WindowSpec(spw, sl, spw * g, sl * g))
        n = len(specs)
        view = _SharedGridView(
            slice_ms=g,
            slices_per_window=max(s.spw for s in specs),
            slide_slices=min(s.sl for s in specs),
            offset_ms=assigners[0].offset_ms,
        )
        super().__init__(
            view, aggregate, key_capacity=key_capacity,
            num_slices=num_slices, nsb=nsb,
            fires_per_step=n * fires_per_step, out_rows=out_rows,
            chunk=chunk, exact_sums=exact_sums, backend=backend,
            pallas_interpret=pallas_interpret, plan_only=plan_only,
            prologue=prologue,
        )
        self.specs = tuple(specs)
        self.F_per_spec = fires_per_step
        self._fire_spws = tuple(
            s.spw for s in specs for _ in range(fires_per_step))
        self.fire_cursors = [None] * n

    # -- per-spec geometry ---------------------------------------------
    def _spec_j_fired_upto(self, i: int, wm: int) -> int:
        s = self.specs[i]
        return (wm + 1 - self.offset - s.size_ms) // s.slide_ms

    def _spec_j_oldest(self, i: int, smin: int) -> int:
        s = self.specs[i]
        return _ceil_div(smin - s.spw + 1, s.sl)

    def _spec_fire_wm(self, i: int, j: int) -> int:
        s = self.specs[i]
        return self.offset + j * s.slide_ms + s.size_ms - 1

    def _spec_window_of(self, i: int, j: int) -> TimeWindow:
        s = self.specs[i]
        start = self.offset + j * s.slide_ms
        return TimeWindow(start, start + s.size_ms)

    # -- shared frontier overrides -------------------------------------
    def _min_live_slice(self, wm: int) -> int:
        return min(
            (self._spec_j_fired_upto(i, wm) + 1) * s.sl
            for i, s in enumerate(self.specs)
        )

    def _wm_keeping_slice_live(self, s: int) -> int:
        # largest wm with min-over-specs of min_live <= s: the LONGEST
        # holder wins (any one spec keeping the slice live keeps it live)
        return max(self._spec_fire_wm(i, s // sp.sl) - 1
                   for i, sp in enumerate(self.specs))

    def _window_of_fire(self, pf: "_PlannedFire"):
        return (pf.spec, self._spec_window_of(pf.spec, pf.j))

    def _cursor(self) -> _SharedPlanCursor:
        return _SharedPlanCursor(self)

    # -- snapshot surface ----------------------------------------------
    def snapshot(self) -> dict:
        snap = super().snapshot()
        snap["fire_cursors"] = list(self.fire_cursors)
        return snap

    def restore(self, snap: dict) -> None:
        super().restore(snap)
        self.fire_cursors = list(snap["fire_cursors"])


# ---------------------------------------------------------------------------
# global-window pipeline: keyed-partial -> cross-segment fold, [S] state
# ---------------------------------------------------------------------------

class FusedGlobalWindowPipeline:
    """Per-window GLOBAL aggregation (the Nexmark Q7 shape) on the
    superscan schedule: the host planner (a plan-only FusedWindowPipeline
    — one source of truth for fire/purge math) plans dispatches exactly
    like the keyed path, but device state collapses from [K, S] to a [S]
    slice ring of partials and every fire folds its slice run into ONE
    scalar. The dense per-batch keyed reduction (and its [R, K] readback
    + host-side fold over keys) is replaced by a keyed-partial →
    cross-segment fold — the single-chip analogue of the mesh's
    psum/pmax merge; readbacks shrink to R scalars. Unbounded min/max
    have a device form here (the fold is elementwise — no scatter unit,
    no bounded-domain declaration).

    On TPU the whole T-step dispatch runs as one pallas kernel
    (ops/pallas_superscan.build_global_superscan) with the ring resident
    in a single VMEM row; elsewhere (and for geometries the kernel
    refuses) the XLA scan form (ops/superscan.make_global_scan_step)
    keeps identical semantics. Staged inputs are interchangeable with the
    keyed pipeline's (`idx = kid * NSB + srel` streams fold by
    `idx % NSB`), so callers that stage on device — the bench's threefry
    generator — switch paths without re-staging."""

    def __init__(self, assigner, aggregate, *, num_slices: Optional[int] = None,
                 nsb: int = 4, fires_per_step: int = 2, out_rows: int = 64,
                 chunk: int = 8192, backend: str = "auto",
                 pallas_interpret: bool = False):
        self._planner = FusedWindowPipeline(
            assigner, aggregate, key_capacity=128, num_slices=num_slices,
            nsb=nsb, fires_per_step=fires_per_step, out_rows=out_rows,
            chunk=chunk, backend="xla", plan_only=True,
        )
        self.agg = self._planner.agg
        self.S = self._planner.S
        self.NSB = nsb
        self.F = fires_per_step
        self.R = out_rows
        self.chunk = chunk
        self.backend = backend
        self.pallas_interpret = pallas_interpret
        self._value_fields = [f for f in self.agg.fields if f.source == VALUE]
        self._needs_vals = bool(self._value_fields)
        self._pallas: Optional[bool] = None
        self.compile_tracker = None
        self.phase_counters = False
        import jax.numpy as jnp

        from flink_tpu.ops.aggregators import scan_identity

        self._count = jnp.zeros((self.S,), jnp.int32)
        self._state = {
            f.name: jnp.full((self.S,),
                             scan_identity(jnp.dtype(f.dtype), f.scatter),
                             jnp.dtype(f.dtype))
            for f in self._value_fields
        }

    # planner-geometry delegation (the sharded pipeline's pattern)
    @property
    def planner(self):
        return self._planner

    def __getattr__(self, name):
        if name == "_planner":
            raise AttributeError(name)
        return getattr(self._planner, name)

    def attach_device_stats(self, tracker, phase_counters: bool = True) -> None:
        """Wire a CompileTracker around the global-superscan dispatch and
        (non-pallas, like the keyed pipeline) thread the ingest/fire/purge
        phase counters through the scan carry. Must run before the first
        dispatch — the phase flag is part of the executable cache key."""
        self.compile_tracker = tracker
        self.phase_counters = bool(phase_counters)

    def _use_pallas(self) -> bool:
        if self._pallas is None:
            from flink_tpu.ops import pallas_superscan as ps

            ok = ps.supports_global(self.agg, self.S, self.R, self.NSB,
                                    self.chunk)
            if self.backend == "xla":
                self._pallas = False
            elif self.backend == "pallas":
                if not ok:
                    raise ValueError(
                        "pallas global superscan does not support this "
                        "aggregate/geometry (need add/min/max fields, "
                        "S<=32, R<=128, chunk-aligned batches)")
                self._pallas = True
            else:
                import jax

                self._pallas = ok and (jax.default_backend() == "tpu"
                                       or self.pallas_interpret)
        return self._pallas

    def plan_superbatch(self, slice_bounds, watermarks):
        return self._planner.plan_superbatch(slice_bounds, watermarks)

    def stage_superbatch(self, batches, watermarks):
        return self._planner.stage_superbatch(batches, watermarks)

    def process_superbatch(self, batches, watermarks, *, staged=None,
                           defer: bool = False):
        import jax
        import jax.numpy as jnp

        from flink_tpu.ops.aggregators import scan_identity

        if staged is None:
            staged = self._planner.stage_superbatch(batches, watermarks)
        idx_d, vals_d, plan = staged
        (smin_pos, fire_pos, fire_valid, fire_row, purge_mask, fires) = plan
        T = int(smin_pos.shape[0])
        B = idx_d.shape[1] if idx_d.ndim == 2 else idx_d.shape[0] // T
        names = [f.name for f in self._value_fields]

        use_pallas = self._use_pallas()
        CH = self.chunk
        if use_pallas:
            # staged inputs are chunk-padded (stage_superbatch), so CH stays
            # self.chunk; externally staged widths halve down to the largest
            # divisor. A width the kernel cannot chunk (below MIN_CHUNK)
            # falls back to the XLA scan for THIS dispatch — identical
            # semantics — unless the caller forced backend="pallas".
            from flink_tpu.ops import pallas_superscan as ps

            while CH > 1 and B % CH != 0:
                CH //= 2
            if B % CH != 0 or CH % ps.MIN_CHUNK != 0:
                if self.backend == "pallas":
                    raise ValueError(
                        f"pallas global superscan cannot chunk batch width "
                        f"{B} (chunks must divide B and be multiples of "
                        f"{ps.MIN_CHUNK}); stage through the pipeline or "
                        "use backend='auto' to allow the XLA scan fallback")
                use_pallas = False

        if use_pallas:
            from flink_tpu.ops import pallas_superscan as ps

            LANE = ps.LANE
            idx_flat = idx_d if idx_d.ndim == 1 else idx_d.reshape(-1)
            vals_flat = None
            if self._needs_vals:
                vals_flat = vals_d if vals_d.ndim == 1 else vals_d.reshape(-1)
            run = ps.build_global_superscan(
                self.agg, self.S, self.NSB, self.F, self._planner.spw,
                self.R, T, B, CH, self.pallas_interpret,
            )
            count_row = jnp.zeros((1, LANE), jnp.int32).at[0, :self.S].set(
                self._count)
            state_rows = tuple(
                jnp.full((1, LANE),
                         scan_identity(self._state[n].dtype,
                                       self.agg.field(n).scatter),
                         self._state[n].dtype).at[0, :self.S].set(
                    self._state[n])
                for n in names
            )
            out = run(smin_pos, fire_pos, fire_valid, fire_row, purge_mask,
                      count_row, state_rows, idx_flat, vals_flat) \
                if self.compile_tracker is None else \
                self.compile_tracker.call(
                    "pallas_global_superscan", run,
                    (smin_pos, fire_pos, fire_valid, fire_row, purge_mask,
                     count_row, state_rows, idx_flat, vals_flat),
                    {"T": T, "B": B, "S": self.S, "scope": "global"})
            count_state, field_states, count_out_row, field_out_rows = out
            self._count = count_state[0, :self.S]
            self._state = {
                n: s[0, :self.S] for n, s in zip(names, field_states)
            }
            count_out = count_out_row[0, :self.R]
            outs = {n: o[0, :self.R]
                    for n, o in zip(names, field_out_rows)}
        else:
            from flink_tpu.ops.superscan import build_global_superscan

            if idx_d.ndim == 1:
                idx_d = idx_d.reshape(T, B)
            if self._needs_vals and vals_d.ndim == 1:
                vals_d = vals_d.reshape(T, B)
            run = build_global_superscan(
                self.agg, self.S, self.NSB, self.F, self.R,
                self._planner.spw, T, B, phases=self.phase_counters,
            )
            outs0 = {
                f.name: jnp.full(
                    (self.R,),
                    scan_identity(jnp.dtype(f.dtype), f.scatter),
                    jnp.dtype(f.dtype))
                for f in self._value_fields
            }
            count_out0 = jnp.zeros((self.R,), jnp.int32)
            args = (self._state, self._count, outs0, count_out0, idx_d,
                    vals_d, smin_pos, fire_pos, fire_valid, fire_row,
                    purge_mask)
            if self.compile_tracker is None:
                out = run(*args)
            else:
                out = self.compile_tracker.call(
                    "global_superscan", run, args,
                    {"T": T, "B": B, "S": self.S, "scope": "global"})
            if self.phase_counters:
                self._state, self._count, outs, count_out, pc = out
            else:
                self._state, self._count, outs, count_out = out

        deferred = DeferredEmissions(
            self._planner, fires, count_out, outs,
            phase_counts=(pc if self.phase_counters and not use_pallas
                          else None))
        return deferred if defer else deferred.resolve()

    def snapshot(self) -> dict:
        return {
            "count": np.asarray(self._count),
            "state": {k: np.asarray(v) for k, v in self._state.items()},
            "watermark": self._planner.watermark,
            "fire_cursor": self._planner.fire_cursor,
            "purged_to": self._planner.purged_to,
            "min_used_slice": self._planner.min_used_slice,
            "max_seen_slice": self._planner.max_seen_slice,
            "num_late_dropped": self._planner.num_late_records_dropped,
        }

    def restore(self, snap: dict) -> None:
        import jax.numpy as jnp

        self._count = jnp.asarray(snap["count"])
        self._state = {k: jnp.asarray(v) for k, v in snap["state"].items()}
        self._planner.watermark = snap["watermark"]
        self._planner.fire_cursor = snap["fire_cursor"]
        self._planner.purged_to = snap["purged_to"]
        self._planner.min_used_slice = snap["min_used_slice"]
        self._planner.max_seen_slice = snap["max_seen_slice"]
        self._planner.num_late_records_dropped = snap["num_late_dropped"]
