"""Continuous (non-windowed) group aggregation over a changelog stream.

Reference semantics: `GroupAggFunction`
(flink-table-runtime .../operators/aggregate/GroupAggFunction.java:33) — for
every input row, update the key's accumulator and emit the transition on the
result changelog:

  first live row for a key              ->  +I(new result)
  result changed                        ->  -U(old result), +U(new result)
  result unchanged                      ->  nothing (RecordEqualiser check)
  live-row count drops to zero          ->  -D(old result), state dropped
  retraction of a never-seen row        ->  error (corrupt changelog)

The batched emission mode mirrors the reference's mini-batch optimization
(`MiniBatchGroupAggFunction`, table.exec.mini-batch.*): one transition per
DISTINCT key per input batch instead of per record — the natural fit for the
stepped columnar executor (accumulators update vectorized across the batch,
emissions shrink from O(records) to O(distinct keys)). `mini_batch=False`
gives the exact per-record reference emission sequence and is the parity
oracle for the batched mode.

Aggregates: COUNT / SUM / AVG retract by sign — the accumulator is a linear
sum, so the whole batch applies as one signed segment-sum (np.add.at on
host; one jitted scatter-add dispatch on device). MIN / MAX need the
retractable multiset the reference keeps in `MinWithRetractAggFunction`'s
MapState (value -> multiplicity); here a per-key Counter with a lazily
recomputed extremum.

Device path (`device=True`, linear aggregates only): accumulators live in
HBM as [capacity] columns; each batch is ONE dispatch — scatter-add of the
signed deltas plus gathers of the affected keys' old/new results — with
batch and distinct-key axes padded to pow2 buckets so XLA compiles a handful
of programs, not one per batch shape.
"""

from __future__ import annotations

import numpy as np

from collections import Counter
from typing import Any, Dict, List, Optional, Tuple

from flink_tpu.table.changelog import (
    DELETE,
    INSERT,
    ROW_KIND_FIELD,
    UPDATE_AFTER,
    UPDATE_BEFORE,
    is_additive,
    is_retractive,
    row_kind,
)
from flink_tpu.runtime.executor import StepRunner
from flink_tpu.utils.arrays import obj_array

LINEAR_FUNCS = frozenset(("COUNT", "SUM", "AVG"))
MINMAX_FUNCS = frozenset(("MIN", "MAX"))


def _pow2(n: int) -> int:
    return 1 << max(int(n) - 1, 0).bit_length() if n > 1 else 1


class _DeviceLinearState:
    """Linear accumulators as device columns: cnt[capacity] (live rows per
    key), sums[n_linear, capacity] (float32 signed value-sums) and
    nn[n_linear, capacity] (int32 signed non-null counts — COUNT stays
    EXACT; only SUM/AVG carry the documented float32 rounding). One jitted
    program per (batch-bucket, uniq-bucket) pair does scatter-add + old/new
    gathers in a single dispatch."""

    def __init__(self, n_linear: int, capacity: int = 1024):
        import jax.numpy as jnp

        self._jnp = jnp
        self.capacity = capacity
        # last slot is a scratch slot for padding lanes (sign 0 writes there)
        self.cnt = jnp.zeros((capacity,), dtype=jnp.int32)
        self.sums = jnp.zeros((n_linear, capacity), dtype=jnp.float32)
        self.nn = jnp.zeros((n_linear, capacity), dtype=jnp.int32)
        self._fns: Dict[Tuple[int, int], Any] = {}

    def grow(self, capacity: int) -> None:
        jnp = self._jnp
        n_linear = self.sums.shape[0]
        cnt = jnp.zeros((capacity,), dtype=jnp.int32)
        sums = jnp.zeros((n_linear, capacity), dtype=jnp.float32)
        nn = jnp.zeros((n_linear, capacity), dtype=jnp.int32)
        self.cnt = cnt.at[: self.capacity].set(self.cnt)
        self.sums = sums.at[:, : self.capacity].set(self.sums)
        self.nn = nn.at[:, : self.capacity].set(self.nn)
        self.capacity = capacity
        self._fns.clear()

    def _fn(self, b: int, u: int):
        fn = self._fns.get((b, u))
        if fn is None:
            import jax

            def step(cnt, sums, nn, slots, signs, vals, nnvals, uniq):
                old_cnt = cnt[uniq]
                old_sums = sums[:, uniq]
                old_nn = nn[:, uniq]
                new_cnt = cnt.at[slots].add(signs)
                new_sums = sums.at[:, slots].add(signs.astype(vals.dtype) * vals)
                new_nn = nn.at[:, slots].add(signs * nnvals)
                return (new_cnt, new_sums, new_nn,
                        old_cnt, old_sums, old_nn,
                        new_cnt[uniq], new_sums[:, uniq], new_nn[:, uniq])

            fn = jax.jit(step, donate_argnums=(0, 1, 2))
            self._fns[(b, u)] = fn
        return fn

    def apply(self, slots: np.ndarray, signs: np.ndarray, vals: np.ndarray,
              nnvals: np.ndarray, uniq: np.ndarray):
        """Returns (old_cnt, old_sums, old_nn, new_cnt, new_sums, new_nn)
        for `uniq` slots (numpy, already sliced to the real uniq length)."""
        b, u = _pow2(len(slots)), _pow2(len(uniq))
        scratch = self.capacity - 1
        pslots = np.full(b, scratch, dtype=np.int32)
        pslots[: len(slots)] = slots
        psigns = np.zeros(b, dtype=np.int32)
        psigns[: len(slots)] = signs
        pvals = np.zeros((vals.shape[0], b), dtype=np.float32)
        pvals[:, : len(slots)] = vals
        pnn = np.zeros((nnvals.shape[0], b), dtype=np.int32)
        pnn[:, : len(slots)] = nnvals
        puniq = np.full(u, scratch, dtype=np.int32)
        puniq[: len(uniq)] = uniq
        fn = self._fn(b, u)
        (self.cnt, self.sums, self.nn, oc, os_, onn, nc, ns, nnn) = fn(
            self.cnt, self.sums, self.nn, pslots, psigns, pvals, pnn, puniq)
        n = len(uniq)
        return (np.asarray(oc)[:n], np.asarray(os_)[:, :n],
                np.asarray(onn)[:, :n], np.asarray(nc)[:n],
                np.asarray(ns)[:, :n], np.asarray(nnn)[:, :n])

    def to_host(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        return np.asarray(self.cnt), np.asarray(self.sums), np.asarray(self.nn)

    def from_host(self, cnt: np.ndarray, sums: np.ndarray,
                  nn: np.ndarray) -> None:
        jnp = self._jnp
        self.cnt = jnp.asarray(cnt)
        self.sums = jnp.asarray(sums)
        self.nn = jnp.asarray(nn)
        self.capacity = int(cnt.shape[0])
        self._fns.clear()


class GroupAggRunner(StepRunner):
    """StepRunner (terminal kind 'group_agg') maintaining per-key
    accumulators and emitting the result changelog. NULL handling follows
    SQL: COUNT(col)/SUM/AVG/MIN/MAX ignore NULL inputs (COUNT(*) counts
    every row); SUM/AVG/MIN/MAX over only-NULL inputs yield NULL."""

    def __init__(self, step, config):
        t = step.terminal
        self.key_selector = t.config["key_selector"]
        self.specs: List[Tuple[str, Optional[str]]] = list(t.config["specs"])
        self.key_fields: List[str] = list(t.config["key_fields"])
        self.out_names: List[str] = list(t.config["out_names"])
        from flink_tpu.config import ExecutionOptions

        mb = t.config.get("mini_batch")
        self.mini_batch: bool = (
            config.get(ExecutionOptions.MINI_BATCH_GROUP_AGG)
            if mb is None else mb)
        self.update_before: bool = t.config.get("generate_update_before", True)
        self.uid = t.uid
        for f, _c in self.specs:
            if f not in LINEAR_FUNCS and f not in MINMAX_FUNCS:
                raise ValueError(f"unsupported aggregate {f!r}")
        self._linear_idx = [i for i, (f, _c) in enumerate(self.specs)
                            if f in LINEAR_FUNCS]
        self._minmax_idx = [i for i, (f, _c) in enumerate(self.specs)
                            if f in MINMAX_FUNCS]
        dev = t.config.get("device")
        self.device: bool = (
            config.get(ExecutionOptions.DEVICE_GROUP_AGG)
            if dev is None else bool(dev))
        if self.device and self._minmax_idx:
            raise ValueError(
                "device group aggregation supports COUNT/SUM/AVG; MIN/MAX "
                "need the retractable multiset (host path)")
        # key -> slot; slots index the accumulator columns
        self._slots: Dict[Any, int] = {}
        self._free: List[int] = []
        self._cap = 1024
        # two linear columns per COUNT/SUM/AVG spec: the signed value-sum
        # (float) and the signed NON-NULL count (integer — exact on both
        # paths; SQL aggregates ignore NULL inputs, and AVG divides by the
        # non-null count, not the live-row count)
        n_lin = len(self._linear_idx)
        if self.device:
            self._dev = _DeviceLinearState(n_lin, self._cap)
            self._cnt = self._sums = self._nn = None
        else:
            self._dev = None
            self._cnt = np.zeros(self._cap, dtype=np.int64)
            self._sums = np.zeros((n_lin, self._cap), dtype=np.float64)
            self._nn = np.zeros((n_lin, self._cap), dtype=np.int64)
        # per-key multisets for MIN/MAX: spec idx -> slot -> Counter
        self._msets: Dict[int, Dict[int, Counter]] = {
            i: {} for i in self._minmax_idx}

    # -- slots --------------------------------------------------------------
    def _slot_of(self, key) -> int:
        slot = self._slots.get(key)
        if slot is not None:
            return slot
        if self._free:
            slot = self._free.pop()
        else:
            slot = len(self._slots)
            # keep one scratch slot spare for device padding lanes
            if slot >= self._cap - 1:
                self._cap *= 2
                if self._dev is not None:
                    self._dev.grow(self._cap)
                else:
                    self._cnt = np.resize(self._cnt, self._cap)
                    self._cnt[self._cap // 2:] = 0
                    sums = np.zeros((self._sums.shape[0], self._cap))
                    sums[:, : self._cap // 2] = self._sums
                    self._sums = sums
                    nn = np.zeros((self._nn.shape[0], self._cap),
                                  dtype=np.int64)
                    nn[:, : self._cap // 2] = self._nn
                    self._nn = nn
        self._slots[key] = slot
        return slot

    # -- aggregation --------------------------------------------------------
    def _result_of(self, slot: int, cnt: int, sums: np.ndarray,
                   nns: np.ndarray) -> Optional[tuple]:
        """Aggregate outputs for one key given its live-row count, the
        linear sums column and the non-null count column (index j for the
        j-th linear spec)."""
        if cnt <= 0:
            return None
        out: List[Any] = []
        li = 0
        for i, (f, _c) in enumerate(self.specs):
            if f in LINEAR_FUNCS:
                s = float(sums[li])
                nn = int(nns[li])
                if f == "COUNT":
                    out.append(nn)
                elif f == "SUM":
                    out.append(s if nn > 0 else None)
                else:  # AVG
                    out.append(s / nn if nn > 0 else None)
                li += 1
            elif f == "MIN":
                ms = self._msets[i].get(slot)
                out.append(min(ms) if ms else None)
            else:  # MAX
                ms = self._msets[i].get(slot)
                out.append(max(ms) if ms else None)
        return tuple(out)

    def on_batch(self, values: np.ndarray, timestamps: np.ndarray) -> None:
        n = len(timestamps)
        if n == 0:
            return
        counter = getattr(self, "records_in_counter", None)
        if counter is not None:
            counter.inc(n)
        if self.mini_batch:
            self._apply(values, np.asarray(timestamps, dtype=np.int64))
        else:
            ts = np.asarray(timestamps, dtype=np.int64)
            for i in range(n):
                self._apply(values[i:i + 1], ts[i:i + 1])

    def _apply(self, rows, tss) -> None:
        n = len(rows)
        L = len(self._linear_idx)
        slots = np.empty(n, dtype=np.int32)
        signs = np.empty(n, dtype=np.int32)
        vals = np.zeros((L, n), dtype=np.float64)
        nnvals = np.zeros((L, n), dtype=np.int64)
        keys_of: Dict[int, Any] = {}
        for i, row in enumerate(rows):
            kind = row_kind(row)
            if is_additive(kind):
                signs[i] = 1
            elif is_retractive(kind):
                signs[i] = -1
            else:
                raise ValueError(f"unknown row kind {kind!r}")
            key = self.key_selector(row)
            slot = self._slot_of(key)
            slots[i] = slot
            keys_of[slot] = key
            for j, si in enumerate(self._linear_idx):
                f, col = self.specs[si]
                if col is None:                       # COUNT(*)
                    v, nn = 1.0, 1
                else:
                    raw = row.get(col)
                    if raw is None:                   # SQL: NULL is ignored
                        v, nn = 0.0, 0
                    else:
                        v = 1.0 if f == "COUNT" else float(raw)
                        nn = 1
                vals[j, i] = v
                nnvals[j, i] = nn
        _, first_idx = np.unique(slots, return_index=True)
        uniq = slots[np.sort(first_idx)]   # distinct, first-appearance order

        if self._dev is not None:
            (old_cnt, old_sums, old_nn, new_cnt, new_sums,
             new_nn) = self._dev.apply(
                slots, signs, vals.astype(np.float32),
                nnvals.astype(np.int32), uniq)
        else:
            old_cnt = self._cnt[uniq].copy()
            old_sums = self._sums[:, uniq].copy()
            old_nn = self._nn[:, uniq].copy()
            np.add.at(self._cnt, slots, signs)
            np.add.at(self._sums.T, slots,
                      (signs.astype(np.float64) * vals).T)
            np.add.at(self._nn.T, slots, (signs * nnvals).T)
            new_cnt = self._cnt[uniq]
            new_sums = self._sums[:, uniq]
            new_nn = self._nn[:, uniq]

        # old results BEFORE multiset mutation
        old_res = [self._result_of(int(s), int(c), old_sums[:, k],
                                   old_nn[:, k])
                   for k, (s, c) in enumerate(zip(uniq, old_cnt))]
        for i in range(n):
            slot = int(slots[i])
            for si in self._minmax_idx:
                _f, col = self.specs[si]
                ms = self._msets[si].setdefault(slot, Counter())
                v = rows[i].get(col)
                if v is None:
                    continue                          # SQL: NULL is ignored
                if signs[i] > 0:
                    ms[v] += 1
                else:
                    if ms[v] <= 0:
                        raise ValueError(
                            f"retraction of unseen value {v!r} for key "
                            f"{keys_of[slot]!r}")
                    ms[v] -= 1
                    if ms[v] == 0:
                        del ms[v]

        out_rows: List[dict] = []
        out_ts: List[int] = []
        ts = int(tss.max())
        for k, slot_np in enumerate(uniq):
            slot = int(slot_np)
            cnt_new = int(new_cnt[k])
            if cnt_new < 0:
                raise ValueError(
                    f"negative live-row count for key {keys_of[slot]!r}: the "
                    "input changelog retracted more rows than it inserted")
            new_res = self._result_of(slot, cnt_new, new_sums[:, k],
                                      new_nn[:, k])
            old = old_res[k]
            if old is None and new_res is None:
                self._drop_key(keys_of[slot], slot)
                continue
            if old is None:
                out_rows.append(self._row(keys_of[slot], new_res, INSERT))
                out_ts.append(ts)
            elif new_res is None:
                out_rows.append(self._row(keys_of[slot], old, DELETE))
                out_ts.append(ts)
                self._drop_key(keys_of[slot], slot)
            elif new_res != old:
                if self.update_before:
                    out_rows.append(
                        self._row(keys_of[slot], old, UPDATE_BEFORE))
                    out_ts.append(ts)
                out_rows.append(self._row(keys_of[slot], new_res, UPDATE_AFTER))
                out_ts.append(ts)
        if out_rows and self.downstream:
            self.downstream.on_batch(
                obj_array(out_rows), np.asarray(out_ts, dtype=np.int64))

    def _drop_key(self, key, slot: int) -> None:
        """Count hit zero: free the slot (state retention on delete —
        GroupAggFunction.java 'state.clear()' branch)."""
        del self._slots[key]
        self._free.append(slot)
        for si in self._minmax_idx:
            self._msets[si].pop(slot, None)
        # zero the columns so a recycled slot starts clean
        if self._dev is not None:
            self._dev.cnt = self._dev.cnt.at[slot].set(0)
            self._dev.sums = self._dev.sums.at[:, slot].set(0.0)
            self._dev.nn = self._dev.nn.at[:, slot].set(0)
        else:
            self._cnt[slot] = 0
            self._sums[:, slot] = 0.0
            self._nn[:, slot] = 0

    def _row(self, key, res: tuple, kind: str) -> dict:
        row: Dict[str, Any] = {}
        parts = key if isinstance(key, tuple) and len(self.key_fields) > 1 \
            else (key,)
        for name, part in zip(self.key_fields, parts):
            row[name] = part
        for name, v in zip(self.out_names, res):
            row[name] = v
        row[ROW_KIND_FIELD] = kind
        return row

    # -- checkpointing ------------------------------------------------------
    def snapshot(self) -> dict:
        cnt, sums, nn = (self._dev.to_host() if self._dev is not None
                         else (self._cnt, self._sums, self._nn))
        return {
            "slots": dict(self._slots),
            "free": list(self._free),
            "cap": self._cap,
            "cnt": np.asarray(cnt).copy(),
            "sums": np.asarray(sums).copy(),
            "nn": np.asarray(nn).copy(),
            "msets": {i: {s: dict(c) for s, c in d.items()}
                      for i, d in self._msets.items()},
        }

    def restore(self, snap: dict) -> None:
        self._slots = dict(snap["slots"])
        self._free = list(snap["free"])
        self._cap = snap["cap"]
        self._msets = {i: {s: Counter(c) for s, c in d.items()}
                       for i, d in snap["msets"].items()}
        if "nn" not in snap:
            # migrate the pre-r5 interleaved layout (savepoints are durable
            # and user-owned): sums was [2L, cap] with value-sums on even
            # rows and non-null counts on odd rows
            old = np.asarray(snap["sums"])
            snap = dict(snap)
            snap["sums"] = old[0::2]
            snap["nn"] = np.rint(old[1::2]).astype(np.int64)
        if self._dev is not None:
            self._dev.from_host(snap["cnt"].astype(np.int32),
                                snap["sums"].astype(np.float32),
                                snap["nn"].astype(np.int32))
        else:
            self._cnt = snap["cnt"].astype(np.int64).copy()
            self._sums = snap["sums"].astype(np.float64).copy()
            self._nn = snap["nn"].astype(np.int64).copy()
