"""High availability: leader election + job result store on a shared FS.

The analogue of the reference's HA services (M7): leader election via an
atomically-created lease file with heartbeat renewal (the file-system
counterpart of ZooKeeperLeaderElectionDriver / the K8s config-map lease,
flink-kubernetes/.../KubernetesLeaderElectionDriver.java:51), and a
JobResultStore (highavailability/FileSystemJobResultStore.java) recording
dirty→clean job results so a recovering dispatcher neither re-runs finished
jobs nor loses unacknowledged results.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional


class FileLeaderElection:
    """Lease file: {leader_id, address, stamp}. The holder renews the stamp;
    contenders take over when the stamp goes stale."""

    def __init__(
        self,
        lease_path: str,
        contender_id: Optional[str] = None,
        *,
        address: str = "",
        renew_interval: float = 0.5,
        lease_timeout: float = 3.0,
        on_grant: Optional[Callable[[], None]] = None,
        on_revoke: Optional[Callable[[], None]] = None,
    ):
        self.path = lease_path
        self.contender_id = contender_id or uuid.uuid4().hex
        self.address = address
        self.renew_interval = renew_interval
        self.lease_timeout = lease_timeout
        self.on_grant = on_grant
        self.on_revoke = on_revoke
        self.is_leader = False
        self._running = True
        self._thread = threading.Thread(target=self._loop, name="leader-election", daemon=True)
        self._thread.start()

    def _read(self) -> Optional[dict]:
        try:
            with open(self.path) as f:
                return json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            return None

    def _write(self) -> None:
        tmp = f"{self.path}.{self.contender_id}.tmp"
        with open(tmp, "w") as f:
            json.dump({"leader": self.contender_id, "address": self.address,
                       "stamp": time.time()}, f)
        os.replace(tmp, self.path)

    def _try_acquire(self) -> bool:
        try:
            fd = os.open(self.path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            cur = self._read()
            if cur is not None and time.time() - cur["stamp"] <= self.lease_timeout:
                return cur["leader"] == self.contender_id
            # stale lease: contend by rewriting, then confirm ownership
            self._write()
            time.sleep(0.05)
            cur = self._read()
            return cur is not None and cur["leader"] == self.contender_id
        else:
            os.close(fd)
            self._write()
            return True

    def _loop(self) -> None:
        while self._running:
            if self.is_leader:
                cur = self._read()
                if cur is None or cur["leader"] != self.contender_id:
                    self.is_leader = False
                    if self.on_revoke:
                        self.on_revoke()
                else:
                    self._write()  # renew
            else:
                if self._try_acquire():
                    self.is_leader = True
                    if self.on_grant:
                        self.on_grant()
            time.sleep(self.renew_interval)

    def current_leader(self) -> Optional[dict]:
        cur = self._read()
        if cur is None or time.time() - cur["stamp"] > self.lease_timeout:
            return None
        return cur

    def stop(self, release: bool = True) -> None:
        self._running = False
        self._thread.join(timeout=2)
        if release and self.is_leader:
            try:
                os.unlink(self.path)
            except FileNotFoundError:
                pass
            self.is_leader = False


class JobResultStore:
    """Dirty/clean job results as files: <dir>/<job_id>.dirty → .clean."""

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)

    def create_dirty(self, job_id: str, result: dict) -> None:
        path = os.path.join(self.dir, f"{job_id}.dirty")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(result, f)
        os.replace(tmp, path)

    def mark_clean(self, job_id: str) -> None:
        dirty = os.path.join(self.dir, f"{job_id}.dirty")
        clean = os.path.join(self.dir, f"{job_id}.clean")
        if os.path.exists(dirty):
            os.replace(dirty, clean)

    def has_result(self, job_id: str) -> bool:
        return any(
            os.path.exists(os.path.join(self.dir, f"{job_id}{ext}"))
            for ext in (".dirty", ".clean")
        )

    def dirty_results(self) -> Dict[str, dict]:
        out = {}
        for name in os.listdir(self.dir):
            if name.endswith(".dirty"):
                with open(os.path.join(self.dir, name)) as f:
                    out[name[: -len(".dirty")]] = json.load(f)
        return out
