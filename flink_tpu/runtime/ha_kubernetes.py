"""Kubernetes leader election over coordination.k8s.io/v1 Lease objects.

The analogue of the reference's KubernetesLeaderElectionDriver
(flink-kubernetes/.../highavailability/KubernetesLeaderElectionDriver.java:51,
which delegates to the fabric8 LeaderElector over a Lease): contenders race
to create/update a Lease whose spec carries holderIdentity, renewTime and
leaseDurationSeconds; the holder renews, contenders take over when
renewTime + duration expires, and optimistic concurrency (resourceVersion +
409 Conflict) arbitrates races.

The driver speaks the real API shapes through an injectable transport
(`api`), so it runs against an actual apiserver (in-cluster: pass an
`InClusterApi()` built from the service-account token) and is unit-tested
against an in-process fake implementing the same verbs + conflict
semantics.
"""

from __future__ import annotations

import logging
import json
import threading
import time
import uuid
from typing import Callable, Optional


class LeaseConflict(Exception):
    """HTTP 409: another contender updated the Lease first."""


class LeaseApi:
    """Transport SPI: the three Lease verbs the elector needs. Implementors
    raise KeyError for 404 and LeaseConflict for 409."""

    def get_lease(self, namespace: str, name: str) -> dict:
        raise NotImplementedError

    def create_lease(self, namespace: str, name: str, body: dict) -> dict:
        raise NotImplementedError

    def replace_lease(self, namespace: str, name: str, body: dict) -> dict:
        raise NotImplementedError


class HttpLeaseApi(LeaseApi):
    """Real apiserver transport (in-cluster service-account auth)."""

    def __init__(self, base_url: str, token: str, ca_file: Optional[str] = None):
        self.base = base_url.rstrip("/")
        self.token = token
        self.ca_file = ca_file

    def _req(self, method: str, path: str, body: Optional[dict] = None) -> dict:
        import ssl
        import urllib.error
        import urllib.request

        url = f"{self.base}{path}"
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Authorization", f"Bearer {self.token}")
        req.add_header("Content-Type", "application/json")
        ctx = ssl.create_default_context(cafile=self.ca_file) if self.ca_file else None
        try:
            with urllib.request.urlopen(req, timeout=10, context=ctx) as r:
                return json.loads(r.read())
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise KeyError(path) from e
            if e.code == 409:
                raise LeaseConflict(path) from e
            raise

    def _path(self, namespace: str, name: str = "") -> str:
        p = f"/apis/coordination.k8s.io/v1/namespaces/{namespace}/leases"
        return f"{p}/{name}" if name else p

    def get_lease(self, namespace, name):
        return self._req("GET", self._path(namespace, name))

    def create_lease(self, namespace, name, body):
        return self._req("POST", self._path(namespace), body)

    def replace_lease(self, namespace, name, body):
        return self._req("PUT", self._path(namespace, name), body)


def in_cluster_api() -> HttpLeaseApi:
    """Build the transport from the pod's service account (the in-cluster
    config convention: token + CA under /var/run/secrets)."""
    sa = "/var/run/secrets/kubernetes.io/serviceaccount"
    with open(f"{sa}/token") as f:
        token = f.read().strip()
    import os

    host = os.environ["KUBERNETES_SERVICE_HOST"]
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
    return HttpLeaseApi(f"https://{host}:{port}", token, f"{sa}/ca.crt")


def _now_micro() -> str:
    # RFC3339 with microseconds, the MicroTime wire format of renewTime
    t = time.time()
    base = time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(t))
    return f"{base}.{int((t % 1) * 1e6):06d}Z"


def _parse_micro(s: str) -> float:
    import calendar

    base, _, frac = s.rstrip("Z").partition(".")
    t = calendar.timegm(time.strptime(base, "%Y-%m-%dT%H:%M:%S"))
    return t + (float(f"0.{frac}") if frac else 0.0)


class KubernetesLeaderElection:
    """Lease-based elector with the same surface as FileLeaderElection
    (is_leader, on_grant/on_revoke, current_leader, stop)."""

    def __init__(
        self,
        api: LeaseApi,
        namespace: str,
        lease_name: str,
        contender_id: Optional[str] = None,
        *,
        address: str = "",
        renew_interval: float = 0.5,
        lease_duration: float = 3.0,
        on_grant: Optional[Callable[[], None]] = None,
        on_revoke: Optional[Callable[[], None]] = None,
        clock: Callable[[], float] = time.time,
    ):
        self.api = api
        self.namespace = namespace
        self.lease_name = lease_name
        self.contender_id = contender_id or uuid.uuid4().hex
        self.address = address
        self.renew_interval = renew_interval
        self.lease_duration = lease_duration
        self.on_grant = on_grant
        self.on_revoke = on_revoke
        self.clock = clock
        self.is_leader = False
        self._last_renew = 0.0
        self._running = True
        self._thread = threading.Thread(
            target=self._loop, name="k8s-leader-election", daemon=True)
        self._thread.start()

    # -- lease bodies -----------------------------------------------------
    def _body(self, resource_version: Optional[str]) -> dict:
        body = {
            "apiVersion": "coordination.k8s.io/v1",
            "kind": "Lease",
            "metadata": {
                "name": self.lease_name,
                "namespace": self.namespace,
                "annotations": {"flink-tpu/leader-address": self.address},
            },
            "spec": {
                "holderIdentity": self.contender_id,
                # wire format is whole seconds; never write 0 (= expired)
                "leaseDurationSeconds": max(1, int(-(-self.lease_duration // 1))),
                "renewTime": _now_micro(),
            },
        }
        if resource_version is not None:
            body["metadata"]["resourceVersion"] = resource_version
        return body

    def _expired(self, lease: dict) -> bool:
        spec = lease.get("spec", {})
        renew = spec.get("renewTime")
        if renew is None:
            return True
        dur = spec.get("leaseDurationSeconds", int(self.lease_duration))
        return self.clock() - _parse_micro(renew) > dur

    # -- elector loop -----------------------------------------------------
    def _try_acquire_or_renew(self) -> bool:
        try:
            lease = self.api.get_lease(self.namespace, self.lease_name)
        except KeyError:
            try:
                self.api.create_lease(
                    self.namespace, self.lease_name, self._body(None))
                return True
            except LeaseConflict:
                return False
        holder = lease.get("spec", {}).get("holderIdentity")
        rv = lease.get("metadata", {}).get("resourceVersion")
        if holder == self.contender_id or self._expired(lease):
            try:
                self.api.replace_lease(
                    self.namespace, self.lease_name, self._body(rv))
                return True
            except LeaseConflict:
                return False
        return False

    def _loop(self) -> None:
        while self._running:
            try:
                leading = self._try_acquire_or_renew()
                if leading:
                    self._last_renew = self.clock()
            except Exception:
                # apiserver unreachable: no contender can steal the lease
                # until it expires, so keep leading until our OWN lease
                # would have lapsed (fabric8/client-go elector semantics —
                # a network blip must not bounce leadership)
                leading = (self.is_leader and
                           self.clock() - self._last_renew <= self.lease_duration)
            if leading and not self.is_leader:
                self.is_leader = True
                if self.on_grant:
                    self.on_grant()
            elif not leading and self.is_leader:
                self.is_leader = False
                if self.on_revoke:
                    self.on_revoke()
            time.sleep(self.renew_interval)

    def current_leader(self) -> Optional[dict]:
        try:
            lease = self.api.get_lease(self.namespace, self.lease_name)
        except KeyError:
            return None
        if self._expired(lease):
            return None
        return {
            "leader_id": lease["spec"].get("holderIdentity"),
            "address": lease.get("metadata", {})
            .get("annotations", {})
            .get("flink-tpu/leader-address", ""),
        }

    def stop(self, release: bool = True) -> None:
        self._running = False
        # join longer than the transport timeout (10s): an in-flight renew
        # completing AFTER the release below would resurrect the lease
        self._thread.join(timeout=12)
        if self._thread.is_alive():
            release = False  # cannot release safely under a wedged renew
        if release and self.is_leader:
            try:
                lease = self.api.get_lease(self.namespace, self.lease_name)
                rv = lease.get("metadata", {}).get("resourceVersion")
                body = self._body(rv)
                body["spec"]["renewTime"] = "1970-01-01T00:00:00.000000Z"
                self.api.replace_lease(self.namespace, self.lease_name, body)
            except Exception as e:
                logging.getLogger(__name__).debug(
                    "lease release failed (next holder waits it out): %r", e)
            self.is_leader = False
