"""Bidirectional heartbeats with timeout-based failure detection.

Analogue of runtime/heartbeat/HeartbeatManagerImpl.java:49: a monitor tracks
last-seen times per target, a sender thread pings peers via a callable, and
targets silent for longer than the timeout are reported dead exactly once.

Shutdown is prompt and observable: the loop waits on an Event (not a bare
sleep), `stop()` joins the thread, and swallowed ping / on_dead callback
exceptions are COUNTED (`missed_pings` / `on_dead_errors`) instead of
silently passed. Note missedPings only moves for monitors registered WITH
a ping callable (active probing); the JM's TM liveness is receive-only,
so its gauge reads 0 by construction — partition drills there are
observed through restart/exception history, not this counter.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional


class HeartbeatManager:
    def __init__(
        self,
        *,
        interval: float = 1.0,
        timeout: float = 5.0,
        on_dead: Optional[Callable[[str], None]] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.interval = interval
        self.timeout = timeout
        self.on_dead = on_dead
        # Injectable clock: timeout decisions compare THIS clock only, so
        # tests can drive virtual time instead of racing real sleeps
        # against suite-wide GIL stalls (long jax compilations in sibling
        # tests stretched 50 ms sleeps past sub-second timeouts).
        self._clock = clock
        self._targets: Dict[str, dict] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        # swallowed-exception accounting (CONC005: no silent swallows);
        # missed_pings moves only for ping-configured (actively probed)
        # monitors — see the module docstring
        self.missed_pings = 0
        self.on_dead_errors = 0
        self._thread = threading.Thread(target=self._loop, name="heartbeat",
                                        daemon=True)
        self._thread.start()

    def monitor(self, target_id: str, ping: Optional[Callable[[], None]] = None) -> None:
        """Track a target; `ping` (optional) is invoked every interval — an
        exception or silence past the timeout marks the target dead."""
        with self._lock:
            self._targets[target_id] = {"last": self._clock(), "ping": ping, "dead": False}

    def unmonitor(self, target_id: str) -> None:
        with self._lock:
            self._targets.pop(target_id, None)

    def receive_heartbeat(self, target_id: str) -> None:
        with self._lock:
            t = self._targets.get(target_id)
            if t is not None:
                t["last"] = self._clock()
                t["dead"] = False

    def is_alive(self, target_id: str) -> bool:
        with self._lock:
            t = self._targets.get(target_id)
            return t is not None and not t["dead"]

    def check_now(self) -> None:
        """Run one ping/timeout sweep at the injected clock's current time.

        The loop thread calls this every interval; tests with a virtual
        clock call it directly so detection is deterministic instead of a
        race between real sleeps and suite-wide scheduler latency."""
        now = self._clock()
        with self._lock:
            items = list(self._targets.items())
        for tid, t in items:
            if t["dead"]:
                continue
            ping = t["ping"]
            if ping is not None:
                try:
                    ping()
                    self.receive_heartbeat(tid)
                    continue
                except Exception:
                    # treat like silence (timeout decides), but COUNT
                    # it: consecutive missed pings are the early
                    # warning a partition drill watches for
                    self.missed_pings += 1
            if now - t["last"] > self.timeout:
                with self._lock:
                    if t["dead"]:
                        continue
                    t["dead"] = True
                if self.on_dead is not None:
                    try:
                        self.on_dead(tid)
                    except Exception:
                        # a throwing death callback must not kill the
                        # detector for every OTHER target — counted,
                        # never silently dropped
                        self.on_dead_errors += 1

    def _loop(self) -> None:
        while True:
            self.check_now()
            # Event.wait, not time.sleep: stop() must not block shutdown
            # for up to a full interval (leaked beat loops kept dialing
            # dead JMs in test stacks)
            if self._stop.wait(self.interval):
                return

    def stop(self) -> None:
        self._stop.set()
        if threading.current_thread() is not self._thread:
            self._thread.join(timeout=5.0)
