"""Managed memory: budgeted reservations for device/host state (D13).

Analogue of runtime/memory/MemoryManager.java:60: consumers lease slices of
a fixed budget by weight (RocksDB block cache / sort-hash / Python in the
reference; HBM state columns, host spill memtables, exchange rings here).
The device budget defaults to the chip's reported HBM capacity minus a
headroom fraction; reservations are bookkeeping that turns an opaque OOM
into an early, attributable error.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional


class MemoryReservationError(MemoryError):
    pass


class MemoryManager:
    def __init__(self, budget_bytes: int):
        self.budget = int(budget_bytes)
        self._used: Dict[str, int] = {}
        self._lock = threading.Lock()

    @staticmethod
    def for_device(device=None, headroom: float = 0.1) -> "MemoryManager":
        """Budget from the accelerator's memory stats (HBM), with headroom
        for XLA temporaries; falls back to 8 GiB when stats are unavailable
        (CPU backend)."""
        total = None
        try:
            import jax

            dev = device or jax.devices()[0]
            stats = dev.memory_stats() or {}
            total = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
        except Exception:
            total = None
        if not total:
            total = 8 << 30
        return MemoryManager(int(total * (1.0 - headroom)))

    def reserve(self, owner: str, nbytes: int) -> None:
        with self._lock:
            used = sum(self._used.values())
            if used + nbytes > self.budget:
                raise MemoryReservationError(
                    f"{owner} wants {nbytes >> 20} MiB but only "
                    f"{(self.budget - used) >> 20} MiB of the "
                    f"{self.budget >> 20} MiB managed budget is free "
                    f"(holders: { {k: v >> 20 for k, v in self._used.items()} })"
                )
            self._used[owner] = self._used.get(owner, 0) + nbytes

    def release(self, owner: str, nbytes: Optional[int] = None) -> None:
        with self._lock:
            if owner not in self._used:
                return
            if nbytes is None or nbytes >= self._used[owner]:
                del self._used[owner]
            else:
                self._used[owner] -= nbytes

    def available(self) -> int:
        with self._lock:
            return self.budget - sum(self._used.values())

    def used_by(self, owner: str) -> int:
        with self._lock:
            return self._used.get(owner, 0)

    def split_by_weights(self, weights: Dict[str, float]) -> Dict[str, int]:
        """Divide the budget by consumer weights (the
        taskmanager.memory.managed.consumer-weights scheme)."""
        total = sum(weights.values())
        return {k: int(self.budget * w / total) for k, w in weights.items()}
