"""MiniCluster: in-process job management with failure recovery.

The control-plane-lite of the reference's Dispatcher/JobMaster/
MiniCluster stack (Dispatcher.submitJob :835 → JobMaster → scheduler;
test-cluster form runtime/minicluster/MiniCluster.java:160): jobs are
submitted asynchronously, each runs attempts on its own thread; on failure
the restart strategy (checkpoint/restart.py — ExponentialDelay/FixedDelay/
FailureRate parity) decides backoff or terminal failure, and each retry
restores from the latest completed checkpoint (region failover degenerates
to whole-pipeline restart in a linear topology). Savepoints are triggered
through the client and written through the same snapshot path
(SavepointType semantics: manually triggered, never auto-discarded).
"""

from __future__ import annotations

import enum
import threading
import time
import traceback
import uuid
from typing import Any, Dict, Optional

from flink_tpu.checkpoint.coordinator import CheckpointCoordinator
from flink_tpu.checkpoint.restart import restart_strategy_from_config
from flink_tpu.checkpoint.storage import (
    FsCheckpointStorage,
    MemoryCheckpointStorage,
)
from flink_tpu.config import CheckpointingOptions, Configuration, ParallelOptions
from flink_tpu.lint.contracts import absorbs_faults
from flink_tpu.graph.transformation import StepGraph
from flink_tpu.runtime.executor import (
    JobCancelledException,
    JobRuntime,
    MeshRescaleRequested,
)


def _effective_mesh_target(runtime: JobRuntime, target: int) -> Optional[int]:
    """Clamp a mesh-rescale target EXACTLY like runner construction will:
    shard_map availability, visible devices, and the largest divisor of
    the operators' construction-time key capacity (NOT the grown pipe.K —
    the rebuilt operator starts from the construction capacity again, so
    clamping against grown state would accept targets the rebuild cannot
    reach and tear the job down for a no-op). None = the job has no
    mesh-capable operator / no mesh backend; otherwise the device count
    the rebuild will actually produce."""
    from flink_tpu.utils.jax_compat import HAS_SHARD_MAP

    if not HAS_SHARD_MAP:
        return None
    caps = [
        op.mesh_capacity()
        for op in (getattr(r, "op", None) for r in runtime.runners)
        if op is not None and hasattr(op, "mesh_capacity")
    ]
    if not caps:
        return None
    import jax

    from flink_tpu.parallel.mesh import usable_mesh_size

    return usable_mesh_size(max(1, int(target)), len(jax.devices()),
                            min(caps))


def _is_device_loss(e: BaseException) -> bool:
    """Does this failure look like the device plane died under the job?
    Real chip/host loss surfaces as an XLA runtime error from the dispatch;
    chaos drills inject the same seam with a `device`-scoped marker. Walks
    the cause chain (cycle-safe) so wrapping never hides the origin."""
    seen = set()
    cur: Optional[BaseException] = e
    while cur is not None and id(cur) not in seen:
        seen.add(id(cur))
        if "XlaRuntimeError" in type(cur).__name__:
            return True
        if "[chaos-injected:device" in str(cur):
            return True
        cur = cur.__cause__ or cur.__context__
    return False


class JobStatus(enum.Enum):
    CREATED = "CREATED"
    RUNNING = "RUNNING"
    RESTARTING = "RESTARTING"
    FINISHED = "FINISHED"
    FAILED = "FAILED"
    CANCELED = "CANCELED"


class JobClient:
    """Client handle (JobClient/RestClusterClient surface: status, cancel,
    savepoint)."""

    def __init__(self, job_id: str, job_name: str):
        self.job_id = job_id
        self.job_name = job_name
        self._status = JobStatus.CREATED
        self._lock = threading.Lock()
        self._done = threading.Event()
        self._cancel = threading.Event()
        self._savepoint_path: Optional[str] = None
        self._savepoint_done = threading.Event()
        self.error: Optional[BaseException] = None
        self.records_in = 0
        self.num_restarts = 0
        self.num_checkpoints = 0
        # multichip (parallel.mesh.*): live mesh-size rescales performed on
        # this job (checkpoint rewind + key-group re-shard across device
        # counts) and the pending target the run loop picks up at the next
        # step boundary
        self.mesh_rescales = 0
        self.last_mesh_rescale_duration_ms = 0.0
        self._mesh_rescale_target: Optional[int] = None
        # skew-aware key-group routing (parallel.mesh.skew-rebalance):
        # completed routing-table rebalances on this job + the policy
        # object that decided them (scheduler/rebalancer.py)
        self.mesh_rebalances = 0
        self.last_mesh_rebalance_duration_ms = 0.0
        self.rebalancer = None

    def latency_report(self) -> dict:
        """Emission-latency + stall-attribution report (/jobs/:id/latency
        shape; the JM's job_latency builds the identical payload from
        shard-folded snapshots): per-operator log-bucket histograms and
        watermark lag from the live registry, outlier EmissionStall spans
        attributed against the job's control-plane spans."""
        from flink_tpu.metrics.emission_latency import build_latency_report
        from flink_tpu.metrics.registry import metrics_snapshot

        registry = getattr(self, "metrics", None)
        snap = metrics_snapshot(registry.all_metrics()) if registry else {}
        log = getattr(self, "span_log", None)
        spans = [s.to_dict() for s in log.spans] if log is not None else []
        return build_latency_report(snap, spans)

    def history_report(self, metric: Optional[str] = None,
                       since: Optional[float] = None) -> dict:
        """Metric time-series rings (/jobs/:id/history?metric=&since=
        shape; the JM's job_history builds the identical payload from
        shard-folded snapshots): per-key bounded point lists sampled on
        the processing-time tick — counters as windowed rates, gauges as
        values, histograms as per-sample p50/p99 sub-series."""
        history = getattr(self, "history", None)
        if history is None:
            return {"enabled": False, "series": {}, "sample_count": 0}
        payload = history.payload(
            metric=metric or None,
            since_ms=float(since) if since not in (None, "") else None)
        payload["enabled"] = True
        return payload

    def doctor_report(self) -> dict:
        """Ranked bottleneck diagnosis (/jobs/:id/doctor shape; identical
        payload on the distributed path): the job doctor joined over the
        history rings and this job's span log."""
        from flink_tpu.metrics.doctor import diagnose

        history = getattr(self, "history", None)
        window_ms = float(getattr(self, "doctor_window_ms", 60000.0))
        if history is None:
            return {"verdict": "unknown", "score": 0.0, "diagnoses": [],
                    "window_ms": window_ms, "samples": 0,
                    "watchdog_events": 0}
        log = getattr(self, "span_log", None)
        spans = [s.to_dict() for s in log.spans] if log is not None else []
        return diagnose(history, spans, window_ms=window_ms)

    # -- status -----------------------------------------------------------
    def status(self) -> JobStatus:
        return self._status

    def _set_status(self, status: JobStatus) -> None:
        with self._lock:
            self._status = status
        if status in (JobStatus.FINISHED, JobStatus.FAILED, JobStatus.CANCELED):
            self._done.set()

    def wait(self, timeout: Optional[float] = None) -> JobStatus:
        self._done.wait(timeout)
        if not self._done.is_set():
            raise TimeoutError(f"job {self.job_id} still {self._status}")
        if self._status == JobStatus.FAILED and self.error is not None:
            raise RuntimeError(f"job {self.job_id} failed") from self.error
        return self._status

    # -- operations -------------------------------------------------------
    def cancel(self) -> None:
        self._cancel.set()

    def trigger_savepoint(self, path: str, timeout: float = 30.0) -> str:
        """Requests a savepoint at the next step boundary; blocks until
        written (stop-with-savepoint arrives with the drain protocol)."""
        self._savepoint_done.clear()
        self._savepoint_path = path
        if not self._savepoint_done.wait(timeout):
            raise TimeoutError("savepoint not taken (job finished or stalled?)")
        return path

    def _poll_savepoint_request(self) -> Optional[str]:
        path = self._savepoint_path
        if path is not None:
            self._savepoint_path = None
            return path
        return None

    def rescale_mesh(self, devices: int) -> None:
        """Request a live mesh-size rescale of a RUNNING mesh job (the
        manual sibling of the autoscaler's decision): at the next step
        boundary the job captures its state, rebuilds over `devices`
        devices, and restores — exactly-once, no restart counted. No-op
        on jobs without parallel.mesh.enabled."""
        self._mesh_rescale_target = max(1, int(devices))

    def _poll_mesh_rescale(self) -> Optional[int]:
        t = self._mesh_rescale_target
        if t is not None:
            self._mesh_rescale_target = None
        return t

    # -- queryable state (S13: KvStateServer/ClientProxy analogue) ---------
    def query_state(self, uid: str, key) -> dict:
        """Point lookup into the RUNNING job's keyed state. Safe without
        locks: device state arrays are immutable (replaced atomically per
        step) and heap tables are only read here.

        Returns, per operator type:
          device window op : {"slices": {abs_slice: {field: value, count}},
                              "watermark": wm}
          oracle window op / keyed ops : {"states": {name: {repr(ns): value}},
                              "watermark": wm}
          rolling reduce   : {"value": current}
        """
        runtime = getattr(self, "_runtime", None)
        if runtime is None:
            raise RuntimeError("job has no running attempt")
        import numpy as np

        for r in runtime.runners:
            if getattr(r, "uid", None) != uid:
                continue
            op = getattr(r, "op", None)
            if op is not None and hasattr(op, "query_state_for"):
                # fused window operator folds ring + buffered views itself
                return {
                    "slices": op.query_state_for(key),
                    "watermark": op.current_watermark,
                }
            if op is not None and hasattr(op, "state") and hasattr(op.state, "keydict"):
                state = op.state
                kid = state.keydict.lookup(key)
                if kid is None:
                    return {"slices": {}, "watermark": op.current_watermark}
                count = np.asarray(state.count)[kid]
                acc = {k: np.asarray(v)[kid] for k, v in state.acc.items()}
                f = state.frontiers
                slices = {}
                if f.min_used is not None:
                    lo = f.min_used if f.purged_to is None else max(f.purged_to, f.min_used)
                    for s in range(lo, f.max_used + 1):
                        pos = s % state.S
                        if count[pos] > 0:
                            entry = {name: arr[pos].item() for name, arr in acc.items()}
                            entry["count"] = int(count[pos])
                            slices[s] = entry
                return {"slices": slices, "watermark": op.current_watermark}
            if op is not None and hasattr(op, "state"):  # oracle/heap ops
                backend = op.state
                backend.set_current_key(key)
                states = {}
                for name in backend.descriptors:
                    for ns in backend.namespaces_for_key(name, key):
                        states.setdefault(name, {})[repr(ns)] = backend.get(name, ns)
                wm = getattr(op, "timer_service", None)
                return {
                    "states": states,
                    "watermark": wm.current_watermark if wm else None,
                }
            if hasattr(r, "state"):  # KeyedReduceRunner et al.
                r.state.set_current_key(key)
                return {"value": r.state.get("rolling")}
        raise KeyError(f"no queryable operator {uid!r}")


class MiniCluster:
    _shared: Optional["MiniCluster"] = None

    def __init__(self, security=None):
        self.jobs: Dict[str, JobClient] = {}
        # the cluster's transport-security identity (auth ON by default):
        # in-process jobs never cross a socket, but everything layered on a
        # MiniCluster that DOES (RestServer bearer derivation, distributed
        # hand-off) shares this one resolved secret/cluster-id
        from flink_tpu.security.transport import SecurityConfig

        self.security = SecurityConfig.resolve() if security is None else security

    @classmethod
    def get_shared(cls) -> "MiniCluster":
        if cls._shared is None:
            cls._shared = MiniCluster()
        return cls._shared

    def submit(
        self,
        graph: StepGraph,
        config: Configuration,
        job_name: Optional[str] = None,
        savepoint_restore_path: Optional[str] = None,
    ) -> JobClient:
        job_id = uuid.uuid4().hex[:16]
        client = JobClient(job_id, job_name or f"job-{job_id}")
        self.jobs[job_id] = client
        thread = threading.Thread(
            target=self._run_job,
            args=(client, graph, config, savepoint_restore_path),
            name=f"jobmaster-{job_id}",
            daemon=True,
        )
        thread.start()
        return client

    # ------------------------------------------------------------------
    def _run_job(
        self,
        client: JobClient,
        graph: StepGraph,
        config: Configuration,
        savepoint_restore_path: Optional[str],
    ) -> None:
        # chaos.* config group: a job config can run a fault drill on the
        # in-process path too (tests/scenarios install plans through
        # testing.harness.fault_injection instead; this never stacks).
        # The plan is uninstalled when THIS job ends — a process-wide hook
        # leaking past the drill would fault every later job for no reason
        from flink_tpu.chaos import plan as _chaos

        chaos_plan = _chaos.FaultPlan.from_config(config)
        installed_chaos = False
        if chaos_plan is not None and _chaos.active_plan() is None:
            _chaos.install_plan(chaos_plan)
            installed_chaos = True
        try:
            self._run_job_inner(client, graph, config, savepoint_restore_path)
        finally:
            if installed_chaos and _chaos.active_plan() is chaos_plan:
                _chaos.uninstall_plan()

    @absorbs_faults('driver failover boundary: the caught failure increments the attempt counter and re-runs the job per the restart strategy; injected faults ride this path by design')
    def _run_job_inner(
        self,
        client: JobClient,
        graph: StepGraph,
        config: Configuration,
        savepoint_restore_path: Optional[str],
    ) -> None:
        from flink_tpu.config import ObservabilityOptions
        from flink_tpu.metrics.checkpoint_stats import (
            CheckpointStatsTracker,
            ExceptionHistory,
            failing_task,
        )
        from flink_tpu.metrics.otel import OtlpJsonTraceReporter
        from flink_tpu.metrics.registry import MetricRegistry
        from flink_tpu.metrics.traces import TraceRegistry, job_trace_id

        client.metrics = MetricRegistry()
        # one correlation id per job: every span this job emits (checkpoint
        # lifecycle, restarts) carries it, and any process that knows the
        # job id derives the same id (traces.job_trace_id) — JM- and
        # TM-side spans stitch into one trace
        client.trace_id = job_trace_id(client.job_id)
        client.traces = TraceRegistry(trace_id=client.trace_id)
        # OTel-shape export: buffered OTLP/JSON, served at /jobs/<id>/traces
        client.otel = OtlpJsonTraceReporter(service_name="flink-tpu")
        client.traces.add_reporter(client.otel)
        # raw-span log for /jobs/:id/latency stall attribution: outlier
        # EmissionStall spans joined against the same registry's
        # checkpoint/recovery/compile spans by interval overlap (bounded —
        # a long-running job must not grow it without limit)
        from flink_tpu.metrics.traces import InMemoryTraceReporter

        client.span_log = InMemoryTraceReporter(max_spans=512)
        client.traces.add_reporter(client.span_log)
        # history plane + health watchdog (ISSUE-19): the client samples
        # its own folded registry view on the processing-time tick (the
        # cancel_check step boundary below); watchdog breaches land in the
        # same trace registry as every other control-plane span
        from flink_tpu.metrics.doctor import HealthWatchdog
        from flink_tpu.metrics.history import MetricHistory
        from flink_tpu.metrics.traces import Span

        client.history = MetricHistory(
            interval_ms=config.get(ObservabilityOptions.HISTORY_INTERVAL_MS),
            retention_points=config.get(
                ObservabilityOptions.HISTORY_RETENTION_POINTS))
        client.doctor_window_ms = float(
            config.get(ObservabilityOptions.DOCTOR_WINDOW_MS))
        client.watchdog = None
        if config.get(ObservabilityOptions.DOCTOR_ENABLED):
            def _health_sink(scope, name, start_ms, end_ms, attrs,
                             _c=client):
                _c.traces.report(Span(scope, name, start_ms, end_ms,
                                      dict(attrs, jobId=_c.job_id)))

            client.watchdog = HealthWatchdog(
                _health_sink,
                min_gap_ms=float(config.get(
                    ObservabilityOptions.DOCTOR_WATCHDOG_MIN_GAP_MS)),
                p99_breach_ms=config.get(
                    ObservabilityOptions.DOCTOR_P99_BREACH_MS))
        interval = config.get(CheckpointingOptions.INTERVAL_MS)
        chk_dir = config.get(CheckpointingOptions.DIRECTORY)
        storage = FsCheckpointStorage(chk_dir) if chk_dir else MemoryCheckpointStorage()
        # fault-tolerance observability: per-checkpoint stats (bounded ring
        # + the standard gauges on the job's registry, so /metrics and
        # /jobs/:id/checkpoints see them) and a bounded exception/recovery
        # history replacing a single overwritten error
        job_group = client.metrics.group("job")
        client.checkpoint_stats = CheckpointStatsTracker(
            history_size=config.get(ObservabilityOptions.CHECKPOINT_HISTORY_SIZE))
        client.checkpoint_stats.register_metrics(job_group)
        client.exceptions = ExceptionHistory(
            size=config.get(ObservabilityOptions.EXCEPTION_HISTORY_SIZE))
        client.exceptions.register_metrics(job_group)
        # elastic autoscaler: an in-process job runs as ONE task, so the
        # slot-parallelism axis has nothing to rescale — but with a device
        # MESH (parallel.mesh.enabled) the mesh size IS a parallelism axis
        # this process owns, and the coordinator gets a real executor:
        # decisions turn into live checkpoint-rewind + key-group re-shard
        # onto a different device count at a step boundary. Without a mesh
        # the coordinator stays observe-only (decision log only).
        from flink_tpu.config import AutoscalerOptions

        mesh_enabled = config.get(ParallelOptions.MESH_ENABLED)
        mesh_autoscale = (mesh_enabled
                          and config.get(ParallelOptions.MESH_AUTOSCALE))
        # skew-aware key-group routing (parallel.mesh.skew-rebalance): the
        # scheduler-side policy decides, the run loop executes at a
        # step-aligned boundary through the rescale capture/restore
        # machinery. Gauges register whenever the mesh is on, so the
        # observability surface is uniform (0 / version until a table
        # exists — the numRescales pattern above).
        skew_rebalance = (mesh_enabled
                          and config.get(ParallelOptions.MESH_SKEW_REBALANCE))
        if mesh_enabled:
            # per-mesh facts every shard would report identically -> MAX
            # (the _REBALANCE_GAUGES rule, now declared at registration)
            job_group.gauge("meshRebalances",
                            lambda: client.mesh_rebalances,
                            fold="max", kind="counter")
            job_group.gauge("lastRebalanceDurationMs",
                            lambda: client.last_mesh_rebalance_duration_ms,
                            fold="max")
            job_group.gauge(
                "routingTableVersion",
                lambda: (getattr(client, "_runtime", None) is not None
                         and client._runtime.mesh_routing_version()) or 0,
                fold="max")
        if skew_rebalance:
            from flink_tpu.scheduler.rebalancer import SkewRebalancer

            client.rebalancer = SkewRebalancer(
                skew_threshold=config.get(
                    ParallelOptions.MESH_REBALANCE_SKEW_THRESHOLD),
                interval_ms=config.get(
                    ParallelOptions.MESH_REBALANCE_INTERVAL_MS))
        if config.get(AutoscalerOptions.ENABLED):
            from flink_tpu.metrics.registry import metrics_snapshot
            from flink_tpu.scheduler import AutoscalerCoordinator

            mesh_executor = None
            if mesh_autoscale:
                def mesh_executor(job_id, target, reason, _c=client):
                    rt = getattr(_c, "_runtime", None)
                    if rt is None:
                        return False, "no running attempt"
                    # pre-apply the SAME clamp the rebuild will apply
                    # (_effective_mesh_target), so an unreachable target
                    # — no mesh-capable operator, no shard_map backend,
                    # or a device count the construction-time capacity
                    # cannot divide — reads as rejected instead of
                    # tearing the job down for a no-op rebuild and
                    # re-firing every stabilization window
                    eff = _effective_mesh_target(rt, int(target))
                    if eff is None:
                        return False, "job has no mesh-capable operator"
                    cur = rt.mesh_devices()
                    if eff == cur:
                        return False, f"mesh already at {cur} device(s)"
                    _c._mesh_rescale_target = eff
                    return True, f"mesh rescale {cur} -> {eff} requested"

            client.autoscaler = AutoscalerCoordinator.from_config(
                config, rescale_executor=mesh_executor)
            # without a mesh executor these read a constant 0 — registered
            # anyway so the gauge surface matches the distributed JM and
            # dashboards scrape one shape
            job_group.gauge("numRescales", lambda: client.mesh_rescales,
                            fold="max", kind="counter")
            job_group.gauge("lastRescaleDurationMs",
                            lambda: client.last_mesh_rescale_duration_ms,
                            fold="max")
            client._autoscaler_metrics = (
                lambda c=client: metrics_snapshot(c.metrics.all_metrics()))
        coordinator = (
            CheckpointCoordinator(
                storage,
                interval,
                config.get(CheckpointingOptions.MAX_RETAINED),
                traces=client.traces,
                stats=client.checkpoint_stats,
                tolerable_failures=config.get(
                    CheckpointingOptions.TOLERABLE_FAILED_CHECKPOINTS),
            )
            if interval > 0
            else None
        )
        if coordinator is not None:
            coordinator.register_on_complete(
                lambda _cp, c=client, co=coordinator:
                    setattr(c, "num_checkpoints", co.num_completed))
        strategy = restart_strategy_from_config(config)
        attempt = 0
        # mesh-size override for the NEXT attempt: set by a live rescale
        # (autoscaler decision or manual rescale_mesh) and by the
        # device-loss degrade policy; None = the configured size
        mesh_override: Optional[int] = None
        pending_rescale: Optional[dict] = None
        # routing assignment for the NEXT attempt: set by a skew rebalance
        # (applied to the rebuilt runtime BEFORE restore, so the canonical
        # capture lands in the new placement)
        pending_rebalance: Optional[dict] = None

        restore_snap = None
        restore_ms = 0.0
        # open recovery span: created at failure, closed only when the
        # REBUILT attempt reaches RUNNING — the interval must cover the
        # runtime rebuild + state restore so emission-stall attribution
        # can overlap post-restore window-fire latency against it
        restart_span = None
        if savepoint_restore_path is not None:
            sp_storage = FsCheckpointStorage(savepoint_restore_path)
            latest = sp_storage.latest()
            if latest is None:
                client.error = FileNotFoundError(
                    f"no savepoint at {savepoint_restore_path}"
                )
                client._set_status(JobStatus.FAILED)
                return
            t_restore = time.perf_counter()
            restore_snap = sp_storage.load(latest[1])
            restore_ms = (time.perf_counter() - t_restore) * 1000.0

        while True:
            cfg = config
            if mesh_override is not None:
                cfg = config.clone()
                cfg.set(ParallelOptions.MESH_DEVICES, mesh_override)
            runtime = JobRuntime(graph, cfg, registry=client.metrics,
                                 traces=client.traces)
            client._runtime = runtime  # queryable-state surface (S13)
            if coordinator is not None:
                # each attempt gets its full tolerable-failed-checkpoints
                # budget (the coordinator outlives restarts)
                coordinator.reset_failure_streak()
                # per-operator breakdown for completed checkpoint records
                # comes from THIS attempt's operators
                coordinator.state_bytes_fn = runtime.operator_state_bytes
            try:
                if restore_snap is not None:
                    runtime.restore(restore_snap)
                    if pending_rescale is None and pending_rebalance is None:
                        # a live mesh rescale/rebalance restores from its
                        # own step-aligned capture, not a stored checkpoint
                        # — stamping a "restored checkpoint None" record
                        # would pollute the checkpoint-restore telemetry
                        client.checkpoint_stats.report_restore(
                            restore_snap.get("checkpoint_id"), restore_ms)
                client._set_status(JobStatus.RUNNING)
                # the restarted attempt is live again: close the recovery
                # timeline record (downtime = fail -> RUNNING)
                client.exceptions.complete_recovery(
                    restored_checkpoint_id=(restore_snap or {}).get(
                        "checkpoint_id"),
                    restore_duration_ms=restore_ms,
                    events_replayed=(
                        client.records_in - restore_snap.get("records_in", 0)
                        if restore_snap is not None else client.records_in),
                )
                if restart_span is not None:
                    # failure -> RUNNING: same downtime interval the
                    # recovery timeline records
                    client.traces.report(restart_span.set_attribute(
                        "restoredCheckpoint", bool(restore_snap)).end())
                    restart_span = None
                if pending_rescale is not None:
                    # the rebuilt attempt is serving at the new mesh size:
                    # stamp the completed rescale (counter + duration) and
                    # close the loop back into the autoscaler's learning
                    # history, target-tagged like the distributed JM does
                    duration_ms = (time.perf_counter()
                                   - pending_rescale["t0"]) * 1000.0
                    client.mesh_rescales += 1
                    client.last_mesh_rescale_duration_ms = duration_ms
                    auto = getattr(client, "autoscaler", None)
                    if auto is not None:
                        auto.rescale_completed(
                            client.job_id, duration_ms,
                            target=runtime.mesh_devices())
                    pending_rescale = None
                if pending_rebalance is not None:
                    # apply the rebalanced routing table AFTER restore:
                    # restore may ADOPT a grown snapshot K (classic keyed
                    # path) and rebuild the table for the new capacity —
                    # applying first would silently discard the
                    # assignment (or raise on a G mismatch) and the
                    # rebalancer would re-decide the same move forever.
                    # The capture is canonical [K, S], so re-laying the
                    # restored rows under the new table is pure placement
                    runtime.set_mesh_routing(pending_rebalance["assign"])
                    # the rebuilt attempt is serving under the new routing
                    # table: stamp the completed rebalance and restart the
                    # policy's interval clock so the new placement gets
                    # fresh traffic before it is judged again
                    duration_ms = (time.perf_counter()
                                   - pending_rebalance["t0"]) * 1000.0
                    client.mesh_rebalances += 1
                    client.last_mesh_rebalance_duration_ms = duration_ms
                    if client.rebalancer is not None:
                        client.rebalancer.rebalance_completed()
                    pending_rebalance = None

                def cancel_check():
                    client.records_in = runtime.records_in  # progress gauge
                    auto = getattr(client, "autoscaler", None)
                    if auto is not None:
                        # throttled: maybe_observe snapshots the registry
                        # only when an autoscaler.interval-ms tick is due.
                        # On a mesh job the parallelism the policy sees IS
                        # the mesh size (the axis its executor rescales)
                        auto.maybe_observe(
                            client.job_id,
                            runtime.mesh_devices() if mesh_autoscale else 1,
                            client._autoscaler_metrics)
                    # history sampling on the same processing-time tick
                    # (the autoscaler's throttled-snapshot pattern): the
                    # cheap due() gate runs every step, the registry
                    # snapshot only on a due interval tick
                    if client.history.due():
                        from flink_tpu.metrics.registry import (
                            metrics_snapshot,
                        )

                        client.history.sample(
                            metrics_snapshot(client.metrics.all_metrics()))
                        if client.watchdog is not None:
                            client.watchdog.observe(client.history)
                    return client._cancel.is_set()

                def poll_mesh_rescale(rt=runtime):
                    # manual rescale_mesh targets arrive unclamped; apply
                    # the construction-time clamp HERE so an unreachable
                    # target (or one landing on the current size) never
                    # costs a stop-the-world rebuild that changes nothing
                    t = client._poll_mesh_rescale()
                    if t is None:
                        return None
                    eff = _effective_mesh_target(rt, t)
                    if eff is None or eff == rt.mesh_devices():
                        return None
                    return eff

                def poll_rebalance(rt=runtime):
                    # skew rebalance, polled at every step boundary: the
                    # interval throttle gates FIRST (one clock read per
                    # step) — only a due tick pays the per-group load
                    # readback and the balanced replan
                    reb = client.rebalancer
                    if reb is None or not reb.due():
                        return None
                    info = rt.mesh_group_loads()
                    if info is None:
                        return None
                    loads, assign, n = info
                    return reb.maybe_decide(loads, assign, n)

                runtime.run(
                    coordinator=coordinator,
                    cancel_check=cancel_check,
                    savepoint_request=lambda: self._savepoint_hook(client, runtime),
                    rescale_request=(poll_mesh_rescale
                                     if mesh_enabled else None),
                    rebalance_request=(poll_rebalance
                                       if skew_rebalance else None),
                )
                client.records_in = runtime.records_in
                client._set_status(JobStatus.FINISHED)
                return
            except JobCancelledException:
                client._set_status(JobStatus.CANCELED)
                return
            except MeshRescaleRequested as mr:
                # deliberate live rescale OR skew rebalance, not a
                # failure: rebuild the runtime (same device count for a
                # rebalance) and restore from the step-aligned capture the
                # run loop handed us (checkpoint rewind + key-group
                # re-shard/re-route; no restart counted, no backoff,
                # restart_attempts untouched)
                client.records_in = runtime.records_in
                mesh_override = mr.target
                restore_snap = mr.snapshot
                restore_ms = 0.0
                if mr.routing is not None:
                    pending_rebalance = {"t0": time.perf_counter(),
                                         "assign": mr.routing}
                    cause = (f"mesh key-group rebalance over {mr.target} "
                             "device(s)")
                    kind = "rebalance"
                else:
                    pending_rescale = {"t0": time.perf_counter(),
                                       "target": mr.target}
                    cause = f"mesh rescale to {mr.target} device(s)"
                    kind = "rescale"
                client._set_status(JobStatus.RESTARTING)
                client.exceptions.begin_recovery(
                    client.num_restarts,
                    cause=cause,
                    events_at_failure=client.records_in,
                    kind=kind)
                continue
            except BaseException as e:  # noqa: BLE001 — failover boundary
                attempt += 1
                client.error = e
                # a mid-rescale/-rebalance failure must not stamp a
                # completed-rescale/-rebalance duration (PR-6 outcome
                # hygiene): the job degraded into the plain restart path
                # instead — the restarted attempt resets to the identity
                # routing table, consistent with the canonical checkpoint
                # it restores (the rebalancer re-decides from live skew)
                pending_rescale = None
                pending_rebalance = None
                if (mesh_enabled
                        and config.get(
                            ParallelOptions.MESH_DEGRADE_ON_DEVICE_LOSS)
                        and runtime.mesh_devices() > 1
                        and _is_device_loss(e)):
                    # chip/host loss: restart the job at a REDUCED mesh
                    # size — the canonical [K, S] checkpoint re-shards over
                    # whatever devices survive (halving per restart,
                    # floor 1 = single-chip)
                    mesh_override = max(1, runtime.mesh_devices() // 2)
                # bounded exception history (ExceptionHistoryEntry analogue):
                # timestamp, failing-operator attribution, root-cause chain
                client.exceptions.record_failure(
                    repr(e),
                    task=failing_task(e) or client.job_name,
                    restart_number=attempt - 1,
                    exception=e,
                )
                delay = strategy.next_delay_ms(attempt)
                if delay is None:
                    client._set_status(JobStatus.FAILED)
                    return
                client.num_restarts = attempt
                client._set_status(JobStatus.RESTARTING)
                client.exceptions.begin_recovery(
                    attempt, cause=repr(e),
                    events_at_failure=client.records_in)
                if restart_span is not None:
                    # the previous recovery never reached RUNNING (the
                    # rebuilt attempt failed during restore) — close its
                    # span so the trace stays bounded
                    client.traces.report(restart_span.set_attribute(
                        "reachedRunning", False).end())
                restart_span = client.traces.span("recovery", "JobRestart") \
                    .set_attribute("attempt", attempt) \
                    .set_attribute("delayMs", delay) \
                    .set_attribute("cause", repr(e)[:200])
                time.sleep(delay / 1000.0)
                t_restore = time.perf_counter()
                restore_snap = coordinator.latest_snapshot() if coordinator else None
                restore_ms = (time.perf_counter() - t_restore) * 1000.0

    def _savepoint_hook(self, client: JobClient, runtime: JobRuntime) -> Optional[str]:
        path = client._poll_savepoint_request()
        if path is not None:
            runtime._write_savepoint(path)
            client._savepoint_done.set()
            return None  # runtime already wrote it
        return None
