"""Oracle WindowOperator: per-record Python implementation at exact reference
parity.

This is the executable specification of
flink-runtime .../streaming/runtime/operators/windowing/WindowOperator.java
(processElement :293-447, onEventTime :450, onProcessingTime :497,
emitWindowContents :575, isWindowLate :609, cleanup timers :631/:670) plus
the MergingWindowSet session-merge path (:303-403). It serves three roles:

1. **Parity oracle** for the batched device operator (property tests assert
   result equality — the "result parity" requirement of BASELINE.json).
2. **CPU baseline operator** for bench.py (the single-node per-record path
   whose throughput the device operator must beat 10×).
3. **Fallback operator** for features outside the device path's columnar
   aggregator model (arbitrary Python AggregateFunctions, evictors).

Not a translation of the Java class structure: it is a direct implementation
of the documented per-record semantics against our heap state backend and
timer service.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

from flink_tpu.api.functions import (
    AggregateFunction,
    LATE_DATA_TAG,
    ProcessWindowFunction,
    ReduceAggregate,
)
from flink_tpu.api.windowing.assigners import WindowAssigner
from flink_tpu.api.windowing.evictors import Evictor
from flink_tpu.api.windowing.triggers import Trigger, TriggerContext, TriggerResult
from flink_tpu.core.keygroups import KeyGroupRange
from flink_tpu.core.time import MAX_WATERMARK, MIN_WATERMARK, TimeWindow, cleanup_time, is_window_late
from flink_tpu.runtime.timers import InternalTimerService
from flink_tpu.state.heap import (
    HeapKeyedStateBackend,
    aggregating_state,
    list_state,
    map_state,
    value_state,
)

WINDOW_STATE = "window-contents"
MERGE_SET_STATE = "merging-window-set"
TRIGGER_STATE_PREFIX = "trigger."


class _OperatorTriggerContext(TriggerContext):
    """Binds (key, window) for trigger callbacks; trigger state is partitioned
    per (key, window-namespace) like Trigger.TriggerContext's partitioned
    state."""

    def __init__(self, op: "OracleWindowOperator"):
        self._op = op
        self.key = None
        self.window = None

    def get_current_watermark(self) -> int:
        return self._op.timer_service.current_watermark

    def register_event_time_timer(self, time: int) -> None:
        self._op.timer_service.register_event_time_timer(self.key, self.window, time)

    def delete_event_time_timer(self, time: int) -> None:
        self._op.timer_service.delete_event_time_timer(self.key, self.window, time)

    def register_processing_time_timer(self, time: int) -> None:
        self._op.timer_service.register_processing_time_timer(self.key, self.window, time)

    def delete_processing_time_timer(self, time: int) -> None:
        self._op.timer_service.delete_processing_time_timer(self.key, self.window, time)

    def get_trigger_state(self, name: str, default=None):
        v = self._op.state.get(TRIGGER_STATE_PREFIX + name, namespace=self.window)
        return default if v is None else v

    def set_trigger_state(self, name: str, value) -> None:
        self._op.state.put(TRIGGER_STATE_PREFIX + name, value, namespace=self.window)

    def clear_trigger_state(self, name: str) -> None:
        self._op.state.clear(TRIGGER_STATE_PREFIX + name, namespace=self.window)

    def merge_trigger_state(self, target, sources: List, names=("count",)) -> None:
        # numeric trigger state (counts) merges additively
        for name in names:
            total, found = 0, False
            for ns in list(sources) + [target]:
                v = self._op.state.get(TRIGGER_STATE_PREFIX + name, namespace=ns)
                if v is not None:
                    total += v
                    found = True
                self._op.state.clear(TRIGGER_STATE_PREFIX + name, namespace=ns)
            if found:
                self._op.state.put(TRIGGER_STATE_PREFIX + name, total, namespace=target)


class MergingWindowSet:
    """window -> state-window mapping with merge-on-add
    (MergingWindowSet.java semantics). Persisted in keyed state per key."""

    def __init__(self, op: "OracleWindowOperator", key):
        self._op = op
        self._key = key
        stored = op.state.get(MERGE_SET_STATE)
        self.mapping: Dict[TimeWindow, TimeWindow] = dict(stored) if stored else {}

    def persist(self) -> None:
        if self.mapping:
            self._op.state.put(MERGE_SET_STATE, dict(self.mapping))
        else:
            self._op.state.clear(MERGE_SET_STATE)

    def get_state_window(self, window: TimeWindow) -> Optional[TimeWindow]:
        return self.mapping.get(window)

    def retire_window(self, window: TimeWindow) -> None:
        self.mapping.pop(window, None)

    def add_window(self, new_window: TimeWindow, merge_fn: Callable) -> TimeWindow:
        """merge_fn(merge_result, merged_windows, state_window_result,
        merged_state_windows) — called only when an actual merge happens."""
        windows = list(self.mapping.keys()) + [new_window]
        merge_results = self._op.assigner.merge_windows(windows)

        result_window = new_window
        merged_new = False
        for cover, members in merge_results:
            if new_window in members:
                result_window = cover
                merged_new = len(members) > 1
            if len(members) <= 1:
                continue
            # pre-existing windows being merged (exclude the brand-new one,
            # which has no state window yet)
            merged_existing = [w for w in members if w != new_window or w in self.mapping]
            if not merged_existing:
                continue
            # keep the state window of one merged member; others get merged in
            kept_state_window = self.mapping.get(merged_existing[0], merged_existing[0])
            merged_state_windows = [
                self.mapping[w]
                for w in merged_existing[1:]
                if w in self.mapping and self.mapping[w] != kept_state_window
            ]
            for w in members:
                self.mapping.pop(w, None)
            self.mapping[cover] = kept_state_window
            # mergedWindows passed to callback excludes the result itself
            callback_merged = [w for w in members if w != cover]
            if callback_merged and (len(merged_existing) > 1 or merged_new):
                merge_fn(cover, callback_merged, kept_state_window, merged_state_windows)
        if not merged_new and new_window not in self.mapping:
            self.mapping[new_window] = new_window
        return result_window


class OracleWindowOperator:
    """One logical operator instance covering a key-group range."""

    def __init__(
        self,
        assigner: WindowAssigner,
        aggregate: AggregateFunction,
        *,
        trigger: Optional[Trigger] = None,
        allowed_lateness: int = 0,
        max_parallelism: int = 128,
        key_group_range: Optional[KeyGroupRange] = None,
        window_function: Optional[ProcessWindowFunction] = None,
        evictor: Optional[Evictor] = None,
        emit_late_to_side_output: bool = False,
    ):
        self.assigner = assigner
        self.aggregate = (
            ReduceAggregate(aggregate) if not isinstance(aggregate, AggregateFunction) and aggregate is not None
            else aggregate
        )
        self.trigger = trigger or assigner.get_default_trigger()
        self.allowed_lateness = allowed_lateness
        self.window_function = window_function
        self.evictor = evictor
        self.emit_late_to_side_output = emit_late_to_side_output
        self.max_parallelism = max_parallelism
        kg_range = key_group_range or KeyGroupRange(0, max_parallelism - 1)

        self.state = HeapKeyedStateBackend(kg_range, max_parallelism)
        if evictor is not None or self.aggregate is None:
            self.state.register(list_state(WINDOW_STATE))
            self._buffering = True
        else:
            self.state.register(aggregating_state(WINDOW_STATE, self.aggregate))
            self._buffering = False
        self.state.register(map_state(MERGE_SET_STATE))
        self.state.register(value_state(TRIGGER_STATE_PREFIX + "count"))

        self.timer_service = InternalTimerService(self._on_event_time, self._on_processing_time)
        self._trigger_ctx = _OperatorTriggerContext(self)

        # outputs: (key, window, result, timestamp) / side outputs / metrics
        self.output: List[Tuple[Any, Any, Any, int]] = []
        self.side_output: Dict[str, List] = {}
        self.num_late_records_dropped = 0

    # ------------------------------------------------------------------
    # processElement (WindowOperator.java:293-447)
    # ------------------------------------------------------------------
    def process_record(self, key, value, timestamp: int) -> None:
        self.state.set_current_key(key)
        windows = self.assigner.assign_windows(value, timestamp)
        skipped = True

        if self.assigner.is_merging:
            skipped = self._process_merging(key, value, timestamp, windows)
        else:
            for window in windows:
                if self._is_window_late(window):
                    continue
                skipped = False
                self._add_to_window(value, timestamp, window)
                self._trigger_ctx.key, self._trigger_ctx.window = key, window
                result = self.trigger.on_element(value, timestamp, window, self._trigger_ctx)
                if result.is_fire:
                    self._fire(key, window, window)
                if result.is_purge:
                    self.state.clear(WINDOW_STATE, namespace=window)
                self._register_cleanup_timer(key, window)

        if skipped and self._is_element_late(timestamp):
            if self.emit_late_to_side_output:
                self.side_output.setdefault(LATE_DATA_TAG.tag_id, []).append((key, value, timestamp))
            else:
                self.num_late_records_dropped += 1

    def _process_merging(self, key, value, timestamp, windows) -> bool:
        skipped = True
        merging = MergingWindowSet(self, key)

        def on_merge(merge_result, merged_windows, state_window_result, merged_state_windows):
            self._trigger_ctx.key, self._trigger_ctx.window = key, merge_result
            if self.trigger.can_merge():
                self.trigger.on_merge(merge_result, self._trigger_ctx)
            self._trigger_ctx.merge_trigger_state(merge_result, merged_windows)
            for m in merged_windows:
                self._trigger_ctx.window = m
                self.trigger.clear(m, self._trigger_ctx)
                self._delete_cleanup_timer(key, m)
            self._trigger_ctx.window = merge_result
            if merged_state_windows:
                self.state.merge_namespaces(WINDOW_STATE, state_window_result, merged_state_windows)

        for window in windows:
            actual = merging.add_window(window, on_merge)
            if self._is_window_late(actual):
                merging.retire_window(actual)
                continue
            skipped = False
            state_window = merging.get_state_window(actual)
            self._add_to_window(value, timestamp, state_window)
            self._trigger_ctx.key, self._trigger_ctx.window = key, actual
            result = self.trigger.on_element(value, timestamp, actual, self._trigger_ctx)
            if result.is_fire:
                self._fire(key, actual, state_window)
            if result.is_purge:
                self.state.clear(WINDOW_STATE, namespace=state_window)
            self._register_cleanup_timer(key, actual)
        merging.persist()
        return skipped

    def _add_to_window(self, value, timestamp, namespace) -> None:
        if self._buffering:
            self.state.add(WINDOW_STATE, (timestamp, value), namespace=namespace)
        else:
            self.state.add(WINDOW_STATE, value, namespace=namespace)

    # ------------------------------------------------------------------
    # timers (onEventTime :450 / onProcessingTime :497)
    # ------------------------------------------------------------------
    def _on_event_time(self, time: int, key, window) -> None:
        self.state.set_current_key(key)
        self._trigger_ctx.key, self._trigger_ctx.window = key, window

        merging = MergingWindowSet(self, key) if self.assigner.is_merging else None
        if merging is not None:
            state_window = merging.get_state_window(window)
            if state_window is None:
                return  # window was merged away; timer is stale
        else:
            state_window = window

        result = self.trigger.on_event_time(time, window, self._trigger_ctx)
        if result.is_fire:
            self._fire(key, window, state_window)
        if result.is_purge:
            self.state.clear(WINDOW_STATE, namespace=state_window)

        if self.assigner.is_event_time and self._is_cleanup_time(window, time):
            self._clear_all_state(key, window, state_window, merging)
        if merging is not None:
            merging.persist()

    def _on_processing_time(self, time: int, key, window) -> None:
        self.state.set_current_key(key)
        self._trigger_ctx.key, self._trigger_ctx.window = key, window
        merging = MergingWindowSet(self, key) if self.assigner.is_merging else None
        if merging is not None:
            state_window = merging.get_state_window(window)
            if state_window is None:
                return
        else:
            state_window = window
        result = self.trigger.on_processing_time(time, window, self._trigger_ctx)
        if result.is_fire:
            self._fire(key, window, state_window)
        if result.is_purge:
            self.state.clear(WINDOW_STATE, namespace=state_window)
        if not self.assigner.is_event_time and self._is_cleanup_time(window, time):
            self._clear_all_state(key, window, state_window, merging)
        if merging is not None:
            merging.persist()

    def process_watermark(self, watermark: int) -> None:
        self.timer_service.advance_watermark(watermark)

    def advance_processing_time(self, time: int) -> None:
        self.timer_service.advance_processing_time(time)

    # ------------------------------------------------------------------
    # firing & cleanup (emitWindowContents :575, clearAllState)
    # ------------------------------------------------------------------
    def _fire(self, key, window, state_window) -> None:
        contents = self.state.get(WINDOW_STATE, namespace=state_window)
        if contents is None:
            return
        ts = window.max_timestamp() if hasattr(window, "max_timestamp") else MAX_WATERMARK
        if self._buffering:
            elements = contents
            if self.evictor is not None:
                elements = self.evictor.evict_before(elements, len(elements), window)
            values = [v for _, v in elements]
            if self.window_function is not None:
                ctx = ProcessWindowFunction.Context(window, self.timer_service.current_watermark)
                for out in self.window_function.process(key, ctx, values):
                    self.output.append((key, window, out, ts))
            else:
                for out in values:
                    self.output.append((key, window, out, ts))
            if self.evictor is not None:
                remaining = self.evictor.evict_after(elements, len(elements), window)
                self.state.put(WINDOW_STATE, list(remaining), namespace=state_window)
        else:
            result = self.aggregate.get_result(contents)
            if self.window_function is not None:
                ctx = ProcessWindowFunction.Context(window, self.timer_service.current_watermark)
                for out in self.window_function.process(key, ctx, [result]):
                    self.output.append((key, window, out, ts))
            else:
                self.output.append((key, window, result, ts))

    def _clear_all_state(self, key, window, state_window, merging) -> None:
        self.state.clear(WINDOW_STATE, namespace=state_window)
        self._trigger_ctx.key, self._trigger_ctx.window = key, window
        self.trigger.clear(window, self._trigger_ctx)
        self._trigger_ctx.clear_trigger_state("count")
        if merging is not None:
            merging.retire_window(window)

    # ------------------------------------------------------------------
    # lateness helpers (:609-:670)
    # ------------------------------------------------------------------
    def _is_window_late(self, window) -> bool:
        if not self.assigner.is_event_time or not isinstance(window, TimeWindow):
            return False
        return is_window_late(window, self.allowed_lateness, self.timer_service.current_watermark)

    def _is_element_late(self, timestamp: int) -> bool:
        return (
            self.assigner.is_event_time
            and timestamp + self.allowed_lateness <= self.timer_service.current_watermark
        )

    def _cleanup_time(self, window) -> int:
        if not isinstance(window, TimeWindow):
            return MAX_WATERMARK
        if self.assigner.is_event_time:
            return cleanup_time(window, self.allowed_lateness)
        return window.max_timestamp()

    def _is_cleanup_time(self, window, time: int) -> bool:
        return time == self._cleanup_time(window)

    def _register_cleanup_timer(self, key, window) -> None:
        ct = self._cleanup_time(window)
        if ct == MAX_WATERMARK:
            return  # no cleanup for global windows / saturated lateness
        if self.assigner.is_event_time:
            self.timer_service.register_event_time_timer(key, window, ct)
        else:
            self.timer_service.register_processing_time_timer(key, window, ct)

    def _delete_cleanup_timer(self, key, window) -> None:
        ct = self._cleanup_time(window)
        if ct == MAX_WATERMARK:
            return
        if self.assigner.is_event_time:
            self.timer_service.delete_event_time_timer(key, window, ct)
        else:
            self.timer_service.delete_processing_time_timer(key, window, ct)

    # ------------------------------------------------------------------
    # snapshot / restore (operator-level; used by checkpointing)
    # ------------------------------------------------------------------
    def snapshot(self) -> dict:
        return {
            "state": self.state.snapshot(),
            "timers": self.timer_service.snapshot(),
        }

    def restore(self, snap: dict) -> None:
        self.state.restore(snap["state"])
        self.timer_service.restore(snap["timers"])

    def drain_output(self) -> List[Tuple[Any, Any, Any, int]]:
        out = self.output
        self.output = []
        return out
