"""REST endpoint + minimal web dashboard.

Capability parity with the reference's web monitor / REST stack
(runtime/rest handlers, WebMonitorEndpoint.java:224, RestClusterClient
submission, the Angular dashboard O5 — here a dependency-free single-page
view). Endpoints:

  GET  /                      → HTML dashboard (jobs + metrics, auto-refresh)
  GET  /overview              → cluster overview JSON
  GET  /jobs                  → [{id, name, status}]
  GET  /jobs/<id>             → job detail JSON
  PATCH/POST /jobs/<id>/cancel→ cancel
  POST /jobs/<id>/savepoints  → {"target-directory": dir} → trigger savepoint
  GET  /jobs/<id>/metrics     → metrics JSON
  GET  /jobs/<id>/vertices/<uid>/backpressure
                              → busy/idle/backPressured ratios + level
                                (JobVertexBackPressureHandler analogue)
  GET  /jobs/<id>/checkpoints → checkpoint statistics: counts, summary,
                                latest completed/failed/restored, bounded
                                per-checkpoint history
                                (CheckpointingStatisticsHandler analogue)
  GET  /jobs/<id>/checkpoints/<cid>
                              → one retained checkpoint's record
  GET  /jobs/<id>/exceptions  → bounded exception history + recovery
                                timeline (JobExceptionsHandler analogue)
  GET  /jobs/<id>/autoscaler  → autoscaler decision log + rescale counters
                                (scheduler/ — signals seen, action taken,
                                outcome, rescale durations)
  GET  /jobs/<id>/device      → device-plane observability: compile/
                                recompile counters + bounded event ring
                                with cause attribution, per-operator
                                roofline utilization and phase counters,
                                key-skew telemetry, profiler capture
                                surface (metrics/device_stats.py)
  GET  /metrics               → Prometheus text exposition (all jobs)
  POST /jars/run              → {"module": "/path/script.py", "entry": "main"}
                                application-mode submission: the script builds
                                an env and returns it (or calls execute_async)

Implementation: stdlib http.server (threaded), JSON payloads.

Distributed bridge: constructed with `jm_gateway` (an RPC gateway to a
JobManagerEndpoint), the same routes ALSO serve that cluster's jobs — the
JM aggregates the metric snapshots and trace spans its TaskExecutors ship
on the authenticated RPC plane, and this server renders them as JSON,
OTLP/JSON traces, and Prometheus text (per-shard samples labeled
{job,shard}).
"""

from __future__ import annotations

import importlib.util
import json
import logging
import threading
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from flink_tpu.metrics.registry import (
    merge_prometheus_text,
    prometheus_text,
    prometheus_text_from_snapshot,
)
from flink_tpu.metrics.task_io import backpressure_level
from flink_tpu.runtime.minicluster import JobStatus, MiniCluster


from flink_tpu.runtime.web_dashboard import DASHBOARD_HTML


class _Handler(BaseHTTPRequestHandler):
    cluster: MiniCluster = None  # set by RestServer
    jm = None                    # optional JobManagerEndpoint RPC gateway

    # -- plumbing ---------------------------------------------------------
    def log_message(self, fmt, *args):  # quiet
        pass

    def _send(self, code: int, body: bytes, content_type="application/json"):
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _json(self, code: int, obj):
        self._send(code, json.dumps(obj).encode())

    def _read_body(self) -> dict:
        length = int(self.headers.get("Content-Length", 0))
        if length == 0:
            return {}
        return json.loads(self.rfile.read(length))

    def _job(self, job_id: str):
        return self.cluster.jobs.get(job_id)

    # -- GET --------------------------------------------------------------
    auth_token: Optional[str] = None

    def _authorized(self) -> bool:
        if self.auth_token is None:
            return True
        from flink_tpu.security import bearer_header_equal

        if bearer_header_equal(self.headers.get("Authorization", ""),
                               self.auth_token):
            return True
        self._json(401, {"error": "missing or invalid bearer token"})
        return False

    def do_GET(self):
        if not self._authorized():
            return
        parts = [p for p in self.path.split("?")[0].split("/") if p]
        if not parts:
            # the live dashboard (web_dashboard.py) polls the JSON routes
            return self._send(200, DASHBOARD_HTML.encode(), "text/html")
        if parts == ["overview"]:
            by_status = {}
            for c in self.cluster.jobs.values():
                by_status[c.status().value] = by_status.get(c.status().value, 0) + 1
            return self._json(200, {"jobs": len(self.cluster.jobs), "by_status": by_status})
        if parts == ["jobs"]:
            jobs = [
                {"id": c.job_id, "name": c.job_name, "status": c.status().value}
                for c in self.cluster.jobs.values()
            ]
            if self.jm is not None:
                try:
                    jobs.extend(self.jm.list_jobs())
                except Exception as e:   # unreachable JM: local jobs still
                    logging.getLogger(__name__).debug(   # serve
                        "jm list_jobs unavailable: %r", e)
            return self._json(200, {"jobs": jobs})
        if parts == ["metrics"]:
            texts = []
            for c in self.cluster.jobs.values():
                if hasattr(c, "metrics"):
                    # every sample labeled by job id: two jobs share family
                    # names, and unlabeled duplicates are invalid exposition
                    texts.append(prometheus_text(c.metrics.all_metrics(),
                                                 labels={"job": c.job_id}))
            if self.jm is not None:
                # distributed jobs: per-shard snapshots the TMs shipped over
                # RPC, labeled so Prometheus keeps shards distinguishable
                try:
                    for j in self.jm.list_jobs():
                        jm_metrics = self.jm.job_metrics(j["id"])
                        for shard, snap in jm_metrics["per_shard"].items():
                            texts.append(prometheus_text_from_snapshot(
                                snap, labels={"job": j["id"], "shard": shard}))
                        # JM-side control-plane gauges (checkpoint stats,
                        # restart/downtime, watermark skew) live on the
                        # coordinator, not any shard — own labeled snapshot
                        jm_side = jm_metrics.get("jm") or {}
                        if jm_side:
                            texts.append(prometheus_text_from_snapshot(
                                jm_side, labels={"job": j["id"]}))
                except Exception as e:   # unreachable JM: local exposition
                    logging.getLogger(__name__).debug(   # still serves
                        "jm metrics unavailable: %r", e)
            # one TYPE line per family, samples grouped — naive
            # concatenation is invalid exposition once two jobs/shards
            # share a family name
            text = merge_prometheus_text(texts) if texts else ""
            return self._send(200, text.encode(), "text/plain; version=0.0.4")
        if parts == ["flamegraph"]:
            # on-demand thread sampling (JobVertexFlameGraphHandler analogue);
            # ?duration=0.5&filter=task samples live process threads
            from urllib.parse import parse_qs, urlparse

            from flink_tpu.metrics.flamegraph import flame_graph

            q = parse_qs(urlparse(self.path).query)
            try:
                duration = min(max(float(q.get("duration", ["0.3"])[0]), 0.01), 10.0)
                hz = min(max(float(q.get("hz", ["50"])[0]), 1.0), 1000.0)
            except ValueError:
                return self._json(400, {"error": "duration/hz must be numbers"})
            return self._json(200, flame_graph(
                duration_s=duration, hz=hz,
                thread_filter=(q.get("filter", [None])[0]),
            ))
        if len(parts) >= 2 and parts[0] == "jobs":
            client = self._job(parts[1])
            if client is None:
                if self.jm is not None:
                    return self._jm_job_routes(parts)
                return self._json(404, {"error": f"unknown job {parts[1]}"})
            if len(parts) == 2:
                detail = {
                    "id": client.job_id,
                    "name": client.job_name,
                    "status": client.status().value,
                    "records_in": client.records_in,
                    "num_restarts": client.num_restarts,
                    "num_checkpoints": getattr(client, "num_checkpoints", 0),
                    "trace_id": getattr(client, "trace_id", None),
                    "error": repr(client.error) if client.error else None,
                }
                # SQL front-door path selection: jobs whose window steps
                # came from the SQL planner carry job.sqlFusedSelected
                # (1 = fused superscan, 0 = interpreted-style execution);
                # non-SQL jobs simply omit the field
                if hasattr(client, "metrics"):
                    g = client.metrics.all_metrics().get(
                        "job.sqlFusedSelected")
                    if g is not None:
                        detail["sqlFusedSelected"] = g.value()
                return self._json(200, detail)
            if parts[2] == "vertices" and len(parts) == 5 \
                    and parts[4] == "backpressure":
                return self._backpressure(client, parts[3])
            if parts[2] == "traces":
                # OTLP/JSON resourceSpans (OpenTelemetryTraceReporter SPI)
                if not hasattr(client, "otel"):
                    return self._json(200, {"resourceSpans": []})
                return self._json(200, client.otel.payload())
            if parts[2] == "latency" and len(parts) == 3:
                # emission-latency plane: event-time tail + stall attribution
                from flink_tpu.metrics.emission_latency import (
                    build_latency_report,
                )

                if hasattr(client, "latency_report"):
                    return self._json(200, _jsonable(client.latency_report()))
                return self._json(200, _jsonable(build_latency_report({}, [])))
            if parts[2] == "history" and len(parts) == 3:
                # metrics history plane: bounded per-key time-series rings
                # sampled on the job's processing-time tick
                metric, since = self._history_query()
                if since is None and "since" in self.path:
                    return self._json(400, {"error": "since must be a number"})
                if hasattr(client, "history_report"):
                    return self._json(200, _jsonable(
                        client.history_report(metric=metric, since=since)))
                return self._json(200, {"enabled": False, "series": {},
                                        "sample_count": 0})
            if parts[2] == "doctor" and len(parts) == 3:
                # job doctor: ranked bottleneck attribution over the recent
                # history window joined with the span stream
                if hasattr(client, "doctor_report"):
                    return self._json(200, _jsonable(client.doctor_report()))
                return self._json(200, {"verdict": "unknown", "score": 0.0,
                                        "diagnoses": []})
            if parts[2] == "metrics":
                if not hasattr(client, "metrics"):
                    return self._json(200, {})
                out = {}
                for k, m in client.metrics.all_metrics().items():
                    v = m.value()
                    out[k] = v if isinstance(v, (int, float, dict)) else str(v)
                return self._json(200, out)
            if parts[2] == "checkpoints":
                from flink_tpu.metrics.checkpoint_stats import (
                    empty_checkpoints_payload,
                )

                stats = getattr(client, "checkpoint_stats", None)
                if len(parts) == 3:
                    return self._json(200, _jsonable(
                        stats.payload() if stats is not None
                        else empty_checkpoints_payload()))
                if len(parts) == 4:
                    if not parts[3].isdigit():
                        return self._json(
                            400, {"error": "checkpoint id must be an integer"})
                    rec = stats.checkpoint(int(parts[3])) if stats else None
                    if rec is None:
                        return self._json(404, {
                            "error": f"no retained stats for checkpoint "
                                     f"{parts[3]}"})
                    return self._json(200, _jsonable(rec))
            if parts[2] == "exceptions" and len(parts) == 3:
                from flink_tpu.metrics.checkpoint_stats import (
                    empty_exceptions_payload,
                )

                hist = getattr(client, "exceptions", None)
                return self._json(200, _jsonable(
                    hist.payload() if hist is not None
                    else empty_exceptions_payload()))
            if parts[2] == "autoscaler" and len(parts) == 3:
                # decision log + rescale counters (scheduler/); MiniCluster
                # jobs run observe-only, so decisions carry outcome
                # 'observe-only' and parallelism is the single in-process task
                from flink_tpu.scheduler import empty_autoscaler_payload

                auto = getattr(client, "autoscaler", None)
                payload = (auto.payload(client.job_id) if auto is not None
                           else empty_autoscaler_payload())
                payload.setdefault("parallelism", 1)
                return self._json(200, _jsonable(payload))
            if parts[2] == "device" and len(parts) == 3:
                # device plane (metrics/device_stats.py): compile events,
                # roofline/phase attribution, key skew, profiler captures
                from flink_tpu.metrics.device_stats import (
                    empty_device_payload,
                )

                rt = getattr(client, "_runtime", None)
                return self._json(200, _jsonable(
                    rt.device_snapshot() if rt is not None
                    else empty_device_payload()))
            if parts[2] == "state" and len(parts) == 4:
                # queryable state (S13): /jobs/<id>/state/<uid>?key=K
                from urllib.parse import parse_qs, urlparse

                qs = parse_qs(urlparse(self.path).query)
                if "key" not in qs:
                    return self._json(400, {"error": "key query param required"})
                raw = qs["key"][0]
                key: object = int(raw) if raw.lstrip("-").isdigit() else raw
                try:
                    result = client.query_state(parts[3], key)
                except KeyError as e:
                    return self._json(404, {"error": str(e)})
                except RuntimeError as e:
                    return self._json(409, {"error": str(e)})
                return self._json(200, _jsonable(result))
        self._json(404, {"error": f"no route {self.path}"})

    # -- observability helpers --------------------------------------------
    def _history_query(self):
        """Parse ?metric=&since= for the history routes; `since` is epoch
        ms (None when absent or non-numeric — the caller 400s on the
        latter when the param was present)."""
        from urllib.parse import parse_qs, urlparse

        qs = parse_qs(urlparse(self.path).query)
        metric = qs.get("metric", [None])[0]
        since = None
        raw = qs.get("since", [None])[0]
        if raw is not None:
            try:
                since = float(raw)
            except ValueError:
                since = None
        return metric, since

    def _backpressure(self, client, uid: str):
        """Backpressure view of an in-process (MiniCluster) job: the job
        runs as ONE task, so the task-level busy/idle/backPressured ratios
        are its single subtask's sample; vertex-scoped metrics (latency
        histogram, device time, state bytes) ride along for the vertex."""
        if not hasattr(client, "metrics"):
            return self._json(200, {"status": "deprecated", "subtasks": []})
        snap = {}
        for k, m in client.metrics.all_metrics().items():
            try:
                snap[k] = m.value()
            except Exception:
                continue
        bp = float(snap.get("job.backPressuredTimeRatio", 0.0) or 0.0)
        busy = float(snap.get("job.busyTimeRatio", 0.0) or 0.0)
        idle = float(snap.get("job.idleTimeRatio", 0.0) or 0.0)
        prefix = f"job.operator.{uid}."
        vertex_metrics = {
            k[len(prefix):]: v for k, v in snap.items() if k.startswith(prefix)
        }
        return self._json(200, _jsonable({
            "status": "ok",
            "vertex": uid,
            "backpressureLevel": backpressure_level(bp),
            "busyRatio": busy,
            "idleRatio": idle,
            "backPressuredRatio": bp,
            "subtasks": [{
                "subtask": 0,
                "backpressureLevel": backpressure_level(bp),
                "backPressuredRatio": bp,
                "busyRatio": busy,
                "idleRatio": idle,
            }],
            "metrics": vertex_metrics,
        }))

    def _jm_job_routes(self, parts):
        """Serve a distributed job from the bridged JobManagerEndpoint (the
        aggregates its TaskExecutors shipped over the RPC plane)."""
        job_id = parts[1]
        try:
            if len(parts) == 2:
                st = self.jm.job_status(job_id)
                return self._json(200, {
                    "id": job_id, "name": st["name"], "status": st["status"],
                    "num_restarts": st["restarts"],
                    "trace_id": st.get("trace_id"),
                    "checkpoints": st["checkpoints"],
                    "error": st.get("failure"),
                })
            if parts[2] == "metrics" and len(parts) == 3:
                return self._json(200, _jsonable(self.jm.job_metrics(job_id)))
            if parts[2] == "traces" and len(parts) == 3:
                from flink_tpu.metrics.otel import span_to_otlp, spans_to_otlp
                from flink_tpu.metrics.traces import Span

                enc = [span_to_otlp(Span.from_dict(d))
                       for d in self.jm.job_spans(job_id)]
                return self._json(200, spans_to_otlp(enc, "flink-tpu"))
            if parts[2] == "latency" and len(parts) == 3:
                return self._json(200, _jsonable(
                    self.jm.job_latency(job_id)))
            if parts[2] == "history" and len(parts) == 3:
                metric, since = self._history_query()
                return self._json(200, _jsonable(
                    self.jm.job_history(job_id, metric=metric, since=since)))
            if parts[2] == "doctor" and len(parts) == 3:
                return self._json(200, _jsonable(
                    self.jm.job_doctor(job_id)))
            if parts[2] == "vertices" and len(parts) == 5 \
                    and parts[4] == "backpressure":
                return self._json(200, _jsonable(
                    self.jm.job_backpressure(job_id)))
            if parts[2] == "checkpoints" and len(parts) == 3:
                return self._json(200, _jsonable(
                    self.jm.job_checkpoints(job_id)))
            if parts[2] == "checkpoints" and len(parts) == 4:
                if not parts[3].isdigit():
                    return self._json(
                        400, {"error": "checkpoint id must be an integer"})
                return self._json(200, _jsonable(
                    self.jm.job_checkpoint(job_id, int(parts[3]))))
            if parts[2] == "exceptions" and len(parts) == 3:
                return self._json(200, _jsonable(
                    self.jm.job_exceptions(job_id)))
            if parts[2] == "autoscaler" and len(parts) == 3:
                return self._json(200, _jsonable(
                    self.jm.job_autoscaler(job_id)))
            if parts[2] == "device" and len(parts) == 3:
                return self._json(200, _jsonable(
                    self.jm.job_device(job_id)))
        except Exception as e:  # noqa: BLE001 — JM lookup failures -> 404
            return self._json(404, {"error": repr(e)})
        return self._json(404, {"error": f"no route {self.path}"})

    # -- POST/PATCH -------------------------------------------------------
    def do_POST(self):
        if not self._authorized():
            return
        parts = [p for p in self.path.split("?")[0].split("/") if p]
        if parts == ["jars", "run"]:
            body = self._read_body()
            try:
                client = _run_application(self.cluster, body["module"], body.get("entry", "main"))
            except Exception as e:  # noqa: BLE001 — surface to caller
                return self._json(400, {"error": repr(e)})
            return self._json(200, {"jobid": client.job_id})
        if len(parts) == 3 and parts[0] == "jobs":
            client = self._job(parts[1])
            if client is None:
                return self._json(404, {"error": f"unknown job {parts[1]}"})
            if parts[2] == "cancel":
                client.cancel()
                return self._json(202, {"status": "cancelling"})
            if parts[2] == "savepoints":
                body = self._read_body()
                target = body.get("target-directory")
                if not target:
                    return self._json(400, {"error": "target-directory required"})
                try:
                    path = client.trigger_savepoint(target)
                except TimeoutError as e:
                    return self._json(409, {"error": str(e)})
                return self._json(200, {"location": path})
        self._json(404, {"error": f"no route {self.path}"})

    do_PATCH = do_POST


# single-sourced with the SQL gateway (utils/arrays.jsonable)
from flink_tpu.utils.arrays import jsonable as _jsonable  # noqa: E402


def _run_application(cluster: MiniCluster, module_path: str, entry: str):
    """Application-mode submission: import the script, call its entry — the
    entry must return a JobClient (via env.execute_async()) or a
    StreamExecutionEnvironment (which we then submit)."""
    spec = importlib.util.spec_from_file_location(f"flink_tpu_app_{uuid.uuid4().hex}", module_path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    fn = getattr(mod, entry)
    result = fn()
    from flink_tpu.api.datastream import StreamExecutionEnvironment
    from flink_tpu.graph.transformation import plan
    from flink_tpu.runtime.minicluster import JobClient

    if isinstance(result, JobClient):
        cluster.jobs.setdefault(result.job_id, result)
        return result
    if isinstance(result, StreamExecutionEnvironment):
        if len(result._sinks) != 1:
            raise RuntimeError("application must define exactly one sink")
        # iteration tails live in env._roots (reachable only via close_with)
        roots = result._sinks[:1] + getattr(result, "_roots", [])
        return cluster.submit(plan(roots), result.config)
    raise TypeError(f"{entry}() must return JobClient or StreamExecutionEnvironment")


class RestServer:
    """Threaded REST server bound to a MiniCluster (WebMonitorEndpoint)."""

    def __init__(self, cluster: Optional[MiniCluster] = None, port: int = 0,
                 auth_token: Optional[str] = None, config=None,
                 jm_gateway=None):
        """auth_token: when set, every request must carry
        `Authorization: Bearer <token>` (the reference's SSL/Kerberos
        plumbing is deployment-level — TLS terminates at the ingress in the
        K8s deployment, this guards the API itself).

        With `config` given and `security.rest.auth.enabled: true`, the
        token derives from the SAME cluster secret that authenticates the
        internal planes (flink_tpu.security.rest_bearer_token) — one secret
        to provision for the whole cluster."""
        self.cluster = cluster or MiniCluster.get_shared()
        if auth_token is None and config is not None:
            from flink_tpu.config import SecurityOptions
            from flink_tpu.security import SecurityConfig, rest_bearer_token

            if config.get(SecurityOptions.REST_AUTH_ENABLED):
                # explicit security.transport.* settings win; otherwise the
                # token derives from the bound cluster's own resolved
                # identity so REST and the internal planes share ONE secret
                explicit = any(config.contains(o) for o in (
                    SecurityOptions.TRANSPORT_ENABLED,
                    SecurityOptions.TRANSPORT_SECRET,
                    SecurityOptions.TRANSPORT_SECRET_FILE,
                ))
                sec = (SecurityConfig.resolve(config) if explicit
                       else self.cluster.security)
                if not sec.enabled:
                    raise ValueError(
                        "security.rest.auth.enabled requires "
                        "security.transport.enabled (the bearer token "
                        "derives from the transport secret)"
                    )
                auth_token = rest_bearer_token(sec)
        handler = type("BoundHandler", (_Handler,),
                       {"cluster": self.cluster, "auth_token": auth_token,
                        "jm": jm_gateway})
        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), handler)
        self.port = self._httpd.server_port
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def start(self) -> "RestServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="rest-server", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
