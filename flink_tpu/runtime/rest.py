"""REST endpoint + minimal web dashboard.

Capability parity with the reference's web monitor / REST stack
(runtime/rest handlers, WebMonitorEndpoint.java:224, RestClusterClient
submission, the Angular dashboard O5 — here a dependency-free single-page
view). Endpoints:

  GET  /                      → HTML dashboard (jobs + metrics, auto-refresh)
  GET  /overview              → cluster overview JSON
  GET  /jobs                  → [{id, name, status}]
  GET  /jobs/<id>             → job detail JSON
  PATCH/POST /jobs/<id>/cancel→ cancel
  POST /jobs/<id>/savepoints  → {"target-directory": dir} → trigger savepoint
  GET  /jobs/<id>/metrics     → metrics JSON
  GET  /metrics               → Prometheus text exposition (all jobs)
  POST /jars/run              → {"module": "/path/script.py", "entry": "main"}
                                application-mode submission: the script builds
                                an env and returns it (or calls execute_async)

Implementation: stdlib http.server (threaded), JSON payloads.
"""

from __future__ import annotations

import importlib.util
import json
import threading
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from flink_tpu.metrics.registry import prometheus_text
from flink_tpu.runtime.minicluster import JobStatus, MiniCluster


from flink_tpu.runtime.web_dashboard import DASHBOARD_HTML


class _Handler(BaseHTTPRequestHandler):
    cluster: MiniCluster = None  # set by RestServer

    # -- plumbing ---------------------------------------------------------
    def log_message(self, fmt, *args):  # quiet
        pass

    def _send(self, code: int, body: bytes, content_type="application/json"):
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _json(self, code: int, obj):
        self._send(code, json.dumps(obj).encode())

    def _read_body(self) -> dict:
        length = int(self.headers.get("Content-Length", 0))
        if length == 0:
            return {}
        return json.loads(self.rfile.read(length))

    def _job(self, job_id: str):
        return self.cluster.jobs.get(job_id)

    # -- GET --------------------------------------------------------------
    auth_token: Optional[str] = None

    def _authorized(self) -> bool:
        if self.auth_token is None:
            return True
        import hmac as _hmac

        got = self.headers.get("Authorization", "")
        if _hmac.compare_digest(got, f"Bearer {self.auth_token}"):
            return True
        self._json(401, {"error": "missing or invalid bearer token"})
        return False

    def do_GET(self):
        if not self._authorized():
            return
        parts = [p for p in self.path.split("?")[0].split("/") if p]
        if not parts:
            # the live dashboard (web_dashboard.py) polls the JSON routes
            return self._send(200, DASHBOARD_HTML.encode(), "text/html")
        if parts == ["overview"]:
            by_status = {}
            for c in self.cluster.jobs.values():
                by_status[c.status().value] = by_status.get(c.status().value, 0) + 1
            return self._json(200, {"jobs": len(self.cluster.jobs), "by_status": by_status})
        if parts == ["jobs"]:
            return self._json(
                200,
                {
                    "jobs": [
                        {"id": c.job_id, "name": c.job_name, "status": c.status().value}
                        for c in self.cluster.jobs.values()
                    ]
                },
            )
        if parts == ["metrics"]:
            text = ""
            for c in self.cluster.jobs.values():
                if hasattr(c, "metrics"):
                    text += prometheus_text(c.metrics.all_metrics())
            return self._send(200, text.encode(), "text/plain; version=0.0.4")
        if parts == ["flamegraph"]:
            # on-demand thread sampling (JobVertexFlameGraphHandler analogue);
            # ?duration=0.5&filter=task samples live process threads
            from urllib.parse import parse_qs, urlparse

            from flink_tpu.metrics.flamegraph import flame_graph

            q = parse_qs(urlparse(self.path).query)
            try:
                duration = min(max(float(q.get("duration", ["0.3"])[0]), 0.01), 10.0)
                hz = min(max(float(q.get("hz", ["50"])[0]), 1.0), 1000.0)
            except ValueError:
                return self._json(400, {"error": "duration/hz must be numbers"})
            return self._json(200, flame_graph(
                duration_s=duration, hz=hz,
                thread_filter=(q.get("filter", [None])[0]),
            ))
        if len(parts) >= 2 and parts[0] == "jobs":
            client = self._job(parts[1])
            if client is None:
                return self._json(404, {"error": f"unknown job {parts[1]}"})
            if len(parts) == 2:
                return self._json(
                    200,
                    {
                        "id": client.job_id,
                        "name": client.job_name,
                        "status": client.status().value,
                        "records_in": client.records_in,
                        "num_restarts": client.num_restarts,
                        "num_checkpoints": getattr(client, "num_checkpoints", 0),
                        "error": repr(client.error) if client.error else None,
                    },
                )
            if parts[2] == "traces":
                # OTLP/JSON resourceSpans (OpenTelemetryTraceReporter SPI)
                if not hasattr(client, "otel"):
                    return self._json(200, {"resourceSpans": []})
                return self._json(200, client.otel.payload())
            if parts[2] == "metrics":
                if not hasattr(client, "metrics"):
                    return self._json(200, {})
                out = {}
                for k, m in client.metrics.all_metrics().items():
                    v = m.value()
                    out[k] = v if isinstance(v, (int, float, dict)) else str(v)
                return self._json(200, out)
            if parts[2] == "state" and len(parts) == 4:
                # queryable state (S13): /jobs/<id>/state/<uid>?key=K
                from urllib.parse import parse_qs, urlparse

                qs = parse_qs(urlparse(self.path).query)
                if "key" not in qs:
                    return self._json(400, {"error": "key query param required"})
                raw = qs["key"][0]
                key: object = int(raw) if raw.lstrip("-").isdigit() else raw
                try:
                    result = client.query_state(parts[3], key)
                except KeyError as e:
                    return self._json(404, {"error": str(e)})
                except RuntimeError as e:
                    return self._json(409, {"error": str(e)})
                return self._json(200, _jsonable(result))
        self._json(404, {"error": f"no route {self.path}"})

    # -- POST/PATCH -------------------------------------------------------
    def do_POST(self):
        if not self._authorized():
            return
        parts = [p for p in self.path.split("?")[0].split("/") if p]
        if parts == ["jars", "run"]:
            body = self._read_body()
            try:
                client = _run_application(self.cluster, body["module"], body.get("entry", "main"))
            except Exception as e:  # noqa: BLE001 — surface to caller
                return self._json(400, {"error": repr(e)})
            return self._json(200, {"jobid": client.job_id})
        if len(parts) == 3 and parts[0] == "jobs":
            client = self._job(parts[1])
            if client is None:
                return self._json(404, {"error": f"unknown job {parts[1]}"})
            if parts[2] == "cancel":
                client.cancel()
                return self._json(202, {"status": "cancelling"})
            if parts[2] == "savepoints":
                body = self._read_body()
                target = body.get("target-directory")
                if not target:
                    return self._json(400, {"error": "target-directory required"})
                try:
                    path = client.trigger_savepoint(target)
                except TimeoutError as e:
                    return self._json(409, {"error": str(e)})
                return self._json(200, {"location": path})
        self._json(404, {"error": f"no route {self.path}"})

    do_PATCH = do_POST


def _jsonable(obj):
    """Best-effort JSON coercion (int dict keys -> str, numpy scalars)."""
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if hasattr(obj, "item"):
        return obj.item()
    if isinstance(obj, (int, float, str, bool)) or obj is None:
        return obj
    return repr(obj)


def _run_application(cluster: MiniCluster, module_path: str, entry: str):
    """Application-mode submission: import the script, call its entry — the
    entry must return a JobClient (via env.execute_async()) or a
    StreamExecutionEnvironment (which we then submit)."""
    spec = importlib.util.spec_from_file_location(f"flink_tpu_app_{uuid.uuid4().hex}", module_path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    fn = getattr(mod, entry)
    result = fn()
    from flink_tpu.api.datastream import StreamExecutionEnvironment
    from flink_tpu.graph.transformation import plan
    from flink_tpu.runtime.minicluster import JobClient

    if isinstance(result, JobClient):
        cluster.jobs.setdefault(result.job_id, result)
        return result
    if isinstance(result, StreamExecutionEnvironment):
        if len(result._sinks) != 1:
            raise RuntimeError("application must define exactly one sink")
        # iteration tails live in env._roots (reachable only via close_with)
        roots = result._sinks[:1] + getattr(result, "_roots", [])
        return cluster.submit(plan(roots), result.config)
    raise TypeError(f"{entry}() must return JobClient or StreamExecutionEnvironment")


class RestServer:
    """Threaded REST server bound to a MiniCluster (WebMonitorEndpoint)."""

    def __init__(self, cluster: Optional[MiniCluster] = None, port: int = 0,
                 auth_token: Optional[str] = None, config=None):
        """auth_token: when set, every request must carry
        `Authorization: Bearer <token>` (the reference's SSL/Kerberos
        plumbing is deployment-level — TLS terminates at the ingress in the
        K8s deployment, this guards the API itself).

        With `config` given and `security.rest.auth.enabled: true`, the
        token derives from the SAME cluster secret that authenticates the
        internal planes (flink_tpu.security.rest_bearer_token) — one secret
        to provision for the whole cluster."""
        self.cluster = cluster or MiniCluster.get_shared()
        if auth_token is None and config is not None:
            from flink_tpu.config import SecurityOptions
            from flink_tpu.security import SecurityConfig, rest_bearer_token

            if config.get(SecurityOptions.REST_AUTH_ENABLED):
                # explicit security.transport.* settings win; otherwise the
                # token derives from the bound cluster's own resolved
                # identity so REST and the internal planes share ONE secret
                explicit = any(config.contains(o) for o in (
                    SecurityOptions.TRANSPORT_ENABLED,
                    SecurityOptions.TRANSPORT_SECRET,
                    SecurityOptions.TRANSPORT_SECRET_FILE,
                ))
                sec = (SecurityConfig.resolve(config) if explicit
                       else self.cluster.security)
                if not sec.enabled:
                    raise ValueError(
                        "security.rest.auth.enabled requires "
                        "security.transport.enabled (the bearer token "
                        "derives from the transport secret)"
                    )
                auth_token = rest_bearer_token(sec)
        handler = type("BoundHandler", (_Handler,),
                       {"cluster": self.cluster, "auth_token": auth_token})
        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), handler)
        self.port = self._httpd.server_port
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def start(self) -> "RestServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="rest-server", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
