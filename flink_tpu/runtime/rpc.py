"""Control-plane RPC: location-transparent endpoint calls over TCP.

The analogue of the reference's actor RPC (flink-rpc-akka/.../PekkoRpcService.java:86,
PekkoInvocationHandler.java:71): named endpoints expose public methods;
remote callers hold a gateway proxy whose attribute calls serialize the
invocation, ship it over a framed TCP connection, and return the result (or
re-raise the remote exception). Each endpoint executes ALL invocations on
one dedicated main thread — the single-threaded actor discipline that the
reference enforces with MainThreadValidatorUtil (MainThreadValidatorUtil.java:35)
— so endpoint state needs no locks.

Wire format: 4-byte big-endian length + pickle of
(endpoint, method, args, kwargs) / (ok, payload). This is the DCN control
plane; the data plane (record batches, credits) lives in dataplane.py.
"""

from __future__ import annotations

import pickle
import socket
import socketserver
import struct
import threading
import traceback
import uuid
from concurrent.futures import Future
from typing import Any, Callable, Dict, Optional


def _send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(struct.pack(">I", len(payload)) + payload)


def _recv_frame(sock: socket.socket) -> Optional[bytes]:
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            return None
        hdr += chunk
    (n,) = struct.unpack(">I", hdr)
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            return None
        buf += chunk
    return bytes(buf)


class RpcEndpoint:
    """Base class: public methods become remotely callable; all invocations
    (local or remote) run on the endpoint's single main thread."""

    def __init__(self, name: Optional[str] = None):
        self.name = name or f"{type(self).__name__}-{uuid.uuid4().hex[:8]}"
        self._inbox: "list" = []
        self._cv = threading.Condition()
        self._running = True
        self._main_thread = threading.Thread(
            target=self._main_loop, name=f"rpc-main-{self.name}", daemon=True
        )
        self._main_thread.start()

    # -- main-thread discipline --------------------------------------------
    def validate_main_thread(self) -> None:
        assert threading.current_thread() is self._main_thread, (
            f"endpoint {self.name} state touched off the main thread"
        )

    def run_in_main_thread(self, fn: Callable, *args, **kwargs) -> Future:
        f: Future = Future()
        with self._cv:
            if not self._running:
                f.set_exception(RuntimeError(f"endpoint {self.name} stopped"))
                return f
            self._inbox.append((fn, args, kwargs, f))
            self._cv.notify()
        return f

    def _main_loop(self) -> None:
        while True:
            with self._cv:
                while self._running and not self._inbox:
                    self._cv.wait(timeout=0.2)
                if not self._running and not self._inbox:
                    return
                fn, args, kwargs, fut = self._inbox.pop(0)
            try:
                fut.set_result(fn(*args, **kwargs))
            except BaseException as e:  # noqa: BLE001 — forwarded to caller
                fut.set_exception(e)

    def stop(self) -> None:
        with self._cv:
            self._running = False
            self._cv.notify_all()

    # called by the server
    def _invoke(self, method: str, args, kwargs):
        fn = getattr(self, method, None)
        if fn is None or method.startswith("_"):
            raise AttributeError(f"{self.name} has no rpc method {method!r}")
        return self.run_in_main_thread(fn, *args, **kwargs)


class RpcService:
    """Hosts endpoints on one TCP port; builds gateways to remote services."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._endpoints: Dict[str, RpcEndpoint] = {}
        self._lock = threading.Lock()
        service = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    frame = _recv_frame(self.request)
                    if frame is None:
                        return
                    try:
                        endpoint, method, args, kwargs = pickle.loads(frame)
                        with service._lock:
                            ep = service._endpoints.get(endpoint)
                        if ep is None:
                            raise LookupError(f"no endpoint {endpoint!r}")
                        result = ep._invoke(method, args, kwargs).result()
                        reply = (True, result)
                    except BaseException as e:  # noqa: BLE001 — shipped back
                        reply = (False, (type(e).__name__, str(e), traceback.format_exc()))
                    try:
                        _send_frame(self.request, pickle.dumps(reply))
                    except OSError:
                        return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread = threading.Thread(
            target=self._server.serve_forever, name=f"rpc-srv-{self.port}", daemon=True
        )
        self._thread.start()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def register(self, endpoint: RpcEndpoint) -> None:
        with self._lock:
            self._endpoints[endpoint.name] = endpoint

    def unregister(self, name: str) -> None:
        with self._lock:
            self._endpoints.pop(name, None)

    def gateway(self, address: str, endpoint: str, timeout: float = 10.0) -> "RpcGateway":
        return RpcGateway(address, endpoint, timeout)

    def stop(self) -> None:
        with self._lock:
            eps = list(self._endpoints.values())
        for ep in eps:
            ep.stop()
        self._server.shutdown()
        self._server.server_close()


class RemoteRpcError(RuntimeError):
    def __init__(self, exc_type: str, message: str, remote_traceback: str):
        super().__init__(f"{exc_type}: {message}")
        self.exc_type = exc_type
        self.remote_traceback = remote_traceback


class RpcGateway:
    """Dynamic proxy: gateway.method(*a, **kw) → remote invocation.

    One TCP connection per gateway, serialized calls (matching the
    per-endpoint ordering guarantee of the reference's actor mailbox)."""

    def __init__(self, address: str, endpoint: str, timeout: float = 10.0):
        self._address = address
        self._endpoint = endpoint
        self._timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()

    def _connect(self) -> socket.socket:
        if self._sock is None:
            host, port = self._address.rsplit(":", 1)
            sock = socket.create_connection((host, int(port)), timeout=self._timeout)
            # the timeout guards CONNECT only: leaving it armed would make
            # any invocation whose reply takes > timeout raise mid-frame and
            # poison the connection for every later call on this gateway
            sock.settimeout(None)
            self._sock = sock
        return self._sock

    def close(self) -> None:
        with self._lock:
            self._close_locked()

    def _close_locked(self) -> None:
        """Drop the cached socket; caller must hold self._lock (the lock is
        non-reentrant, so call() error paths use this instead of close())."""
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def __getattr__(self, method: str):
        if method.startswith("_"):
            raise AttributeError(method)

        def call(*args, **kwargs):
            with self._lock:
                sock = self._connect()
                try:
                    _send_frame(sock, pickle.dumps((self._endpoint, method, args, kwargs)))
                    frame = _recv_frame(sock)
                except OSError:
                    self._close_locked()
                    raise
                if frame is None:
                    self._close_locked()
                    raise ConnectionError(f"rpc connection to {self._address} closed")
            ok, payload = pickle.loads(frame)
            if ok:
                return payload
            raise RemoteRpcError(*payload)

        return call

    def call_async(self, method: str, *args, **kwargs) -> Future:
        f: Future = Future()

        def run():
            try:
                f.set_result(getattr(self, method)(*args, **kwargs))
            except BaseException as e:  # noqa: BLE001
                f.set_exception(e)

        threading.Thread(target=run, daemon=True).start()
        return f
