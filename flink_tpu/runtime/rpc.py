"""Control-plane RPC: location-transparent endpoint calls over TCP.

The analogue of the reference's actor RPC (flink-rpc-akka/.../PekkoRpcService.java:86,
PekkoInvocationHandler.java:71): named endpoints expose public methods;
remote callers hold a gateway proxy whose attribute calls serialize the
invocation, ship it over a framed TCP connection, and return the result (or
re-raise the remote exception). Each endpoint executes ALL invocations on
one dedicated main thread — the single-threaded actor discipline that the
reference enforces with MainThreadValidatorUtil (MainThreadValidatorUtil.java:35)
— so endpoint state needs no locks.

Wire format (flink_tpu/security): connection handshake (version +
cluster-id + nonce challenge against the cluster secret), then 4-byte
big-endian length + HMAC-signed frame of the restricted-pickled
(endpoint, method, args, kwargs[, trace_id]) / (ok, payload) — the
optional fifth element is the caller's trace context (W3C-traceparent
analogue; see trace_context/current_trace_id). Frames are MAC-verified
BEFORE deserialization and deserialized through the security allowlist;
`security.transport.enabled: false` restores the legacy plain-pickle wire.
This is the DCN control plane; the data plane (record batches, credits)
lives in dataplane.py.
"""

from __future__ import annotations

import contextlib
import socket
import socketserver
import threading
import traceback
import uuid
from concurrent.futures import Future
from typing import Any, Callable, Dict, Optional


# ---------------------------------------------------------------------------
# trace-context propagation (W3C-traceparent-lite over the RPC frame)
# ---------------------------------------------------------------------------

_trace_ctx = threading.local()


def current_trace_id() -> Optional[str]:
    """Trace id of the RPC invocation currently executing on this endpoint
    main thread (None outside an invocation or when the caller sent none).
    The observability analogue of reading the traceparent header."""
    return getattr(_trace_ctx, "incoming", None)


@contextlib.contextmanager
def trace_context(trace_id: Optional[str]):
    """Attach `trace_id` to every RPC this thread issues inside the block:
    the gateway appends it to the invocation frame, the receiving endpoint
    exposes it via current_trace_id() for the duration of the handler —
    spans emitted on both sides of the wire stitch into one trace."""
    prev = getattr(_trace_ctx, "outgoing", None)
    _trace_ctx.outgoing = trace_id
    try:
        yield
    finally:
        _trace_ctx.outgoing = prev

from flink_tpu.security.framing import FrameAuthError, RestrictedUnpicklingError
from flink_tpu.security.transport import (
    SecurityConfig,
    client_handshake,
    recv_obj,
    send_obj,
    server_handshake,
    validate_server_config,
    wrap_client_socket,
    wrap_server_socket,
)


class RpcEndpoint:
    """Base class: public methods become remotely callable; all invocations
    (local or remote) run on the endpoint's single main thread."""

    def __init__(self, name: Optional[str] = None):
        self.name = name or f"{type(self).__name__}-{uuid.uuid4().hex[:8]}"
        self._inbox: "list" = []
        self._cv = threading.Condition()
        self._running = True
        self._main_thread = threading.Thread(
            target=self._main_loop, name=f"rpc-main-{self.name}", daemon=True
        )
        self._main_thread.start()

    # -- main-thread discipline --------------------------------------------
    def validate_main_thread(self) -> None:
        assert threading.current_thread() is self._main_thread, (
            f"endpoint {self.name} state touched off the main thread"
        )

    def run_in_main_thread(self, fn: Callable, *args, **kwargs) -> Future:
        f: Future = Future()
        with self._cv:
            if not self._running:
                f.set_exception(RuntimeError(f"endpoint {self.name} stopped"))
                return f
            self._inbox.append((fn, args, kwargs, f))
            self._cv.notify()
        return f

    def _main_loop(self) -> None:
        while True:
            with self._cv:
                while self._running and not self._inbox:
                    self._cv.wait(timeout=0.2)
                if not self._running and not self._inbox:
                    return
                fn, args, kwargs, fut = self._inbox.pop(0)
            try:
                fut.set_result(fn(*args, **kwargs))
            except BaseException as e:  # noqa: BLE001 — forwarded to caller
                fut.set_exception(e)

    def stop(self) -> None:
        with self._cv:
            self._running = False
            self._cv.notify_all()

    # called by the server
    def _invoke(self, method: str, args, kwargs, trace_id: Optional[str] = None):
        fn = getattr(self, method, None)
        if fn is None or method.startswith("_"):
            raise AttributeError(f"{self.name} has no rpc method {method!r}")
        if trace_id is None:
            return self.run_in_main_thread(fn, *args, **kwargs)

        def with_ctx(*a, **kw):
            # surface the caller's trace id to the handler (main thread)
            _trace_ctx.incoming = trace_id
            try:
                return fn(*a, **kw)
            finally:
                _trace_ctx.incoming = None

        return self.run_in_main_thread(with_ctx, *args, **kwargs)


class RpcService:
    """Hosts endpoints on one TCP port; builds gateways to remote services.

    `security` defaults to the process-wide SecurityConfig (auth ON): every
    accepted connection must complete the cluster handshake before a single
    request byte is parsed, and every frame is MAC-verified before
    deserialization."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 security: Optional[SecurityConfig] = None):
        self._endpoints: Dict[str, RpcEndpoint] = {}
        self._lock = threading.Lock()
        self.security = SecurityConfig.resolve() if security is None else security
        validate_server_config(self.security)
        service = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                sock = self.request
                codec = None
                if service.security.enabled:
                    try:
                        sock.settimeout(service.security.handshake_timeout_s)
                        sock = wrap_server_socket(sock, service.security)
                        codec = server_handshake(sock, service.security)
                        sock.settimeout(None)
                    except (FrameAuthError, OSError, ValueError):
                        return   # unauthenticated peer: drop pre-parse
                while True:
                    try:
                        msg = recv_obj(sock, codec)
                    except (FrameAuthError, RestrictedUnpicklingError):
                        return   # tampered frame / disallowed global: drop
                    except OSError:
                        return
                    if msg is None:
                        return
                    try:
                        # 4-tuple = legacy frame; 5th element carries the
                        # optional trace context (traceparent analogue)
                        trace_id = None
                        if len(msg) == 5:
                            endpoint, method, args, kwargs, trace_id = msg
                        else:
                            endpoint, method, args, kwargs = msg
                        with service._lock:
                            ep = service._endpoints.get(endpoint)
                        if ep is None:
                            raise LookupError(f"no endpoint {endpoint!r}")
                        result = ep._invoke(method, args, kwargs,
                                            trace_id).result()
                        reply = (True, result)
                    except BaseException as e:  # noqa: BLE001 — shipped back
                        reply = (False, (type(e).__name__, str(e), traceback.format_exc()))
                    try:
                        send_obj(sock, reply, codec)
                    except OSError:
                        return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread = threading.Thread(
            target=self._server.serve_forever, name=f"rpc-srv-{self.port}", daemon=True
        )
        self._thread.start()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def register(self, endpoint: RpcEndpoint) -> None:
        with self._lock:
            self._endpoints[endpoint.name] = endpoint

    def unregister(self, name: str) -> None:
        with self._lock:
            self._endpoints.pop(name, None)

    def gateway(self, address: str, endpoint: str, timeout: float = 10.0) -> "RpcGateway":
        return RpcGateway(address, endpoint, timeout, security=self.security)

    def stop(self) -> None:
        with self._lock:
            eps = list(self._endpoints.values())
        for ep in eps:
            ep.stop()
        self._server.shutdown()
        self._server.server_close()


class RemoteRpcError(RuntimeError):
    def __init__(self, exc_type: str, message: str, remote_traceback: str):
        super().__init__(f"{exc_type}: {message}")
        self.exc_type = exc_type
        self.remote_traceback = remote_traceback


class RpcGateway:
    """Dynamic proxy: gateway.method(*a, **kw) → remote invocation.

    One TCP connection per gateway, serialized calls (matching the
    per-endpoint ordering guarantee of the reference's actor mailbox)."""

    def __init__(self, address: str, endpoint: str, timeout: float = 10.0,
                 security: Optional[SecurityConfig] = None):
        self._address = address
        self._endpoint = endpoint
        self._timeout = timeout
        self._security = SecurityConfig.resolve() if security is None else security
        self._sock: Optional[socket.socket] = None
        self._codec = None
        self._lock = threading.Lock()

    def _connect(self) -> socket.socket:
        if self._sock is None:
            host, port = self._address.rsplit(":", 1)
            sock = socket.create_connection((host, int(port)), timeout=self._timeout)
            if self._security.enabled:
                try:
                    sock = wrap_client_socket(sock, self._security)
                    self._codec = client_handshake(sock, self._security)
                except BaseException:
                    sock.close()
                    raise
            # the timeout guards CONNECT + handshake only: leaving it armed
            # would make any invocation whose reply takes > timeout raise
            # mid-frame and poison the connection for every later call
            sock.settimeout(None)
            self._sock = sock
        return self._sock

    def close(self) -> None:
        with self._lock:
            self._close_locked()

    def _close_locked(self) -> None:
        """Drop the cached socket; caller must hold self._lock (the lock is
        non-reentrant, so call() error paths use this instead of close())."""
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None
                self._codec = None

    def __getattr__(self, method: str):
        if method.startswith("_"):
            raise AttributeError(method)

        def call(*args, **kwargs):
            trace_id = getattr(_trace_ctx, "outgoing", None)
            frame = ((self._endpoint, method, args, kwargs, trace_id)
                     if trace_id is not None
                     else (self._endpoint, method, args, kwargs))
            with self._lock:
                sock = self._connect()
                try:
                    send_obj(sock, frame, self._codec)
                    reply = recv_obj(sock, self._codec)
                except (OSError, FrameAuthError, RestrictedUnpicklingError):
                    self._close_locked()
                    raise
                if reply is None:
                    self._close_locked()
                    raise ConnectionError(f"rpc connection to {self._address} closed")
            ok, payload = reply
            if ok:
                return payload
            raise RemoteRpcError(*payload)

        return call

    def call_async(self, method: str, *args, **kwargs) -> Future:
        f: Future = Future()

        def run():
            try:
                f.set_result(getattr(self, method)(*args, **kwargs))
            except BaseException as e:  # noqa: BLE001
                f.set_exception(e)

        threading.Thread(target=run, daemon=True,
                         name=f"rpc-async-{self._endpoint}.{method}").start()
        return f
