"""Control-plane RPC: location-transparent endpoint calls over TCP.

The analogue of the reference's actor RPC (flink-rpc-akka/.../PekkoRpcService.java:86,
PekkoInvocationHandler.java:71): named endpoints expose public methods;
remote callers hold a gateway proxy whose attribute calls serialize the
invocation, ship it over a framed TCP connection, and return the result (or
re-raise the remote exception). Each endpoint executes ALL invocations on
one dedicated main thread — the single-threaded actor discipline that the
reference enforces with MainThreadValidatorUtil (MainThreadValidatorUtil.java:35)
— so endpoint state needs no locks.

Wire format (flink_tpu/security): connection handshake (version +
cluster-id + nonce challenge against the cluster secret), then 4-byte
big-endian length + HMAC-signed frame of the restricted-pickled
(endpoint, method, args, kwargs[, trace_id]) / (ok, payload) — the
optional fifth element is the caller's trace context (W3C-traceparent
analogue; see trace_context/current_trace_id). Frames are MAC-verified
BEFORE deserialization and deserialized through the security allowlist;
`security.transport.enabled: false` restores the legacy plain-pickle wire.
This is the DCN control plane; the data plane (record batches, credits)
lives in dataplane.py.
"""

from __future__ import annotations

import contextlib
import dataclasses
import random
import socket
import socketserver
import threading
import time
import traceback
import uuid
from concurrent.futures import Future
from typing import Any, Callable, Dict, FrozenSet, Optional

from flink_tpu.chaos import plan as _chaos
from flink_tpu.lint.contracts import absorbs_faults


# ---------------------------------------------------------------------------
# trace-context propagation (W3C-traceparent-lite over the RPC frame)
# ---------------------------------------------------------------------------

_trace_ctx = threading.local()


def current_trace_id() -> Optional[str]:
    """Trace id of the RPC invocation currently executing on this endpoint
    main thread (None outside an invocation or when the caller sent none).
    The observability analogue of reading the traceparent header."""
    return getattr(_trace_ctx, "incoming", None)


@contextlib.contextmanager
def trace_context(trace_id: Optional[str]):
    """Attach `trace_id` to every RPC this thread issues inside the block:
    the gateway appends it to the invocation frame, the receiving endpoint
    exposes it via current_trace_id() for the duration of the handler —
    spans emitted on both sides of the wire stitch into one trace."""
    prev = getattr(_trace_ctx, "outgoing", None)
    _trace_ctx.outgoing = trace_id
    try:
        yield
    finally:
        _trace_ctx.outgoing = prev

from flink_tpu.security.framing import FrameAuthError, RestrictedUnpicklingError
from flink_tpu.security.transport import (
    SecurityConfig,
    client_handshake,
    recv_obj,
    send_obj,
    server_handshake,
    validate_server_config,
    wrap_client_socket,
    wrap_server_socket,
)


class RpcEndpoint:
    """Base class: public methods become remotely callable; all invocations
    (local or remote) run on the endpoint's single main thread."""

    def __init__(self, name: Optional[str] = None):
        self.name = name or f"{type(self).__name__}-{uuid.uuid4().hex[:8]}"
        self._inbox: "list" = []
        self._cv = threading.Condition()
        self._running = True
        self._main_thread = threading.Thread(
            target=self._main_loop, name=f"rpc-main-{self.name}", daemon=True
        )
        self._main_thread.start()

    # -- main-thread discipline --------------------------------------------
    def validate_main_thread(self) -> None:
        assert threading.current_thread() is self._main_thread, (
            f"endpoint {self.name} state touched off the main thread"
        )

    def run_in_main_thread(self, fn: Callable, *args, **kwargs) -> Future:
        f: Future = Future()
        with self._cv:
            if not self._running:
                f.set_exception(RuntimeError(f"endpoint {self.name} stopped"))
                return f
            self._inbox.append((fn, args, kwargs, f))
            self._cv.notify()
        return f

    def _main_loop(self) -> None:
        while True:
            with self._cv:
                while self._running and not self._inbox:
                    self._cv.wait(timeout=0.2)
                if not self._running and not self._inbox:
                    return
                fn, args, kwargs, fut = self._inbox.pop(0)
            try:
                fut.set_result(fn(*args, **kwargs))
            except BaseException as e:  # noqa: BLE001 — forwarded to caller
                fut.set_exception(e)

    def stop(self) -> None:
        with self._cv:
            self._running = False
            self._cv.notify_all()

    # called by the server
    def _invoke(self, method: str, args, kwargs, trace_id: Optional[str] = None):
        fn = getattr(self, method, None)
        if fn is None or method.startswith("_"):
            raise AttributeError(f"{self.name} has no rpc method {method!r}")
        if trace_id is None:
            return self.run_in_main_thread(fn, *args, **kwargs)

        def with_ctx(*a, **kw):
            # surface the caller's trace id to the handler (main thread)
            _trace_ctx.incoming = trace_id
            try:
                return fn(*a, **kw)
            finally:
                _trace_ctx.incoming = None

        return self.run_in_main_thread(with_ctx, *args, **kwargs)


class RpcService:
    """Hosts endpoints on one TCP port; builds gateways to remote services.

    `security` defaults to the process-wide SecurityConfig (auth ON): every
    accepted connection must complete the cluster handshake before a single
    request byte is parsed, and every frame is MAC-verified before
    deserialization."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 security: Optional[SecurityConfig] = None):
        self._endpoints: Dict[str, RpcEndpoint] = {}
        self._lock = threading.Lock()
        self.security = SecurityConfig.resolve() if security is None else security
        validate_server_config(self.security)
        service = self

        class Handler(socketserver.BaseRequestHandler):
            @absorbs_faults("RPC server loop: handler errors ship back to the caller as failed replies; the crash model (sever the connection, no reply) is implemented at the seam's own InjectedCrash handler")
            def handle(self):
                sock = self.request
                codec = None
                if service.security.enabled:
                    try:
                        sock.settimeout(service.security.handshake_timeout_s)
                        sock = wrap_server_socket(sock, service.security)
                        codec = server_handshake(sock, service.security)
                        sock.settimeout(None)
                    except (FrameAuthError, OSError, ValueError):
                        return   # unauthenticated peer: drop pre-parse
                while True:
                    try:
                        msg = recv_obj(sock, codec)
                    except (FrameAuthError, RestrictedUnpicklingError):
                        return   # tampered frame / disallowed global: drop
                    except OSError:
                        return
                    if msg is None:
                        return
                    try:
                        # 4-tuple = legacy frame; 5th element carries the
                        # optional trace context (traceparent analogue)
                        trace_id = None
                        if len(msg) == 5:
                            endpoint, method, args, kwargs, trace_id = msg
                        else:
                            endpoint, method, args, kwargs = msg
                        # chaos seam (server side): a delay rule wedges
                        # this connection thread — the stuck-endpoint
                        # model; drop severs the connection pre-dispatch;
                        # crash ALSO severs it with no reply (a crashed
                        # server cannot answer — shipping it back as a
                        # RemoteRpcError would absorb the process-death
                        # model into an ordinary handler error)
                        hook = _chaos.HOOK
                        if hook is not None:
                            try:
                                if hook("rpc",
                                        f"server:{endpoint}.{method}") \
                                        == "drop":
                                    return
                            except _chaos.InjectedCrash:
                                return
                        with service._lock:
                            ep = service._endpoints.get(endpoint)
                        if ep is None:
                            raise LookupError(f"no endpoint {endpoint!r}")
                        result = ep._invoke(method, args, kwargs,
                                            trace_id).result()
                        reply = (True, result)
                    except BaseException as e:  # noqa: BLE001 — shipped back
                        reply = (False, (type(e).__name__, str(e), traceback.format_exc()))
                    try:
                        send_obj(sock, reply, codec)
                    except OSError:
                        return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread = threading.Thread(
            target=self._server.serve_forever, name=f"rpc-srv-{self.port}", daemon=True
        )
        self._thread.start()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def register(self, endpoint: RpcEndpoint) -> None:
        with self._lock:
            self._endpoints[endpoint.name] = endpoint

    def unregister(self, name: str) -> None:
        with self._lock:
            self._endpoints.pop(name, None)

    def gateway(self, address: str, endpoint: str, timeout: float = 10.0,
                reply_timeout: Optional[float] = None) -> "RpcGateway":
        return RpcGateway(address, endpoint, timeout, security=self.security,
                          reply_timeout=reply_timeout)

    def stop(self) -> None:
        with self._lock:
            eps = list(self._endpoints.values())
        for ep in eps:
            ep.stop()
        self._server.shutdown()
        self._server.server_close()


class RemoteRpcError(RuntimeError):
    def __init__(self, exc_type: str, message: str, remote_traceback: str):
        super().__init__(f"{exc_type}: {message}")
        self.exc_type = exc_type
        self.remote_traceback = remote_traceback


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff + jitter + an overall deadline for gateway-side
    retries of IDEMPOTENT control-plane calls (the transient-fault
    hardening the chaos rpc-flap scenario exercises). Job-mutating calls
    never retry: a re-sent submit/deploy/rescale whose first attempt DID
    land server-side would double-apply."""

    max_attempts: int = 5
    initial_backoff_s: float = 0.05
    multiplier: float = 2.0
    max_backoff_s: float = 1.0
    jitter: float = 0.5          # backoff scaled by U[1-jitter, 1+jitter]
    deadline_s: float = 8.0      # overall wall budget across attempts


#: control-plane methods safe to re-send after a transport-level failure
#: (connection reset/refused, reply timeout): liveness reports, checkpoint
#: acks/declines (the JM's handlers are attempt-guarded and level-
#: triggered), registrations (keyed by tm_id), and pure reads. Everything
#: else — submit_job, deploy_task, rescale_job, cancel_job, put — stays
#: single-attempt.
IDEMPOTENT_METHODS: FrozenSet[str] = frozenset({
    "ping", "heartbeat_tm", "register_task_executor",
    "ack_checkpoint", "decline_checkpoint",
    "task_finished", "cancel_task", "release_job_state",
    "peer_alive", "fetch_shard_restore",
    "job_status", "job_result", "job_metrics", "job_spans",
    "job_backpressure", "job_checkpoints", "job_checkpoint",
    "job_exceptions", "job_autoscaler", "job_device", "list_jobs", "get",
    # NOT here: trigger_checkpoint — the JM-side method of that name
    # allocates a fresh checkpoint id per call, so a retry after a lost
    # reply double-triggers (two barrier rounds, an orphaned savepoint)
})


class RpcGateway:
    """Dynamic proxy: gateway.method(*a, **kw) → remote invocation.

    One TCP connection per gateway, serialized calls (matching the
    per-endpoint ordering guarantee of the reference's actor mailbox).
    Replies are awaited under `reply_timeout` (default: the connect
    `timeout`) — a wedged server handler surfaces as a loud TimeoutError
    on a now-closed connection instead of blocking the caller forever —
    and calls in :data:`IDEMPOTENT_METHODS` retry transport failures per
    `retry`. Gateways carrying payload-shipping calls whose server-side
    handling is legitimately slow (deploys restoring large snapshots,
    acks persisting them) should pass a generous `reply_timeout` — the
    cluster uses PAYLOAD_REPLY_TIMEOUT_S — so the wedge detector never
    misfires on a genuinely big transfer."""

    def __init__(self, address: str, endpoint: str, timeout: float = 10.0,
                 security: Optional[SecurityConfig] = None,
                 retry: Optional[RetryPolicy] = None,
                 reply_timeout: Optional[float] = None):
        self._address = address
        self._endpoint = endpoint
        self._timeout = timeout
        self._reply_timeout = timeout if reply_timeout is None else reply_timeout
        self._retry = RetryPolicy() if retry is None else retry
        self._security = SecurityConfig.resolve() if security is None else security
        self._sock: Optional[socket.socket] = None
        self._codec = None
        self._lock = threading.Lock()

    def _connect(self) -> socket.socket:
        if self._sock is None:
            host, port = self._address.rsplit(":", 1)
            sock = socket.create_connection((host, int(port)), timeout=self._timeout)
            if self._security.enabled:
                try:
                    sock = wrap_client_socket(sock, self._security)
                    self._codec = client_handshake(sock, self._security)
                except BaseException:
                    sock.close()
                    raise
            # the timeout guards CONNECT + handshake only: leaving it armed
            # would make any invocation whose reply takes > timeout raise
            # mid-frame and poison the connection for every later call
            sock.settimeout(None)
            self._sock = sock
        return self._sock

    @property
    def address(self) -> str:
        """host:port this gateway dials (for building sibling gateways to
        the same service, e.g. a tight-timeout liveness probe next to a
        payload-tier gateway)."""
        return self._address

    def close(self) -> None:
        with self._lock:
            self._close_locked()

    def _close_locked(self) -> None:
        """Drop the cached socket; caller must hold self._lock (the lock is
        non-reentrant, so call() error paths use this instead of close())."""
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None
                self._codec = None

    def _attempt(self, method: str, frame) -> tuple:
        """One wire attempt: connect (if needed), send, await the reply
        under the gateway timeout. Any failure closes the connection (a
        half-done exchange poisons frame alignment) and raises."""
        with self._lock:
            # chaos seam: drop = the connection "died" before the frame
            # left; error/crash raise from the hook itself. Inside the
            # attempt so retries re-consult the plan (nth-counting sees
            # every attempt).
            hook = _chaos.HOOK
            if hook is not None and hook(
                    "rpc", f"{self._endpoint}.{method}") == "drop":
                self._close_locked()
                raise _chaos.InjectedFault(
                    f"rpc-drop:{self._endpoint}.{method}")
            sock = self._connect()
            try:
                # armed for THIS call only: a wedged server handler (its
                # endpoint main thread blocked in the invocation) must
                # surface as a timeout, not hold the caller forever. The
                # connection is closed on timeout, so a later call gets a
                # fresh socket with no stale reply in flight.
                sock.settimeout(self._reply_timeout)
                send_obj(sock, frame, self._codec)
                reply = recv_obj(sock, self._codec)
                sock.settimeout(None)
            except TimeoutError as e:
                self._close_locked()
                raise TimeoutError(
                    f"rpc {self._endpoint}.{method} to {self._address} "
                    f"timed out after {self._reply_timeout}s (wedged or "
                    f"partitioned endpoint)") from e
            except (OSError, FrameAuthError, RestrictedUnpicklingError):
                self._close_locked()
                raise
            if reply is None:
                self._close_locked()
                raise ConnectionError(f"rpc connection to {self._address} closed")
            return reply

    def __getattr__(self, method: str):
        if method.startswith("_"):
            raise AttributeError(method)

        def call(*args, **kwargs):
            trace_id = getattr(_trace_ctx, "outgoing", None)
            frame = ((self._endpoint, method, args, kwargs, trace_id)
                     if trace_id is not None
                     else (self._endpoint, method, args, kwargs))
            retry = self._retry
            can_retry = method in IDEMPOTENT_METHODS \
                and retry.max_attempts > 1
            deadline = time.monotonic() + retry.deadline_s
            backoff = retry.initial_backoff_s
            attempt = 0
            while True:
                attempt += 1
                try:
                    reply = self._attempt(method, frame)
                    break
                except (FrameAuthError, RestrictedUnpicklingError):
                    raise          # tampering is never transient
                except _chaos.InjectedCrash:
                    raise          # models process death: must escalate
                except OSError:
                    # transient transport failure (reset, refused, reply
                    # timeout, injected flap): re-send with backoff +
                    # jitter inside the overall deadline — but ONLY for
                    # idempotent calls; the lock is NOT held across the
                    # backoff sleep (CONC003), so other callers proceed
                    now = time.monotonic()
                    if (not can_retry or attempt >= retry.max_attempts
                            or now >= deadline):
                        raise
                    pause = backoff * (1.0 + retry.jitter
                                       * (2.0 * random.random() - 1.0))
                    time.sleep(max(min(pause, deadline - now), 0.0))
                    backoff = min(backoff * retry.multiplier,
                                  retry.max_backoff_s)
            ok, payload = reply
            if ok:
                return payload
            raise RemoteRpcError(*payload)

        return call

    def call_async(self, method: str, *args, **kwargs) -> Future:
        f: Future = Future()

        def run():
            try:
                f.set_result(getattr(self, method)(*args, **kwargs))
            except BaseException as e:  # noqa: BLE001
                f.set_exception(e)

        threading.Thread(target=run, daemon=True,
                         name=f"rpc-async-{self._endpoint}.{method}").start()
        return f
