"""Host-routed multi-shard window operator over a device mesh.

`ShardedTpuWindowOperator` inherits all window/slice math and the watermark
protocol from the single-shard TpuWindowOperator, overriding the state
plumbing with `parallel/sharded_window.ShardedColumnarState` — per-key-group
routing on host, shard_map ingest/fire/purge kernels on the mesh. It lives
in `runtime/` (not `parallel/`) because it subclasses a runtime operator:
the `parallel` layer is the kernel/state library below the runtime (ARCH001)
and must not import upward.
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh

from flink_tpu.core.keygroups import key_groups_for_hashes
from flink_tpu.lint.contracts import inflight_ring
from flink_tpu.core.records import hash_keys
from flink_tpu.ops import segment_ops
from flink_tpu.parallel.mesh import SHARD_AXIS
from flink_tpu.parallel.sharded_window import ShardedColumnarState
from flink_tpu.runtime.tpu_window_operator import TpuWindowOperator
from flink_tpu.state.columnar import KeyDictionary, RingFrontiers


@inflight_ring("_pending", drained_by="flush")
class ShardedTpuWindowOperator(TpuWindowOperator):
    """Host-routed multi-shard operator; inherits all window/slice math and
    the watermark protocol from the single-shard operator, overriding the
    state plumbing to route per key group and emit from all shards."""

    def __init__(
        self,
        assigner,
        aggregate,
        mesh: Mesh,
        *,
        max_parallelism: int = 128,
        axis: str = SHARD_AXIS,
        **kwargs,
    ):
        self.mesh = mesh
        self.axis = axis
        self.max_parallelism = max_parallelism
        dense = kwargs.pop("dense_int_keys", False)
        key_capacity = kwargs.pop("key_capacity", 1 << 12)
        num_slices = kwargs.pop("num_slices", None)
        super().__init__(
            assigner,
            aggregate,
            key_capacity=key_capacity,
            num_slices=num_slices,
            dense_int_keys=dense,
            **kwargs,
        )
        # replace single-shard state with the sharded one (same interface)
        self.state = ShardedColumnarState(
            self.agg,
            mesh,
            key_capacity=key_capacity,
            num_slices=self.S,
            dense_int_keys=dense,
            axis=axis,
        )
        self.n_shards = self.state.n

    # -- routed ingest --------------------------------------------------
    def _route(self, keys: np.ndarray, s_abs: np.ndarray, vals: np.ndarray):
        """Partition a host batch into [n, B] INVALID-padded routed arrays."""
        kg = key_groups_for_hashes(hash_keys(keys), self.max_parallelism)
        shard = (kg.astype(np.int64) * self.n_shards // self.max_parallelism).astype(np.int32)
        counts = np.bincount(shard, minlength=self.n_shards)
        B = max(int(counts.max()) if counts.size else 0, 1)
        B = 1 << (B - 1).bit_length()  # pad to pow2: bounds compile variants
        kid = np.full((self.n_shards, B), segment_ops.INVALID_INDEX, dtype=np.int64)
        sl = np.zeros((self.n_shards, B), dtype=np.int64)
        vl = np.zeros((self.n_shards, B), dtype=np.float32)
        required = 0
        for d in range(self.n_shards):
            idx = np.flatnonzero(shard == d)
            if idx.size == 0:
                continue
            ids, req = self.state.keydicts[d].lookup_or_insert(keys[idx])
            required = max(required, req)
            kid[d, : idx.size] = ids
            sl[d, : idx.size] = s_abs[idx]
            vl[d, : idx.size] = vals[idx]
        self.state.ensure_key_capacity(required)
        return kid, sl, vl

    def _ingest_arrays(self, keys: np.ndarray, vals: np.ndarray, ts: np.ndarray) -> None:
        if len(ts) == 0:
            return
        from flink_tpu.core.time import MIN_WATERMARK
        from flink_tpu.api.functions import LATE_DATA_TAG

        wm = self.current_watermark
        s_abs = self.slice_of_np(ts)
        if wm > MIN_WATERMARK:
            late = s_abs < self.min_live_slice(wm)
        else:
            late = np.zeros(len(ts), dtype=bool)
        if late.any():
            if self.emit_late_to_side_output:
                lt = self.side_output.setdefault(LATE_DATA_TAG.tag_id, [])
                for i in np.flatnonzero(late):
                    lt.append((keys[i], float(vals[i]), int(ts[i])))
            else:
                self.num_late_records_dropped += int(late.sum())
        keep = ~late
        if not keep.any():
            return
        batch_min = int(s_abs[keep].min())
        floor = self._ring_floor(batch_min)
        over = keep & (s_abs >= floor + self.S)
        if over.any():
            for i in np.flatnonzero(over):
                self._future.append((keys[i], vals[i], int(ts[i])))
            keep = keep & ~over
            if not keep.any():
                return

        kid, sl, vl = self._route(keys[keep], s_abs[keep], vals[keep].astype(np.float32))
        kid32 = np.where(
            kid == segment_ops.INVALID_INDEX, segment_ops.INVALID_INDEX, kid
        ).astype(np.int32)
        self.state.ingest(kid32, sl, vl)

        live_slices = s_abs[keep]
        cand = self.j_oldest(int(live_slices.min()))
        if wm > MIN_WATERMARK:
            cand = max(cand, self.j_fired_upto(wm) + 1)
        self.fire_cursor = cand if self.fire_cursor is None else min(self.fire_cursor, cand)

        if wm > MIN_WATERMARK:
            fired_hi = self.j_fired_upto(wm)
            lo = max(self.j_oldest(int(live_slices.min())), self.j_min_live(wm))
            hi = min(self.j_newest(int(live_slices.max())), fired_hi)
            for j in range(lo, hi + 1):
                self._emit_window(j, touch_mask=True)

    # -- sharded emission -----------------------------------------------
    def _emit_window(self, j: int, *, touch_mask: bool) -> None:
        window = self.window_of(j)
        start_slice = j * self.sl
        fired = self.state.fire(
            range(start_slice, start_slice + self.spw), touch_mask=touch_mask
        )
        if fired is None:
            return
        result, cnt, mask = fired
        mask_np = np.asarray(mask)  # [n, K]
        if not mask_np.any():
            return
        ts = window.max_timestamp()
        result_np = np.asarray(result)
        if self.columnar_output:
            self.output.append((None, window, (mask_np, result_np), ts))
            return
        for d in range(self.n_shards):
            idxs = np.flatnonzero(mask_np[d])
            if idxs.size == 0:
                continue
            keydict = self.state.keydicts[d]
            for i in idxs:
                self.output.append((keydict.key_at(int(i)), window, result_np[d, i].item(), ts))

    # -- snapshot / restore / rescale ------------------------------------
    def snapshot(self) -> dict:
        self.flush()
        return {
            "sharded": self.state.snapshot(),
            "watermark": self.current_watermark,
            "fire_cursor": self.fire_cursor,
            "future": [(k, float(v), int(t)) for k, v, t in self._future],
            "num_late_dropped": self.num_late_records_dropped,
            "max_parallelism": self.max_parallelism,
        }

    def restore(self, snap: dict) -> None:
        """Restore with key-group re-routing: works across different shard
        counts (rescale) because keys re-route by key group."""
        src = snap["sharded"]
        self.current_watermark = snap["watermark"]
        self.fire_cursor = snap["fire_cursor"]
        self._future = list(snap["future"])
        self.num_late_records_dropped = snap["num_late_dropped"]
        self._pending = []
        self.output = []
        self.state.frontiers = RingFrontiers(**src["frontiers"])
        if src["S"] != self.S:
            raise ValueError("slice-ring size change across restore is unsupported")

        # host-side re-route of every key's accumulator row
        n_old, K_old = src["n"], src["K"]
        acc_h = {
            f.name: np.full(
                (self.n_shards, self.state.K, self.S), f.identity, dtype=f.dtype
            )
            for f in self.agg.fields
        }
        cnt_h = np.zeros((self.n_shards, self.state.K, self.S), dtype=np.int32)
        new_dicts = [
            KeyDictionary(self.state.keydicts[0].dense_int) for _ in range(self.n_shards)
        ]
        required = 0
        for d_old in range(n_old):
            kd = KeyDictionary.restore(src["keydicts"][d_old])
            if len(kd) == 0:
                continue
            keys = np.asarray(kd._keys, dtype=object)
            kg = key_groups_for_hashes(hash_keys(keys), self.max_parallelism)
            new_shard = (
                kg.astype(np.int64) * self.n_shards // self.max_parallelism
            ).astype(np.int32)
            for d_new in range(self.n_shards):
                idx = np.flatnonzero(new_shard == d_new)
                if idx.size == 0:
                    continue
                ids, req = new_dicts[d_new].lookup_or_insert(keys[idx])
                required = max(required, req)
                if req > self.state.K:
                    grow = self.state.K
                    while grow < req:
                        grow *= 2
                    pad = grow - acc_h[self.agg.fields[0].name].shape[1]
                    if pad > 0:
                        for f in self.agg.fields:
                            filler = np.full(
                                (self.n_shards, pad, self.S), f.identity, dtype=f.dtype
                            )
                            acc_h[f.name] = np.concatenate([acc_h[f.name], filler], axis=1)
                        cnt_h = np.concatenate(
                            [cnt_h, np.zeros((self.n_shards, pad, self.S), np.int32)], axis=1
                        )
                for f in self.agg.fields:
                    acc_h[f.name][d_new, ids, :] = src["acc"][f.name][d_old, idx, :]
                cnt_h[d_new, ids, :] = src["count"][d_old, idx, :]
        self.state.K = acc_h[self.agg.fields[0].name].shape[1]
        self.state.keydicts = new_dicts
        self.state.acc = {
            k: jax.device_put(v, self.state._sharding3) for k, v in acc_h.items()
        }
        self.state.count = jax.device_put(cnt_h, self.state._sharding3)
        self.state.last_touch = None
