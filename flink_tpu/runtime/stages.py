"""Slot-sharing groups → pipeline stages.

Reference capability: `SlotSharingGroup` / `CoLocationGroup`
(flink-runtime .../runtime/jobmanager/scheduler/SlotSharingGroup.java,
`DataStream.slotSharingGroup`). In the reference, subtasks of all vertices
share one slot by default, and naming a group ISOLATES heavyweight
operators into their own slots — which also makes the cut stages run
concurrently as a pipeline (PIPELINED result partitions).

The stepped-executor analogue: a planned StepGraph is split at
slot-sharing-group boundaries into *stages*. Each stage is deployed as its
own task in its own slot (one process/thread running a JobRuntime over the
stage's sub-graph) and the cross-stage edges become credit-controlled
dataplane exchanges (runtime/dataplane.py — the PIPELINED partition
analogue, backpressure via credits). The default (everything in one group)
keeps today's behavior: the whole pipeline slice in one slot, which is
exactly the reference's default slot sharing.

Co-location: an iteration's feedback cycle (head → body → tail) must stay
within one stage — the CoLocationGroup constraint the reference applies to
iteration head/tail pairs — validated here.

Protocol on a cross-stage channel (FIFO, credit-controlled):
  ("b", values, timestamps)  — a record batch
  ("w", watermark_ms)        — a watermark advance
  ("m", wall_ms)             — a latency marker (source wall-clock stamp)
  ("barrier", cp_id)         — an aligned checkpoint barrier
  end-of-stream via the channel's eos frame (OutputChannel.end()).
Latency markers cross stages: the producer forwards its marker stamp as an
("m", wall_ms) frame (throttled to one per ~100 ms per channel — markers
are samples, and an unthrottled forward would cost one credit per batch)
and the consuming stage's input reader hands it to the run loop
(take_marker), so a sink's (now - stamp) measures END-TO-END transit
across every stage and exchange hop rather than resetting at each
boundary.

Checkpoints across stages use the reference's aligned-barrier algorithm
(CheckpointCoordinator → CheckpointBarrier → CheckpointBarrierHandler
alignment): the JM triggers the SOURCE stages; a source stage snapshots at
its next step boundary and emits a barrier into every out-channel; a
downstream stage pauses each input gate as its barrier arrives (alignment
backpressure — paused gates stop consuming, so post-barrier records never
enter pre-barrier state), and when every gate plus the local source
contribution has arrived it snapshots, forwards the barrier, and acks.
FIFO channels make the cut consistent with NO channel state in the
snapshot: everything pre-barrier is reflected in some stage's state,
everything post-barrier is regenerated from the rewound sources on
restore.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from flink_tpu.connectors.source import Batch, Source, SourceSplit, SplitEnumerator, SourceReader
from flink_tpu.core.time import MIN_WATERMARK
from flink_tpu.graph.transformation import Step, StepGraph, Transformation
from flink_tpu.utils.arrays import as_device_column, obj_array


# ---------------------------------------------------------------------------
# stage assignment / validation
# ---------------------------------------------------------------------------

def stage_names(graph: StepGraph) -> List[str]:
    """Distinct slot-sharing groups in first-appearance (topological)
    order; the deployment order of the stages."""
    names: List[str] = []
    for s in graph.steps:
        if s.slot_group not in names:
            names.append(s.slot_group)
    return names


def num_stages(graph: StepGraph) -> int:
    return len(stage_names(graph))


def _stage_index(graph: StepGraph) -> Dict[int, int]:
    names = stage_names(graph)
    return {id(s): names.index(s.slot_group) for s in graph.steps}


def validate_stages(graph: StepGraph) -> None:
    """Slot-sharing groups must cut the graph into a forward pipeline:

    - every cross-group edge flows from an earlier stage to a later one
      (groups may not interleave along a path);
    - all steps fed directly by one source belong to one stage (a physical
      reader cannot be split across processes);
    - an iteration's feedback cycle stays within one stage (CoLocationGroup
      analogue — the runtime cycle is process-local)."""
    idx = _stage_index(graph)
    # co-location first: a split iteration loop is the clearer diagnosis
    # (its backward feedback edge would otherwise read as "interleaved")
    tails = [s for s in graph.steps
             if s.terminal is not None and s.terminal.kind == "iteration_tail"]
    heads = {s.terminal.id: s for s in graph.steps
             if s.terminal is not None and s.terminal.kind == "iteration_head"}
    for tail in tails:
        head = heads.get(tail.terminal.config["head"].id)
        if head is None:
            continue  # caught by build_runners
        loop_steps = _between(graph, head, tail)
        bad = [s for s in loop_steps if idx[id(s)] != idx[id(head)]]
        if bad:
            raise ValueError(
                "iteration loop must stay within one slot sharing group "
                f"(co-location): step '{bad[0].name}' is in group "
                f"{bad[0].slot_group!r} but the iteration head is in "
                f"{head.slot_group!r}"
            )
    for s in graph.steps:
        for edge in s.inputs:
            ent = edge[0]
            if isinstance(ent, Step) and idx[id(ent)] > idx[id(s)]:
                raise ValueError(
                    f"slot sharing groups interleave: step '{s.name}' "
                    f"(group {s.slot_group!r}) consumes step '{ent.name}' "
                    f"(group {ent.slot_group!r}) which is scheduled later; "
                    "groups must form a forward pipeline"
                )
    src_stage: Dict[int, int] = {}
    for s in graph.steps:
        for edge in s.inputs:
            ent = edge[0]
            if isinstance(ent, Transformation):
                prev = src_stage.setdefault(ent.id, idx[id(s)])
                if prev != idx[id(s)]:
                    raise ValueError(
                        f"source '{ent.name}' feeds steps in different slot "
                        "sharing groups; keep its direct consumers in one "
                        "group"
                    )


def _between(graph: StepGraph, head: Step, tail: Step) -> List[Step]:
    """Steps on any path head → … → tail (inclusive), following step edges."""
    consumers: Dict[int, List[Step]] = {}
    for s in graph.steps:
        for edge in s.inputs:
            if isinstance(edge[0], Step):
                consumers.setdefault(id(edge[0]), []).append(s)
    reach_from_head = set()
    work = [head]
    while work:
        s = work.pop()
        if id(s) in reach_from_head:
            continue
        reach_from_head.add(id(s))
        work.extend(consumers.get(id(s), ()))
    reaches_tail = set()
    work = [tail]
    while work:
        s = work.pop()
        if id(s) in reaches_tail:
            continue
        reaches_tail.add(id(s))
        for edge in s.inputs:
            if isinstance(edge[0], Step):
                work.append(edge[0])
    both = reach_from_head & reaches_tail
    return [s for s in graph.steps if id(s) in both]


# ---------------------------------------------------------------------------
# cross-stage edges
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class CrossEdge:
    edge_id: str
    producer_step: int        # index into graph.steps
    consumer_step: int
    ordinal: int              # input gate at the consumer
    tag: Optional[str]        # producer side-output channel, if any
    src_stage: int
    dst_stage: int


def cross_edges(graph: StepGraph) -> List[CrossEdge]:
    """Deterministic enumeration of edges crossing stage boundaries —
    identical on every task, so channel ids agree across processes."""
    idx = _stage_index(graph)
    pos = {id(s): i for i, s in enumerate(graph.steps)}
    edges: List[CrossEdge] = []
    for s in graph.steps:
        for edge in s.inputs:
            ent, ordinal = edge[0], edge[1]
            tag = edge[2] if len(edge) > 2 else None
            if isinstance(ent, Step) and idx[id(ent)] != idx[id(s)]:
                edges.append(CrossEdge(
                    edge_id=f"x{len(edges)}",
                    producer_step=pos[id(ent)],
                    consumer_step=pos[id(s)],
                    ordinal=ordinal,
                    tag=tag,
                    src_stage=idx[id(ent)],
                    dst_stage=idx[id(s)],
                ))
    return edges


# ---------------------------------------------------------------------------
# runtime pieces: channel-fed source, channel-writing sink
# ---------------------------------------------------------------------------

class _WmBox:
    """Shared watermark cell between a stage-input reader (writer) and its
    watermark 'generator' (reader)."""

    __slots__ = ("wm",)

    def __init__(self):
        self.wm = MIN_WATERMARK


class _ChannelWatermarkGenerator:
    def __init__(self, box: _WmBox):
        self._box = box
        self._emitted = MIN_WATERMARK

    def on_batch_np(self, ts) -> int:
        # always an int (None would trigger the per-event fallback in the
        # source driver); non-advancing values are dropped by the valves
        if self._box.wm > self._emitted:
            self._emitted = self._box.wm
        return self._emitted

    def snapshot(self):
        return self._emitted

    def restore(self, snap) -> None:
        self._emitted = snap


class _ChannelWatermarks:
    """WatermarkStrategy duck-type forwarding upstream-stage watermarks."""

    timestamp_assigner = None

    def __init__(self, box: _WmBox):
        self._box = box

    def create_generator(self) -> _ChannelWatermarkGenerator:
        return _ChannelWatermarkGenerator(self._box)


def _graph_disorder_bound(graph) -> "int | None":
    """Largest bounded-out-of-orderness delay (ms) across the FULL job's
    original sources, or None if any bound is not statically knowable.
    Stage-in sources carry this as `out_of_orderness_hint` so operator
    selection inside a carved stage (executor._max_source_out_of_orderness)
    still sees the job's real disorder bound — a _ChannelWatermarks
    generator alone would make the device-session routing gate fail open
    across every stage boundary. Conservative: the max is over all sources,
    not only those reaching a given window step."""
    from flink_tpu.core.watermarks import BoundedOutOfOrdernessWatermarks

    bound = 0
    for src in graph.sources:
        strategy = src.config.get("watermark_strategy")
        if strategy is None:
            continue
        gen = strategy.create_generator()
        if not isinstance(gen, BoundedOutOfOrdernessWatermarks):
            return None
        bound = max(bound, gen._delay)
    return bound


class BarrierAligner:
    """Aligned-barrier tracker for one stage task (the
    CheckpointBarrierHandler analogue). Gates are the stage's cross-input
    edge ids plus the virtual '__source__' gate when the stage also hosts
    original sources (its barrier is the JM trigger consumed at a step
    boundary). A gate that delivered the in-flight barrier is PAUSED —
    its reader yields empty batches without consuming — until every gate
    arrives; then `on_complete(cp_id)` runs on the run-loop thread
    (snapshot + forward + ack) and all gates resume. FIFO channels make
    one-at-a-time alignment sufficient: a later barrier simply waits in
    its paused gate's ring."""

    SOURCE_GATE = "__source__"

    def __init__(self, gates, has_local_sources: bool, on_complete):
        self.expected = set(gates)
        if has_local_sources:
            self.expected.add(self.SOURCE_GATE)
        self.on_complete = on_complete
        self.cp: Optional[int] = None
        self.arrived: set = set()
        self._queued: List[tuple] = []   # barriers for LATER checkpoints

    def on_barrier(self, gate: str, cp_id: int) -> None:
        if self.cp is not None and gate in self.arrived:
            # a later checkpoint's barrier on an already-aligned gate
            # (only the virtual source gate can do this — channel gates
            # pause): queue it for after the in-flight alignment, or it
            # would be silently merged into the wrong cut
            self._queued.append((gate, cp_id))
            return
        if self.cp is None:
            self.cp = cp_id
        self.arrived.add(gate)
        self._maybe_complete()

    def on_eos(self, gate: str) -> None:
        """End-of-stream on a gate (EndOfPartition analogue —
        SingleCheckpointBarrierHandler.processEndOfPartition): an ended
        channel can never deliver a barrier, so stop expecting it; an ended
        channel also has no pre-barrier data left, so for alignment purposes
        it counts as aligned. Without this, a stage whose upstreams end at
        different lengths stalls forever: the shorter upstream never emits
        the in-flight barrier, the already-paused gates never resume, and
        the paused upstream blocks on credits."""
        self.expected.discard(gate)
        self.arrived.discard(gate)
        self._maybe_complete()

    def _maybe_complete(self) -> None:
        if self.cp is not None and self.arrived >= self.expected:
            cp, self.cp, self.arrived = self.cp, None, set()
            self.on_complete(cp)
            queued, self._queued = self._queued, []
            for g, c in queued:
                self.on_barrier(g, c)

    def paused(self, gate: str) -> bool:
        return self.cp is not None and gate in self.arrived


class _StageReader(SourceReader):
    """Reads ('b', values, ts) / ('w', wm) / ('barrier', cp) messages off
    one exchange channel. Returns an EMPTY batch on poll timeout (keeps
    the round-robin source loop live for the job's other inputs) and None
    only at end-of-stream. While this gate's barrier is aligning, the
    reader yields empty batches WITHOUT consuming (alignment
    backpressure)."""

    def __init__(self, channel, cancelled: threading.Event, box: _WmBox,
                 gate: str = "", aligner: Optional[BarrierAligner] = None):
        self._chan = channel
        self._cancelled = cancelled
        self._box = box
        self._gate = gate
        self._aligner = aligner
        self._pending_marker: Optional[float] = None

    def add_split(self, split: SourceSplit) -> None:
        pass

    def take_marker(self) -> Optional[float]:
        """Latest upstream latency-marker stamp received on this channel
        (cleared on read). The run loop attaches it to the next batch it
        pushes, preserving cross-stage transit measurement; markers are
        samples, so keeping only the latest between batches is lossless for
        percentile purposes."""
        m, self._pending_marker = self._pending_marker, None
        return m

    def poll_batch(self, max_records: int) -> Optional[Batch]:
        while not self._cancelled.is_set():
            if self._aligner is not None and self._aligner.paused(self._gate):
                return _EMPTY_BATCH               # aligning: do not consume
            try:
                msg = self._chan.poll(timeout=0.05)
            except TimeoutError:
                return _EMPTY_BATCH
            if msg is None:
                if self._aligner is not None:     # ended gates align freely
                    self._aligner.on_eos(self._gate)
                return None                       # upstream stage ended
            if msg[0] == "w":
                self._box.wm = max(self._box.wm, int(msg[1]))
                return _EMPTY_BATCH               # watermark piggybacks next
            if msg[0] == "m":
                self._pending_marker = float(msg[1])
                return _EMPTY_BATCH               # marker rides the next batch
            if msg[0] == "barrier":
                if self._aligner is not None:
                    # may complete the alignment: the snapshot callback runs
                    # HERE, on the run-loop thread between batches
                    self._aligner.on_barrier(self._gate, int(msg[1]))
                return _EMPTY_BATCH
            # numeric columns forward device-ready: the binary wire decodes
            # straight into contiguous np.frombuffer views, which pass
            # through untouched and jax.device_put can stage without a host
            # transform pass (whole-graph fusion ingest, docs/fusion.md);
            # only a non-contiguous view pays one compaction here
            return Batch(values=as_device_column(msg[1]),
                         timestamps=np.asarray(msg[2], dtype=np.int64))
        return None


_EMPTY_BATCH = Batch(values=obj_array([]),
                     timestamps=np.asarray([], dtype=np.int64))


class StageInputSource(Source):
    """Source wrapping one cross-stage input channel."""

    boundedness = "CONTINUOUS_UNBOUNDED"

    def __init__(self, channel, cancelled: threading.Event, box: _WmBox,
                 gate: str = "", aligner: Optional[BarrierAligner] = None):
        self._channel = channel
        self._cancelled = cancelled
        self._box = box
        self._gate = gate
        self._aligner = aligner

    def create_enumerator(self) -> SplitEnumerator:
        return SplitEnumerator([SourceSplit("stage-input")])

    def create_reader(self) -> _StageReader:
        return _StageReader(self._channel, self._cancelled, self._box,
                            self._gate, self._aligner)


class StageOutputRunner:
    """Terminal step writing this stage's boundary output to the exchange
    (instantiated via executor._make_runner on kind 'stage_output';
    duck-typed StepRunner — import cycle keeps it out of executor.py).
    Backpressure: send blocks on credits, surfacing the downstream stage's
    backlog to this stage's run loop (reference: writer blocking on
    LocalBufferPool)."""

    downstream = None
    sides = None
    num_inputs = 1

    MARKER_FORWARD_SPACING_MS = 100.0

    def __init__(self, step: Step):
        t = step.terminal
        self.uid = t.uid
        self.sender = t.config["sender"]
        self.cancelled: threading.Event = t.config["cancelled"]
        # BufferDebloater analogue at batch granularity: observes this
        # sender's achieved throughput and splits oversized batches toward
        # throughput x target latency, so a backpressured exchange carries
        # smaller batches (None = exchange.debloat.enabled: false)
        self.debloater = t.config.get("debloater")
        self._ended = False
        self._last_marker_fwd = 0.0
        self.records_out = None

    def register_metrics(self, group) -> None:
        self.records_out = group.counter("numRecordsOut")
        # exchange-side observability: credits left (outPoolUsage inverse —
        # 0 while the downstream stage lags) and cumulative time this task
        # spent blocked on them (the task's backPressured contribution)
        group.gauge("availableCredits", self.sender.available_credits,
                    fold="sum")
        group.gauge("backPressuredTimeMsTotal",
                    lambda: self.backpressure_seconds() * 1000.0,
                    fold="sum", kind="counter")
        if self.debloater is not None:
            group.gauge("debloatedBatchSize", self.debloater.batch_size,
                        fold="sum")

    def backpressure_seconds(self) -> float:
        """Cumulative seconds blocked waiting for downstream credits; the
        task's TaskIOMetrics subtracts this from busy time."""
        return getattr(self.sender, "backpressured_s", 0.0)

    def _send(self, msg) -> None:
        while True:
            try:
                self.sender.send(msg, timeout=1.0)
                return
            except TimeoutError:
                if self.cancelled.is_set():
                    from flink_tpu.runtime.executor import JobCancelledException

                    raise JobCancelledException()

    # StepRunner protocol (single gate)
    def on_batch_n(self, ordinal, values, timestamps) -> None:
        self.on_batch(values, timestamps)

    def on_watermark_n(self, ordinal, watermark) -> None:
        self.on_watermark(watermark)

    def on_end_n(self, ordinal) -> None:
        self.on_end()

    def on_batch(self, values, timestamps) -> None:
        n = len(timestamps)
        if not n:
            return
        if self.records_out is not None:
            self.records_out.inc(n)
        d = self.debloater
        if d is None:
            self._send(("b", values, timestamps))
            return
        # split-only debloating: an oversized batch is sent in target-sized
        # slices (views, no copies). Splitting is stateless, so it composes
        # with aligned checkpoints — nothing is ever buffered across a
        # barrier. Until the first observation the batch passes through
        # whole (min_size would shred it for no reason).
        target = max(d.batch_size(), 1) if d.observed else n
        t0 = time.perf_counter()
        if n > target:
            for lo in range(0, n, target):
                self._send(("b", values[lo:lo + target],
                            timestamps[lo:lo + target]))
        else:
            self._send(("b", values, timestamps))
        # send time includes any credit wait — exactly the signal that
        # should shrink batches under backpressure
        d.observe(n, time.perf_counter() - t0)

    def on_watermark(self, watermark: int) -> None:
        self._send(("w", int(watermark)))

    def on_marker(self, wall_ms: float) -> None:
        # forward the stamp across the exchange so downstream stages (and
        # ultimately the sinks) measure end-to-end transit; the send shares
        # the data channel's credit discipline, which is exactly right — a
        # marker delayed by backpressure reports latency that backpressure
        # really added. Forwarding is throttled (markers are samples): with
        # per-batch markers at the source, an unthrottled forward would add
        # one exchange frame — one credit — per batch on the hot path.
        if self._ended:
            return
        now = time.monotonic() * 1000.0
        if now - self._last_marker_fwd >= self.MARKER_FORWARD_SPACING_MS:
            self._last_marker_fwd = now
            self._send(("m", float(wall_ms)))

    def on_processing_time(self, now_ms: int) -> None:
        pass

    def on_end(self) -> None:
        if not self._ended:
            self._ended = True
            self.sender.end()

    def snapshot(self) -> dict:
        return {}

    def restore(self, snap: dict) -> None:
        pass


# ---------------------------------------------------------------------------
# per-stage sub-graph
# ---------------------------------------------------------------------------

def stage_has_original_sources(graph: StepGraph, stage_idx: int) -> bool:
    idx = _stage_index(graph)
    return any(
        isinstance(edge[0], Transformation)
        for s in graph.steps if idx[id(s)] == stage_idx
        for edge in s.inputs
    )


def source_stage_indices(graph: StepGraph) -> List[int]:
    """Stages hosting original sources — the ones the JM's checkpoint
    trigger targets (barriers cascade to the rest)."""
    return [i for i in range(num_stages(graph))
            if stage_has_original_sources(graph, i)]


def build_stage_graph(
    graph: StepGraph,
    stage_idx: int,
    in_channels: Dict[str, Any],
    out_senders: Dict[str, Any],
    cancelled: threading.Event,
    aligner: Optional[BarrierAligner] = None,
    debloaters: Optional[Dict[str, Any]] = None,
) -> StepGraph:
    """Carve stage `stage_idx` out of `graph` (the task's OWN unpickled
    copy — mutated in place): cross-stage inputs become StageInputSource
    transformations reading `in_channels[edge_id]`; boundary outputs grow a
    'stage_output' terminal step writing `out_senders[edge_id]`."""
    idx = _stage_index(graph)
    edges = cross_edges(graph)
    mine = [s for s in graph.steps if idx[id(s)] == stage_idx]
    disorder_hint = _graph_disorder_bound(graph)   # before sources mutate

    for e in edges:
        if e.dst_stage == stage_idx:
            consumer = graph.steps[e.consumer_step]
            box = _WmBox()
            src_t = Transformation(
                "source", f"stage-in:{e.edge_id}", [],
                {
                    "source": StageInputSource(
                        in_channels[e.edge_id], cancelled, box,
                        gate=e.edge_id, aligner=aligner),
                    "watermark_strategy": _ChannelWatermarks(box),
                    "out_of_orderness_hint": disorder_hint,
                },
            )
            src_t.uid = f"stage-in-{e.edge_id}"
            # string id: the unpickled graph carries CLIENT-counter ids, and
            # this process's fresh counter would collide with them (feeds in
            # build_runners key by id — a collision merges two sources'
            # feed lists and misroutes records)
            src_t.id = f"stage-in-{e.edge_id}"
            for j, edge in enumerate(consumer.inputs):
                ent, ordinal = edge[0], edge[1]
                tag = edge[2] if len(edge) > 2 else None
                if (isinstance(ent, Step)
                        and graph.steps[e.producer_step] is ent
                        and ordinal == e.ordinal and tag == e.tag):
                    # tag consumed producer-side; this gate sees a plain feed
                    consumer.inputs[j] = (src_t, ordinal, None)
                    break
        if e.src_stage == stage_idx:
            producer = graph.steps[e.producer_step]
            out_t = Transformation(
                "stage_output", f"stage-out:{e.edge_id}", [],
                {"sender": out_senders[e.edge_id], "cancelled": cancelled,
                 "debloater": (debloaters or {}).get(e.edge_id)},
            )
            out_t.uid = f"stage-out-{e.edge_id}"
            out_t.id = f"stage-out-{e.edge_id}"   # collision-proof (see above)
            mine.append(Step(
                chain=[], terminal=out_t, partitioning="forward",
                inputs=[(producer, 0, e.tag)],
            ))

    sources: List[Transformation] = []
    for s in mine:
        for edge in s.inputs:
            ent = edge[0]
            if isinstance(ent, Transformation) and ent.kind == "source" \
                    and ent not in sources:
                sources.append(ent)
    if not sources:
        raise ValueError(f"stage {stage_idx} has no inputs")
    return StepGraph(sources=sources, steps=mine)
