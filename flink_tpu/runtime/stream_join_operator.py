"""Regular (non-windowed) streaming equi-join over changelog streams.

Reference: `StreamingJoinOperator`
(flink-table-runtime .../operators/join/stream/StreamingJoinOperator.java:40)
— both sides buffer EVERY live row per join key indefinitely; an arriving
row joins against the opposite side's current buffer and emits immediately;
a retraction removes its row from the buffer and retracts the joins it had
produced. Without an upsert key the output changelog uses +I / -D only
(the reference's "retract stream" join mode; JoinRecordStateViews
.InputSideHasNoUniqueKey keeps row -> appearance-count, exactly the
multiset kept here).

Inner join only matches; LEFT/RIGHT OUTER additionally emit (row, NULL)
paddings when the opposite buffer is empty and retract them when the first
match arrives (StreamingJoinOperator.processElement outerRecord handling).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Tuple

import numpy as np

from flink_tpu.table.changelog import (
    DELETE,
    INSERT,
    ROW_KIND_FIELD,
    is_additive,
    is_retractive,
    row_kind,
    strip_kind,
)
from flink_tpu.runtime.executor import StepRunner
from flink_tpu.utils.arrays import obj_array


def _freeze(row: dict) -> Tuple:
    return tuple(sorted(row.items()))


class StreamingJoinRunner(StepRunner):
    """StepRunner (terminal kind 'regular_join'). Inherits the two-gate
    valve: watermarks min-combine across the inputs and on_end fires only
    after BOTH sides end (StatusWatermarkValve semantics — a finished
    dimension side must not flush downstream state while the other side is
    still joining)."""

    num_inputs = 2

    def __init__(self, step, config):
        t = step.terminal
        self.key_selectors = (t.config["key_selector1"],
                              t.config["key_selector2"])
        self.merge_fn: Callable[[dict, dict], dict] = t.config["merge_fn"]
        self.join_type: str = t.config.get("join_type", "inner")
        if self.join_type not in ("inner", "left", "right"):
            # typed + attributed, never a bare job-build crash: FULL OUTER
            # is a catalogued refusal the SQL front door surfaces with the
            # same reason code (joins/spec.py, docs/joins.md)
            from flink_tpu.joins.spec import JoinUnsupported

            if self.join_type == "full":
                raise JoinUnsupported(
                    "join-full-outer",
                    "FULL OUTER JOIN is not supported: neither the host "
                    "StreamingJoinRunner nor the device join ring "
                    "implements two-sided padding retraction")
            raise ValueError(f"unsupported join type {self.join_type!r}")
        # per side: a schema-shaped all-NULL row used to pad the opposite
        # side of an unmatched outer row (fields present, values None — so
        # downstream predicates/projections see SQL NULL, not a missing key)
        self.null_rows: Tuple[dict, dict] = tuple(
            t.config.get("null_rows") or ({}, {}))
        self.uid = t.uid
        # per side: key -> {frozen_row: [row, count]}
        self._state: Tuple[Dict, Dict] = ({}, {})
        # outer-side keys currently padded with NULLs: key -> {frozen: [row, count]}
        self._padded: Dict[Any, Dict] = {}
        self._out: List[dict] = []
        self._out_ts: List[int] = []

    def on_batch(self, values, timestamps) -> None:  # pragma: no cover
        raise AssertionError("StreamingJoinRunner consumes via input gates")

    # -- join ----------------------------------------------------------------
    def _merge(self, ordinal: int, mine: dict, other: dict) -> dict:
        return (self.merge_fn(mine, other) if ordinal == 0
                else self.merge_fn(other, mine))

    def _emit(self, row: dict, kind: str, ts: int) -> None:
        out = dict(row)
        out[ROW_KIND_FIELD] = kind
        self._out.append(out)
        self._out_ts.append(ts)

    def _outer_side(self) -> int:
        return {"left": 0, "right": 1}.get(self.join_type, -1)

    def _null_pad(self, ordinal: int, row: dict) -> dict:
        """(row, NULL) padding for the outer side: merge against the
        opposite side's all-NULL schema row."""
        return self._merge(ordinal, row, self.null_rows[1 - ordinal])

    def on_batch_n(self, ordinal: int, values, timestamps) -> None:
        counter = getattr(self, "records_in_counter", None)
        if counter is not None:
            counter.inc(len(timestamps))
        ks = self.key_selectors[ordinal]
        mine, other = self._state[ordinal], self._state[1 - ordinal]
        outer = self._outer_side()
        for v, ts_np in zip(values, np.asarray(timestamps, dtype=np.int64)):
            ts = int(ts_np)
            kind = row_kind(v)
            row = strip_kind(v)
            key = ks(row)
            f = _freeze(row)
            # SQL equi-join: NULL never matches (not even NULL = NULL) —
            # a NULL-keyed row joins nothing; on the outer side it stays a
            # NULL-padded row for its whole lifetime
            if key is None and ordinal != outer:
                # on every OTHER side (both sides of an inner join, the
                # non-outer side of an outer join) a NULL-keyed row can
                # never match and never pads: buffering it would only grow
                # state without bound under NULL-keyed streams, so inserts
                # and their retractions pass through without touching state
                if not (is_additive(kind) or is_retractive(kind)):
                    raise ValueError(f"unknown row kind {kind!r}")
                continue
            matches = None if key is None else other.get(key)
            if is_additive(kind):
                if matches:
                    for orow, cnt in matches.values():
                        joined = self._merge(ordinal, row, orow)
                        for _ in range(cnt):
                            self._emit(joined, INSERT, ts)
                    if ordinal != outer and 1 - ordinal == outer:
                        # first match(es) arrived for padded outer rows:
                        # retract their NULL paddings
                        padded = self._padded.pop(key, None)
                        if padded:
                            for orow, cnt in padded.values():
                                pad = self._null_pad(1 - ordinal, orow)
                                for _ in range(cnt):
                                    self._emit(pad, DELETE, ts)
                elif ordinal == outer:
                    self._emit(self._null_pad(ordinal, row), INSERT, ts)
                    slot = self._padded.setdefault(key, {})
                    ent = slot.setdefault(f, [row, 0])
                    ent[1] += 1
                bucket = mine.setdefault(key, {})
                ent = bucket.setdefault(f, [row, 0])
                ent[1] += 1
            elif is_retractive(kind):
                bucket = mine.get(key)
                if bucket is None or f not in bucket:
                    raise ValueError(
                        f"join input retracts a row that is not buffered: "
                        f"{row!r}")
                ent = bucket[f]
                ent[1] -= 1
                if ent[1] == 0:
                    del bucket[f]
                    if not bucket:
                        del mine[key]
                if matches:
                    for orow, cnt in matches.values():
                        joined = self._merge(ordinal, row, orow)
                        for _ in range(cnt):
                            self._emit(joined, DELETE, ts)
                elif ordinal == outer:
                    self._emit(self._null_pad(ordinal, row), DELETE, ts)
                padded = self._padded.get(key)
                if padded is not None and ordinal == outer and f in padded:
                    padded[f][1] -= 1
                    if padded[f][1] == 0:
                        del padded[f]
                        if not padded:
                            del self._padded[key]
                if key is not None and 1 - ordinal == outer and (
                        bucket is None or key not in mine):
                    # this side's buffer for the key just emptied: the outer
                    # side's surviving rows fall back to NULL paddings
                    surv = other.get(key)
                    if surv:
                        for orow, cnt in surv.values():
                            pad = self._null_pad(1 - ordinal, orow)
                            for _ in range(cnt):
                                self._emit(pad, INSERT, ts)
                            slot = self._padded.setdefault(key, {})
                            ent2 = slot.setdefault(_freeze(orow), [orow, 0])
                            ent2[1] += cnt
            else:
                raise ValueError(f"unknown row kind {kind!r}")
        self._flush()

    def _flush(self) -> None:
        if self._out and self.downstream:
            self.downstream.on_batch(
                obj_array(self._out),
                np.asarray(self._out_ts, dtype=np.int64))
        self._out, self._out_ts = [], []

    # -- checkpointing -------------------------------------------------------
    def snapshot(self) -> dict:
        def dump(side):
            return {k: {f: (row, cnt) for f, (row, cnt) in b.items()}
                    for k, b in side.items()}

        return {"left": dump(self._state[0]), "right": dump(self._state[1]),
                "padded": dump(self._padded)}

    def restore(self, snap: dict) -> None:
        def load(d):
            return {k: {f: [row, cnt] for f, (row, cnt) in b.items()}
                    for k, b in d.items()}

        self._state = (load(snap["left"]), load(snap["right"]))
        self._padded = load(snap.get("padded", {}))
