"""Internal timer service: per-key, per-namespace event/processing-time timers.

Reference: InternalTimerServiceImpl.java:45 — priority queues of
InternalTimer(key, namespace, time); event-time timers fire when the
watermark advances past them (advanceWatermark:314); timers are exact-once
per (key, namespace, time) (set semantics).
"""

from __future__ import annotations

import heapq
from typing import Any, Callable, List, Set, Tuple

from flink_tpu.core.time import MIN_WATERMARK

Timer = Tuple[int, Any, Any]  # (time, key, namespace)


class _TimerQueue:
    """Min-heap on time with insertion-order tiebreak (keys/namespaces need
    not be orderable); set-dedup per (time, key, namespace)."""

    def __init__(self):
        self._heap: List[Tuple[int, int, Timer]] = []
        self._set: Set[Timer] = set()
        self._seq = 0

    def add(self, timer: Timer) -> None:
        if timer not in self._set:
            self._set.add(timer)
            heapq.heappush(self._heap, (timer[0], self._seq, timer))
            self._seq += 1

    def remove(self, timer: Timer) -> None:
        self._set.discard(timer)  # lazily skipped on pop

    def peek_time(self):
        while self._heap and self._heap[0][2] not in self._set:
            heapq.heappop(self._heap)
        return self._heap[0][0] if self._heap else None

    def pop_until(self, time_inclusive: int) -> List[Timer]:
        out = []
        while True:
            t = self.peek_time()
            if t is None or t > time_inclusive:
                break
            _, _, timer = heapq.heappop(self._heap)
            self._set.discard(timer)
            out.append(timer)
        return out

    def all_timers(self) -> List[Timer]:
        return list(self._set)

    def restore(self, timers: List[Timer]) -> None:
        self._heap = []
        self._set = set()
        self._seq = 0
        for t in timers:
            self.add(t)


class InternalTimerService:
    """Timers keyed by (time, key, namespace); callbacks receive (time, key, ns)."""

    def __init__(
        self,
        on_event_time: Callable[[int, Any, Any], None],
        on_processing_time: Callable[[int, Any, Any], None],
    ):
        self._event = _TimerQueue()
        self._proc = _TimerQueue()
        self._on_event_time = on_event_time
        self._on_processing_time = on_processing_time
        self.current_watermark = MIN_WATERMARK

    # -- registration (key must be provided by caller: operator fixes it) --
    def register_event_time_timer(self, key, namespace, time: int) -> None:
        self._event.add((time, key, namespace))

    def delete_event_time_timer(self, key, namespace, time: int) -> None:
        self._event.remove((time, key, namespace))

    def register_processing_time_timer(self, key, namespace, time: int) -> None:
        self._proc.add((time, key, namespace))

    def delete_processing_time_timer(self, key, namespace, time: int) -> None:
        self._proc.remove((time, key, namespace))

    # -- advance ----------------------------------------------------------
    def advance_watermark(self, watermark: int) -> None:
        """Fires all event-time timers with time <= watermark, in time order
        (InternalTimerServiceImpl.advanceWatermark:314)."""
        self.current_watermark = watermark
        # timers registered while firing (e.g. by trigger re-registration)
        # must also fire if eligible — loop until drained
        while True:
            due = self._event.pop_until(watermark)
            if not due:
                break
            for time, key, ns in due:
                self._on_event_time(time, key, ns)

    def advance_processing_time(self, time: int) -> None:
        while True:
            due = self._proc.pop_until(time)
            if not due:
                break
            for t, key, ns in due:
                self._on_processing_time(t, key, ns)

    def next_event_time_timer(self):
        return self._event.peek_time()

    def next_processing_time_timer(self):
        return self._proc.peek_time()

    # -- snapshot ---------------------------------------------------------
    def snapshot(self) -> dict:
        return {
            "event": self._event.all_timers(),
            "proc": self._proc.all_timers(),
            "watermark": self.current_watermark,
        }

    def restore(self, snap: dict) -> None:
        self._event.restore(list(map(tuple, snap["event"])))
        self._proc.restore(list(map(tuple, snap["proc"])))
        self.current_watermark = snap["watermark"]
