"""Device global-window operator with count-based triggers.

GlobalWindows + CountTrigger (the Nexmark Q7-style keyed pre-aggregation
pattern: GlobalWindows.java + CountTrigger.java, fired per key every N
elements, optionally purging via PurgingTrigger) on the columnar state:
accumulators are [K, 1] columns; after each batch ingest, keys whose count
reached N fire in ONE masked extract, and purging resets exactly the fired
rows — all in a single fused program.

Batching semantics (documented deviation, same family as the window
operator's late-refire coalescing): a key crossing multiple N-multiples
within one batch fires once per batch with its current accumulator, not once
per multiple; the per-record oracle remains the exact-semantics path. With
per-record batches the two coincide (property-tested).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flink_tpu.api.windowing.assigners import GlobalWindow, GlobalWindows
from flink_tpu.api.windowing.triggers import CountTrigger, PurgingTrigger, Trigger
from flink_tpu.core.time import MAX_WATERMARK, MIN_WATERMARK
from flink_tpu.lint.contracts import inflight_ring
from flink_tpu.ops import segment_ops
from flink_tpu.ops.aggregators import DeviceAggregator, ONE, resolve
from flink_tpu.state.columnar import KeyDictionary


@functools.lru_cache(maxsize=None)
def _make_step(agg: DeviceAggregator, purge: bool):
    """ingest+fire: (acc {f:[K]}, count i32[K], fired_count i32[K],
    kid i32[B], vals f32[B], n) -> (acc', count', fired', result[K], mask[K])

    `count` counts elements since last purge; `fired_count` tracks the last
    fire multiple for non-purging triggers (fire when count crosses a new
    multiple of n)."""

    def step(acc, count, fired_count, kid, vals, n):
        new_acc = {}
        for f in agg.fields:
            src = jnp.ones(vals.shape, dtype=f.dtype) if f.source == ONE else vals.astype(f.dtype)
            ref = acc[f.name].at[kid]
            op = {"add": ref.add, "min": ref.min, "max": ref.max}[f.scatter]
            new_acc[f.name] = op(src, mode="drop")
        new_count = count.at[kid].add(jnp.ones(kid.shape, dtype=count.dtype), mode="drop")
        mask = (new_count // n) > (fired_count // n) if not purge else new_count >= n
        result = agg.extract(new_acc).astype(agg.result_dtype)
        if purge:
            out_acc = {}
            for f in agg.fields:
                ident = jnp.full_like(new_acc[f.name], f.identity)
                out_acc[f.name] = jnp.where(mask, ident, new_acc[f.name])
            out_count = jnp.where(mask, 0, new_count)
            new_fired = fired_count
        else:
            out_acc = new_acc
            out_count = new_count
            new_fired = jnp.where(mask, new_count, fired_count)
        return out_acc, out_count, new_fired, result, mask

    return jax.jit(step, donate_argnums=(0, 1, 2))


def supported_trigger(trigger) -> Optional[Tuple[int, bool]]:
    """(n, purging) when the trigger is CountTrigger or
    PurgingTrigger(CountTrigger); None otherwise."""
    if isinstance(trigger, PurgingTrigger) and isinstance(trigger.inner, CountTrigger):
        return trigger.inner.max_count, True
    if isinstance(trigger, CountTrigger):
        return trigger.max_count, False
    return None


@inflight_ring("_pending", drained_by="flush")
class TpuGlobalWindowOperator:
    """Duck-types the window-operator runner interface."""

    _WINDOW = GlobalWindow()

    def __init__(
        self,
        aggregate,
        *,
        count_n: int,
        purging: bool,
        key_capacity: int = 1 << 12,
        dense_int_keys: bool = False,
        batch_pad: int = 256,
    ):
        agg = resolve(aggregate)
        if agg is None:
            raise ValueError(f"{aggregate!r} has no device form")
        self.agg = agg
        self.n = count_n
        self.purging = purging
        self.K = key_capacity
        self.batch_pad = batch_pad
        self.keydict = KeyDictionary(dense_int_keys)
        self._step = _make_step(agg, purging)
        self._init_arrays()
        self.current_watermark = MIN_WATERMARK
        self.emission_tracker = None   # emission-latency plane (runner-set)
        self._pending: List[Tuple[Any, Any, int]] = []
        self.output: List[Tuple[Any, Any, Any, int]] = []
        self.side_output: Dict[str, List] = {}
        self.num_late_records_dropped = 0

    def _init_arrays(self):
        self.acc = {
            f.name: jnp.full((self.K,), f.identity, dtype=f.dtype) for f in self.agg.fields
        }
        self.count = jnp.zeros((self.K,), dtype=jnp.int32)
        self.fired = jnp.zeros((self.K,), dtype=jnp.int32)

    def _grow(self, required: int) -> None:
        if required <= self.K:
            return
        new_k = self.K
        while new_k < required:
            new_k *= 2
        pad = new_k - self.K
        for f in self.agg.fields:
            filler = jnp.full((pad,), f.identity, dtype=f.dtype)
            self.acc[f.name] = jnp.concatenate([self.acc[f.name], filler])
        self.count = jnp.concatenate([self.count, jnp.zeros((pad,), jnp.int32)])
        self.fired = jnp.concatenate([self.fired, jnp.zeros((pad,), jnp.int32)])
        self.K = new_k

    # -- runner interface --------------------------------------------------
    def process_record(self, key, value, timestamp: int) -> None:
        self._pending.append((key, value, timestamp))

    def process_batch(self, keys: np.ndarray, values: np.ndarray, timestamps) -> None:
        self.flush()
        self._ingest(keys, values.astype(np.float32))

    def flush(self) -> None:
        if not self._pending:
            return
        pend, self._pending = self._pending, []
        keys = np.empty(len(pend), dtype=object)
        keys[:] = [p[0] for p in pend]
        vals = np.asarray([p[1] for p in pend], dtype=np.float32)
        self._ingest(keys, vals)

    def _ingest(self, keys: np.ndarray, vals: np.ndarray) -> None:
        if len(keys) == 0:
            return
        ids, required = self.keydict.lookup_or_insert(keys)
        self._grow(required)
        n = len(ids)
        padded = self.batch_pad
        while padded < n:
            padded *= 2
        kid = np.full(padded, segment_ops.INVALID_INDEX, dtype=np.int32)
        kid[:n] = ids
        v = np.zeros(padded, dtype=np.float32)
        v[:n] = vals
        self.acc, self.count, self.fired, result, mask = self._step(
            self.acc, self.count, self.fired, kid, v, self.n
        )
        mask_np = np.asarray(mask)
        if mask_np.any():
            result_np = np.asarray(result)
            fired = np.flatnonzero(mask_np)
            if self.emission_tracker is not None:
                # count-triggered GlobalWindow fires have no event-time
                # close: MAX_WATERMARK would poison the histogram, so the
                # tracker's int64-safe clamp counts them as `sentinel`
                self.emission_tracker.record_fire(
                    MAX_WATERMARK, count=len(fired))
            for i in fired:
                self.output.append(
                    (self.keydict.key_at(int(i)), self._WINDOW, result_np[i].item(),
                     MAX_WATERMARK)
                )

    def process_watermark(self, watermark: int) -> None:
        self.flush()
        self.current_watermark = max(self.current_watermark, watermark)

    def advance_processing_time(self, time: int) -> None:
        pass

    def drain_output(self):
        out = self.output
        self.output = []
        return out

    # -- observability gauges ---------------------------------------------
    def state_bytes(self) -> int:
        n = sum(int(getattr(a, "nbytes", 0)) for a in self.acc.values())
        n += int(getattr(self.count, "nbytes", 0))
        n += int(getattr(self.fired, "nbytes", 0))
        return n

    def state_key_count(self) -> int:
        return len(self.keydict)

    # -- snapshot ---------------------------------------------------------
    def snapshot(self) -> dict:
        self.flush()
        return {
            "acc": {k: np.asarray(v) for k, v in self.acc.items()},
            "count": np.asarray(self.count),
            "fired": np.asarray(self.fired),
            "keydict": self.keydict.snapshot(),
            "K": self.K,
            "watermark": self.current_watermark,
        }

    def restore(self, snap: dict) -> None:
        self.K = snap["K"]
        self.acc = {k: jnp.asarray(v) for k, v in snap["acc"].items()}
        self.count = jnp.asarray(snap["count"])
        self.fired = jnp.asarray(snap["fired"])
        self.keydict = KeyDictionary.restore(snap["keydict"])
        self.current_watermark = snap["watermark"]
        self._pending = []
        self.output = []
