"""Device-path session windows: per-slice fragments + vectorized gap-merge.

The reference merges session windows per record through MergingWindowSet
(WindowOperator.java:303-403, EventTimeSessionWindows.java): each element's
[ts, ts+gap) window is merged with intersecting in-flight windows, state
namespaces are merged, and the merged window's trigger fires when the
watermark passes its end.

The TPU-native re-design exploits one invariant: with slice width == gap,
ALL events that land in the same slice belong to the same session (any two
timestamps in a slice differ by < gap). Ingest therefore needs no merge
logic at all — it is the same columnar scatter as the sliced aggregates,
accumulating per-(key, slice) *fragments*:

    count[k, s], min_rel[k, s], max_rel[k, s], field[k, s]...

(min/max are stored slice-relative so int32 device arithmetic never
overflows millisecond timestamps). Merging collapses to a LINEAR SCAN over
the slice axis: fragment s+i joins the current session iff
``min_ts(frag) - max_ts(session) < gap``; the scan is vectorized over the
whole key dimension at once (numpy [K]-wide ops per slice column, ~S tiny
ops per watermark instead of per-record hash-map surgery). A session is
emitted when a later fragment proves a gap, or when the watermark passes
``max_ts + gap - 1``; emitted cells purge, open sessions stay resident.

Late contract: a record whose standalone session is already expired
(ts + gap - 1 <= watermark) is dropped and counted, matching the oracle
whenever the stream's out-of-orderness is below the session gap (the
merging analogue of isWindowLate, WindowOperator.java:609). Streams with
out-of-orderness >= gap should use the oracle operator, which implements
the order-dependent late-merge semantics exactly.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.api.windowing.assigners import EventTimeSessionWindows
from flink_tpu.core.time import MIN_WATERMARK, TimeWindow
from flink_tpu.lint.contracts import inflight_ring
from flink_tpu.ops.aggregators import DeviceAggregator, VALUE, resolve
from flink_tpu.state.columnar import KeyDictionary

_NP_COMBINE = {"add": np.add, "min": np.minimum, "max": np.maximum}


@functools.lru_cache(maxsize=None)
def _build_ingest(K: int, S: int, B: int, vfields: tuple):
    # the scatter body lives in ops/superscan.session_ingest_scatter — ONE
    # copy shared with the fused superspan, so the overflow-replay path is
    # bit-identical to the dispatch it replaces by construction
    import jax

    from flink_tpu.ops.superscan import session_ingest_scatter

    return jax.jit(session_ingest_scatter(K, S, vfields))


@functools.lru_cache(maxsize=None)
def _build_take(nf: int):
    """Gather the resident span's columns: one int stack + field tuple."""
    import jax
    import jax.numpy as jnp

    def run(cnt, mn, mx, fields, pos):
        ints = jnp.stack([cnt[:, pos], mn[:, pos], mx[:, pos]])
        return ints, tuple(f[:, pos] for f in fields)

    return jax.jit(run)


@functools.lru_cache(maxsize=None)
def _build_precheck(g: int):
    """Scalar 'any session closable at wm?' test — a fragment whose
    max_ts + g - 1 <= wm must exist for any emission to be possible, so the
    expensive span pull + merge scan is skipped (one bool crosses the link)
    while every resident session is provably still open. `valid` masks the
    bucket padding (positions are padded to pow2 lengths so each bucket
    size compiles ONCE — an unpadded span length would retrace per call)."""
    import jax
    import jax.numpy as jnp

    def run(cnt, mx, pos, s_rel, wm_rel, valid):
        c = cnt[:, pos]
        m = mx[:, pos] + s_rel[None, :] * g
        return jnp.any((c > 0) & (m + g - 1 <= wm_rel) & valid[None, :])

    return jax.jit(run)


@functools.lru_cache(maxsize=None)
def _build_merge_scan(K: int, S: int, P: int, M: int, g: int, vfields: tuple,
                      idents: tuple):
    """The WHOLE watermark path as one device program: gather the resident
    span, run the gap-merge scan [K]-wide over its P slices, write closed
    sessions into M fixed emission slots per key, purge their cells, and
    return the updated ring plus compact emission arrays — ONE dispatch and
    one D2H instead of (precheck + span pull + host python scan + purge
    scatter). The scan is a static python loop over P (P <= 64, bucketed
    pow2), so XLA sees straight-line [K]-wide ops it can fuse.

    Returns (cnt, mn, mx, fields, e_start, e_end, e_cnt, e_fields [K,M],
    e_n [K], overflow, lo_rel, hi_rel): e_* rel-ms coordinates against the
    span base; overflow=True means a key closed more than M sessions in one
    scan — the caller falls back to the exact host path (state unmodified
    because the returned arrays are simply discarded)."""
    import jax
    import jax.numpy as jnp

    from flink_tpu.ops.superscan import session_gap_merge_scan

    def run(cnt, mn, mx, fields, pos, valid, wm_rel):
        i32 = jnp.int32
        idx_p = jnp.arange(P, dtype=i32)
        vmask = valid[None, :]
        c = jnp.where(vmask, cnt[:, pos], 0)              # [K, P]
        fmn = mn[:, pos] + idx_p[None, :] * g
        fmx = mx[:, pos] + idx_p[None, :] * g
        fl = [f[:, pos] for f in fields]

        slots = jnp.zeros((K,), i32)                      # next emit slot
        e_start = jnp.zeros((K, M), i32)
        e_end = jnp.zeros((K, M), i32)
        e_cnt = jnp.zeros((K, M), i32)
        e_s0 = jnp.zeros((K, M), i32)                     # cell range for purge
        e_s1 = jnp.full((K, M), -1, i32)
        e_flds = [jnp.full((K, M), ident, f.dtype)
                  for f, ident in zip(fl, idents)]
        overflow = jnp.zeros((), bool)
        mslots = jnp.arange(M, dtype=i32)[None, :]

        # the scan body lives in ops/superscan.session_gap_merge_scan —
        # the ONE copy both this per-watermark program and the fused
        # superspan's in-carry merges compile, so the overflow-replay
        # parity contract cannot drift between them
        est = session_gap_merge_scan(
            c, fmn, fmx, fl, vfields, idents, g, wm_rel,
            (slots, e_start, e_end, e_cnt, e_s0, e_s1, e_flds, overflow))
        (slots, e_start, e_end, e_cnt, e_s0, e_s1, e_flds, overflow) = est

        # purge the emitted sessions' cells, write the span back
        cover = (idx_p[None, None, :] >= e_s0[:, :, None]) & \
                (idx_p[None, None, :] <= e_s1[:, :, None]) & \
                (mslots[:, :, None] < slots[:, None, None])
        purge = jnp.any(cover, axis=1) & vmask            # [K, P]
        c_new = jnp.where(purge, 0, c)
        # write back through DROPPED pad columns: pos carries duplicate
        # padded indices, and a duplicate scatter-set of the unpurged
        # original would undo the purge of the highest resident slice
        pos_w = jnp.where(valid, pos, S)
        cnt = cnt.at[:, pos_w].set(c_new, mode="drop")
        mn = mn.at[:, pos_w].set(
            jnp.where(purge, g, mn[:, pos]), mode="drop")
        mx = mx.at[:, pos_w].set(
            jnp.where(purge, -1, mx[:, pos]), mode="drop")
        fields = tuple(
            f.at[:, pos_w].set(
                jnp.where(purge, jnp.asarray(ident, f.dtype), f[:, pos]),
                mode="drop")
            for f, ident in zip(fields, idents)
        )
        live = jnp.any(c_new > 0, axis=0) & valid          # [P]
        lo_rel = jnp.min(jnp.where(live, idx_p, P))
        hi_rel = jnp.max(jnp.where(live, idx_p, -1))

        # ONE packed i32 result so a deferred resolve costs a single D2H:
        # [K+1, (3+nf)*M + 1] = start|end|cnt|fields(bitcast)… blocks, last
        # column = per-key emit count, extra row = [lo_rel, hi_rel, overflow]
        blocks = [e_start, e_end, e_cnt]
        for ef in e_flds:
            blocks.append(jax.lax.bitcast_convert_type(
                ef, jnp.int32) if ef.dtype != jnp.int32 else ef)
        packed = jnp.concatenate(blocks + [slots[:, None]], axis=1)
        scal = jnp.zeros((1, packed.shape[1]), jnp.int32)
        scal = scal.at[0, 0].set(lo_rel)
        scal = scal.at[0, 1].set(hi_rel)
        scal = scal.at[0, 2].set(overflow.astype(jnp.int32))
        packed = jnp.concatenate([packed, scal], axis=0)
        return cnt, mn, mx, fields, packed

    return jax.jit(run)


@functools.lru_cache(maxsize=None)
def _build_purge(K: int, S: int, nf: int, idents: tuple, dts: tuple, g: int):
    import jax
    import jax.numpy as jnp

    def run(cnt, mn, mx, fields, keep_mask):
        cnt = jnp.where(keep_mask, cnt, 0)
        mn = jnp.where(keep_mask, mn, g)
        mx = jnp.where(keep_mask, mx, -1)
        new_fields = tuple(
            jnp.where(keep_mask, f, jnp.asarray(ident, dt))
            for f, ident, dt in zip(fields, idents, dts)
        )
        return cnt, mn, mx, new_fields

    return jax.jit(run)


@inflight_ring("_pending", drained_by="_resolve_pending")
class TpuSessionWindowOperator:
    """One shard's keyed session-window aggregation on one device."""

    # emission-latency plane: set by the runner; stamped where merged
    # sessions become host rows (deferred-resolve and host-path emits)
    emission_tracker = None

    def __init__(
        self,
        assigner: EventTimeSessionWindows,
        aggregate,
        *,
        key_capacity: int = 1 << 12,
        num_slices: int = 64,
        batch_pad: int = 256,
        defer_emissions: bool = False,
    ):
        agg = resolve(aggregate)
        if agg is None:
            raise ValueError(f"aggregate {aggregate!r} has no device form")
        for f in agg.fields:
            if f.source == VALUE and f.scatter not in ("add", "min", "max"):
                raise ValueError(f"unsupported scatter {f.scatter!r}")
        if not assigner.is_event_time:
            raise ValueError("TpuSessionWindowOperator is event-time only")
        self.agg: DeviceAggregator = agg
        self.g = assigner.gap
        self.S = num_slices
        self.batch_pad = batch_pad
        self.keydict = KeyDictionary()
        self.K = key_capacity

        self._vfields = tuple(
            (f.name, np.dtype(f.dtype).name, f.scatter)
            for f in agg.fields
            if f.source == VALUE
        )
        self._idents = tuple(
            f.identity for f in agg.fields if f.source == VALUE
        )
        self._init_state()

        self.current_watermark = MIN_WATERMARK
        self.ring_lo: Optional[int] = None     # lowest resident slice
        self.max_used: Optional[int] = None
        self._future: List[Tuple[Any, float, int]] = []
        self.output: List[Tuple[Any, Any, Any, int]] = []
        self.num_late_records_dropped = 0
        # deferred-emission mode (the DeferredEmissions pattern of the fused
        # pipeline): watermark merge scans are enqueued WITHOUT a device
        # sync; the packed emission arrays resolve at drain_output (or when
        # the ring needs fresh bounds). Ring bookkeeping stays conservative
        # (stale-low ring_lo only widens the next scan's span over provably
        # empty slices).
        self.defer_emissions = defer_emissions
        self._pending: List[dict] = []
        self._since_dispatch: Optional[Tuple[int, int]] = None

    # ------------------------------------------------------------------
    def _init_state(self) -> None:
        import jax.numpy as jnp

        K, S = self.K, self.S
        self._cnt = jnp.zeros((K, S), jnp.int32)
        self._mn = jnp.full((K, S), self.g, jnp.int32)    # identity: > any rel
        self._mx = jnp.full((K, S), -1, jnp.int32)
        self._fields = tuple(
            jnp.full((K, S), ident, jnp.dtype(dt))
            for (_n, dt, _s), ident in zip(self._vfields, self._idents)
        )

    def ensure_key_capacity(self, required: int) -> None:
        if required <= self.K:
            return
        import jax.numpy as jnp

        new_k = 1 << (required - 1).bit_length()
        pad = new_k - self.K

        def grow(arr, fill, dt):
            return jnp.concatenate(
                [arr, jnp.full((pad, self.S), fill, dt)]
            )

        self._cnt = grow(self._cnt, 0, jnp.int32)
        self._mn = grow(self._mn, self.g, jnp.int32)
        self._mx = grow(self._mx, -1, jnp.int32)
        self._fields = tuple(
            grow(f, ident, f.dtype)
            for f, ident in zip(self._fields, self._idents)
        )
        self.K = new_k

    # ------------------------------------------------------------------
    def process_record(self, key, value, timestamp: int) -> None:
        self.process_batch(
            np.asarray([key]), np.asarray([value], dtype=np.float32),
            np.asarray([timestamp], dtype=np.int64),
        )

    def process_batch(self, keys: np.ndarray, vals: np.ndarray,
                      ts: np.ndarray) -> None:
        ts = np.asarray(ts, dtype=np.int64)
        if len(ts) == 0:
            return
        if getattr(self, "_dense", False):
            raise ValueError(
                "process_batch (keydict path) cannot be mixed with "
                "process_batch_staged dense ids on one operator"
            )
        vals = np.asarray(vals, dtype=np.float32)
        wm = self.current_watermark

        # standalone-expired records are late (see module docstring)
        late = ts + self.g - 1 <= wm
        if late.any():
            self.num_late_records_dropped += int(late.sum())
            keep = ~late
            keys, vals, ts = keys[keep], vals[keep], ts[keep]
            if len(ts) == 0:
                return

        s_abs = ts // self.g
        # the ring floor this batch will actually occupy: its own lowest
        # slice can move ring_lo DOWN, so the overflow check must use the
        # post-batch floor or aliased positions corrupt state
        lo = int(s_abs.min())
        if self.ring_lo is not None:
            lo = min(self.ring_lo, lo)
        if self._pending and self.max_used is not None \
                and self.max_used - lo >= self.S:
            self._resolve_pending()    # stale bounds: learn the truth first
            lo = int(s_abs.min())
            if self.ring_lo is not None:
                lo = min(self.ring_lo, lo)
        if self.max_used is not None and self.max_used - lo >= self.S:
            # a record this far BELOW resident fragments cannot be ingested
            # (existing cells cannot be held back retroactively) — the
            # resident span must fit the ring, same contract as the fused
            # pipeline's inverted-skew check
            raise ValueError(
                f"session slice ring too small for this skew: batch slice "
                f"{lo} is {self.max_used - lo} gap-slices below resident "
                f"slice {self.max_used}, ring holds num_slices={self.S}. "
                f"Raise num_slices above the expected out-of-orderness "
                f"(in units of the session gap)."
            )
        # ring overflow: far-future records wait on host until purge opens
        # space (same hold-back contract as TpuWindowOperator._future)
        over = s_abs >= lo + self.S
        if over.any() and self._pending:
            # stale deferred bounds must not park records sync mode would
            # ingest (parking past a watermark advance turns them late)
            self._resolve_pending()
            lo = int(s_abs.min())
            if self.ring_lo is not None:
                lo = min(self.ring_lo, lo)
            over = s_abs >= lo + self.S
        if over.any():
            for i in np.flatnonzero(over):
                self._future.append((keys[i], float(vals[i]), int(ts[i])))
            keep = ~over
            keys, vals, ts, s_abs = keys[keep], vals[keep], ts[keep], s_abs[keep]
            if len(ts) == 0:
                return

        ids, required = self.keydict.lookup_or_insert(keys)
        self.ensure_key_capacity(required)

        n = len(ts)
        padded = self.batch_pad
        while padded < n:
            padded *= 2
        kid = np.full(padded, -1, dtype=np.int32)
        kid[:n] = ids.astype(np.int32)
        spos = np.zeros(padded, dtype=np.int32)
        spos[:n] = (s_abs % self.S).astype(np.int32)
        rel = np.zeros(padded, dtype=np.int32)
        rel[:n] = (ts - s_abs * self.g).astype(np.int32)
        v = np.zeros(padded, dtype=np.float32)
        v[:n] = vals

        run = _build_ingest(self.K, self.S, padded, self._vfields)
        self._cnt, self._mn, self._mx, self._fields = run(
            self._cnt, self._mn, self._mx, self._fields, kid, spos, rel, v,
        )

        smin, smax = int(s_abs.min()), int(s_abs.max())
        self.ring_lo = smin if self.ring_lo is None else min(self.ring_lo, smin)
        self.max_used = smax if self.max_used is None else max(self.max_used, smax)
        self._track_ingest(smin, smax)

    def _track_ingest(self, smin: int, smax: int) -> None:
        """Record post-dispatch ingest bounds so a deferred merge scan's
        resolved ring bounds can be merged with what arrived after it."""
        s = self._since_dispatch
        self._since_dispatch = (
            (smin, smax) if s is None else (min(s[0], smin), max(s[1], smax))
        )

    def process_batch_staged(self, kid, spos, rel, vals,
                             smin: int, smax: int) -> None:
        """Device-staged dense-key ingest: `kid`/`spos`/`rel`/`vals` are
        device int32/float32 arrays already in ring coordinates (kid < the
        declared key capacity or -1 to drop, spos = abs_slice % S, rel =
        ts - abs_slice*gap). The caller guarantees no record is late and
        that [smin, smax] keeps the resident span inside the ring — this is
        the zero-host-copy path for device-side sources (the session
        analogue of FusedWindowPipeline.plan_superbatch staging)."""
        self._sync_superspan()
        lo = smin if self.ring_lo is None else min(self.ring_lo, smin)
        if self._pending and (
            (self.max_used is not None and self.max_used - lo >= self.S)
            or smax - lo >= self.S
        ):
            # deferred-mode bookkeeping is conservative (stale-low ring_lo);
            # resolve to learn the true bounds before declaring overflow
            self._resolve_pending()
            lo = smin if self.ring_lo is None else min(self.ring_lo, smin)
        if (self.max_used is not None and self.max_used - lo >= self.S) or (
                smax - lo >= self.S):
            raise ValueError(
                f"session slice ring too small: span [{lo}, "
                f"{max(smax, self.max_used or smax)}] exceeds num_slices={self.S}"
            )
        if len(self.keydict) > 0:
            raise ValueError(
                "process_batch_staged (dense ids) cannot be mixed with the "
                "keydict-backed process_batch path on one operator"
            )
        self._dense = True
        run = _build_ingest(self.K, self.S, int(kid.shape[0]), self._vfields)
        self._cnt, self._mn, self._mx, self._fields = run(
            self._cnt, self._mn, self._mx, self._fields, kid, spos, rel, vals,
        )
        self.ring_lo = lo
        self.max_used = smax if self.max_used is None else max(self.max_used, smax)
        self._track_ingest(smin, smax)

    def _key_of(self, kid: int):
        return kid if getattr(self, "_dense", False) else self.keydict.key_at(kid)

    # ------------------------------------------------------------------
    # fused superspan: T staged ingest steps + in-scan gap-merges, ONE
    # device dispatch and ONE packed readback (ops/superscan.
    # make_session_superscan) — sessions merge in the scan carry and
    # never round-trip to host per watermark
    # ------------------------------------------------------------------
    MAX_SUPERSPAN_SLOTS = 40

    def process_superspan_staged(self, kid, spos, rel, vals,
                                 step_bounds, merge_wms) -> None:
        """Device-staged fused superspan: `kid`/`spos`/`rel`/`vals` are
        [T, B] device arrays in ring coordinates (the per-step contract of
        process_batch_staged, stacked), `step_bounds[t] = (smin, smax)`
        the step's absolute gap-slice bounds, and `merge_wms[t]` the
        watermark to gap-merge at after step t (None = ingest-only step).

        The whole superspan — every ingest and every merge — is ONE
        compiled dispatch; closed sessions accumulate into M emission
        slots per key and come back as one packed array, resolved
        deferred exactly like the per-watermark merge scans. Geometries
        the fused program cannot bound (emission slots past
        MAX_SUPERSPAN_SLOTS, rel-ms beyond int32) replay through the
        exact per-step path instead, and an in-dispatch slot overflow
        (pathological re-filled-slice churn) discards the fused result
        and replays from the retained pre-dispatch state — placement
        never changes a result."""
        import jax.numpy as jnp

        from flink_tpu.ops.superscan import make_session_superscan

        T = int(kid.shape[0])
        if not any(w is not None for w in merge_wms):
            raise ValueError("a superspan needs at least one merge step")
        if len(self.keydict) > 0:
            raise ValueError(
                "process_superspan_staged (dense ids) cannot be mixed with "
                "the keydict-backed process_batch path on one operator"
            )
        self._dense = True
        self._resolve_pending()   # learn true bounds; one dispatch in flight

        smin_all = min(b[0] for b in step_bounds)
        smax_all = max(b[1] for b in step_bounds)
        lo0 = smin_all if self.ring_lo is None else min(self.ring_lo, smin_all)
        hi = smax_all if self.max_used is None else max(self.max_used, smax_all)
        if hi - lo0 >= self.S:
            raise ValueError(
                f"session slice ring too small: superspan [{lo0}, {hi}] "
                f"exceeds num_slices={self.S}"
            )
        g = self.g
        span = hi - lo0 + 1
        n_merges = sum(1 for w in merge_wms if w is not None)
        # emission-slot bound: sessions closed per key per dispatch <=
        # fragments consumed <= span slices + per-merge re-fills; rounded
        # up to a multiple of 8 so streams whose per-dispatch span drifts
        # land on a few compiled shapes instead of one per distinct M
        M = -(-(span + n_merges + 2) // 8) * 8
        wm_last = max(w for w in merge_wms if w is not None)
        int32_ok = (span + 2) * g < (1 << 31) and \
            0 <= wm_last - lo0 * g < (1 << 31)
        dtypes_ok = all(
            np.dtype(dt) in (np.dtype(np.int32), np.dtype(np.float32))
            for _n, dt, _s in self._vfields)
        if M > min(self.S, self.MAX_SUPERSPAN_SLOTS) or not int32_ok \
                or not dtypes_ok:
            self._replay_superspan(kid, spos, rel, vals, step_bounds,
                                   merge_wms)
            return

        merge_flag = np.asarray(
            [1 if w is not None else 0 for w in merge_wms], np.int32)
        lo_pos = np.full(T, lo0 % self.S, np.int32)
        lo_rel = np.zeros(T, np.int32)
        wm_rel = np.asarray(
            [(w - lo0 * g) if w is not None else 0 for w in merge_wms],
            np.int32)

        old_state = (self._cnt, self._mn, self._mx, self._fields,
                     self.ring_lo, self.max_used, self.current_watermark)
        run = make_session_superscan(
            self.K, self.S, M, g, self._vfields, self._idents,
            T, int(kid.shape[1]))
        cnt2, mn2, mx2, flds2, packed = run(
            self._cnt, self._mn, self._mx, self._fields,
            kid, spos, rel, vals,
            jnp.asarray(merge_flag), jnp.asarray(lo_pos),
            jnp.asarray(lo_rel), jnp.asarray(wm_rel))
        self._cnt, self._mn, self._mx, self._fields = cnt2, mn2, mx2, flds2
        self.ring_lo = lo0          # stale-low; refreshed at resolve
        self.max_used = hi
        self.current_watermark = max(self.current_watermark, wm_last)
        self._since_dispatch = None   # packed live bounds are dispatch-final
        entry = {
            "packed": packed, "lo": lo0, "M": M, "watermark": wm_last,
            "old_state": old_state,
            "superspan": (kid, spos, rel, vals, step_bounds, merge_wms),
        }
        if self.defer_emissions:
            self._pending.append(entry)
            if self._future:
                self._resolve_pending()
        else:
            self._resolve_entry(entry, last=True)
        self._drain_future()

    def _replay_superspan(self, kid, spos, rel, vals, step_bounds,
                          merge_wms) -> None:
        """Exact per-step replay of a superspan (fused-path fallback and
        the overflow recovery path): per-step staged ingest + sync
        per-watermark merge scans — bit-identical semantics, more
        dispatches."""
        was_deferred, self.defer_emissions = self.defer_emissions, False
        try:
            for t in range(int(kid.shape[0])):
                self.process_batch_staged(
                    kid[t], spos[t], rel[t], vals[t], *step_bounds[t])
                if merge_wms[t] is not None:
                    self.process_watermark(merge_wms[t])
        finally:
            self.defer_emissions = was_deferred

    # ------------------------------------------------------------------
    def process_watermark(self, watermark: int) -> None:
        if watermark <= self.current_watermark:
            return
        self._sync_superspan()
        self.current_watermark = watermark
        if self.ring_lo is None:
            self._drain_future()
            return

        g, S = self.g, self.S
        lo, hi = self.ring_lo, self.max_used
        K = self.K
        span = hi - lo + 1
        P, pos_pad, valid = self._pad_span(lo, hi)
        import jax.numpy as jnp

        pos_d = jnp.asarray(pos_pad)

        if (P + 2) * g >= (1 << 31):
            # span-relative arithmetic would overflow int32 on device; the
            # host path needs resolved bounds and ordered output first
            self._resolve_pending()
            return self._watermark_host_path(watermark, lo, hi, span,
                                             pos_pad, valid)

        wm_rel = watermark - lo * g
        wm_c = int(np.clip(wm_rel, -(1 << 31) + 1, (1 << 31) - 1))
        if not self.defer_emissions:
            # cheap closable test before the merge dispatch: while no
            # fragment's standalone window has expired, nothing can emit
            # (break-closed sessions wait for the watermark to pass their
            # end — exactly the oracle's trigger time). Skipped in deferred
            # mode: the dispatch itself is async and costs no sync.
            pre = _build_precheck(g)
            closable = pre(
                self._cnt, self._mx, pos_d,
                jnp.arange(P, dtype=jnp.int32), jnp.int32(wm_c),
                jnp.asarray(valid),
            )
            if not bool(closable):
                self._drain_future()
                return

        if any(np.dtype(dt) not in (np.dtype(np.int32), np.dtype(np.float32))
               for _n, dt, _s in self._vfields):
            # the packed emission encoding bitcasts fields to int32 lanes;
            # wider dtypes keep the exact host path
            self._resolve_pending()
            return self._watermark_host_path(watermark, lo, hi, span,
                                             pos_pad, valid)

        # fused device path: gather + gap-merge scan + emit + purge in ONE
        # dispatch; emissions come back as one packed array. A P-slice span
        # closes at most P sessions per key, so M = P+1 cannot overflow;
        # wide spans cap M at 8 and keep the exact host path as fallback.
        can_overflow = P > 8
        M = 8 if can_overflow else P + 1
        run = _build_merge_scan(K, S, P, M, g, self._vfields, self._idents)
        old_state = (self._cnt, self._mn, self._mx, self._fields) \
            if can_overflow else None
        cnt2, mn2, mx2, flds2, packed = run(
            self._cnt, self._mn, self._mx, self._fields, pos_d,
            jnp.asarray(valid), jnp.int32(wm_c),
        )
        self._cnt, self._mn, self._mx, self._fields = cnt2, mn2, mx2, flds2
        entry = {
            "packed": packed, "lo": lo, "hi": hi, "M": M,
            "watermark": watermark, "old_state": old_state,
        }
        self._since_dispatch = None
        if self.defer_emissions and not can_overflow:
            if len(self._pending) >= 32:
                # bound the in-flight packed buffers (one sync per 32 scans)
                self._resolve_pending()
            self._pending.append(entry)
            if self._future:
                # parked records need the TRUE post-scan bounds now, or the
                # stale-bounds drain below re-parks them past further
                # watermark advances (which would late-drop them — a
                # divergence from sync mode)
                self._resolve_pending()
        else:
            self._resolve_pending()          # keep emission order
            self._resolve_entry(entry, last=True)
        self._drain_future()

    def _pad_span(self, lo: int, hi: int):
        """Span positions padded to a pow2 bucket so the jitted programs
        compile once per bucket size instead of retracing per span length."""
        span = hi - lo + 1
        P = 1 << (span - 1).bit_length()
        pos_pad = np.empty(P, dtype=np.int32)
        pos_pad[:span] = [(s % self.S) for s in range(lo, hi + 1)]
        pos_pad[span:] = pos_pad[span - 1]
        return P, pos_pad, np.arange(P) < span

    def _sync_superspan(self) -> None:
        """Resolve a pending fused-superspan entry before dispatching ANY
        new device work on top of it. Its resolve may overflow-replay:
        discard the fused lineage wholesale and rebuild state through the
        per-step path — so a merge scan dispatched meanwhile would resolve
        against the discarded lineage (duplicate emissions, corrupted ring
        bounds) and an ingest into it would be lost with it. The guard
        also keeps the superspan entry the ONLY pending entry when its
        overflow flag is read, which is what lets the replay restore
        `old_state` without reconciling later dispatches."""
        if any("superspan" in e for e in self._pending):
            self._resolve_pending()

    def _resolve_pending(self) -> None:
        pending, self._pending = self._pending, []
        for i, entry in enumerate(pending):
            self._resolve_entry(entry, last=(i == len(pending) - 1))
        if pending:
            # bounds are fresh now: records parked while they were stale can
            # re-enter (or be counted late), matching the sync path's order
            self._drain_future()

    def _resolve_entry(self, entry: dict, last: bool) -> None:
        """Pull one merge scan's packed emissions, append outputs, and (for
        the latest entry) refresh the ring bounds — merged with any ingest
        that happened after the scan was dispatched."""
        g = self.g
        M, lo = entry["M"], entry["lo"]
        arr = np.asarray(entry["packed"])
        lo_rel, hi_rel, ovf = int(arr[-1, 0]), int(arr[-1, 1]), int(arr[-1, 2])
        if ovf:
            if "superspan" in entry:
                # a key closed > M sessions across the fused superspan
                # (pathological re-filled-slice churn): discard the fused
                # result wholesale and replay the exact per-step path from
                # the retained pre-dispatch state
                (self._cnt, self._mn, self._mx, self._fields,
                 self.ring_lo, self.max_used,
                 self.current_watermark) = entry["old_state"]
                self._replay_superspan(*entry["superspan"])
                return
            # a key closed > M sessions in one scan (wide-span sync path
            # only): discard the fused results and redo exactly on host
            (self._cnt, self._mn, self._mx, self._fields) = entry["old_state"]
            hi = entry["hi"]
            _P, pos_pad, valid = self._pad_span(lo, hi)
            self._watermark_host_path(entry["watermark"], lo, hi,
                                      hi - lo + 1, pos_pad, valid)
            return
        body = arr[:-1]
        e_n = body[:, -1]
        total = int(e_n.sum())
        if total:
            es = body[:, 0:M]
            ee = body[:, M:2 * M]
            ec = body[:, 2 * M:3 * M]
            kk, mm_ = np.nonzero(np.arange(M)[None, :] < e_n[:, None])
            start_ts = lo * g + es[kk, mm_].astype(np.int64)
            end_ts = lo * g + ee[kk, mm_].astype(np.int64)
            cnts = ec[kk, mm_]
            fdict = {}
            for j, (name, dt, _s) in enumerate(self._vfields):
                block = np.ascontiguousarray(body[:, (3 + j) * M:(4 + j) * M])
                if np.dtype(dt) == np.float32:
                    block = block.view(np.float32)   # undo device bitcast
                elif np.dtype(dt) != np.int32:
                    block = block.astype(dt)
                fdict[name] = block[kk, mm_]
            for f in self.agg.fields:
                if f.source != VALUE:   # ONE-source fields carry the count
                    fdict[f.name] = cnts
            results = np.asarray(self.agg.extract(fdict))
            # fire order: merged-window end then key id (oracle's timers)
            order = np.lexsort((kk, end_ts))
            tracker = self.emission_tracker
            for i in order:
                window = TimeWindow(int(start_ts[i]), int(end_ts[i]) + g)
                if tracker is not None:
                    tracker.record_fire(window.end)
                self.output.append(
                    (self._key_of(int(kk[i])), window,
                     results[i].item(), window.max_timestamp())
                )
        if not last:
            return
        resolved = (lo + lo_rel, lo + hi_rel) if hi_rel >= 0 else None
        since = self._since_dispatch
        if resolved is None:
            merged = since
        elif since is None:
            merged = resolved
        else:
            merged = (min(resolved[0], since[0]), max(resolved[1], since[1]))
        self.ring_lo, self.max_used = merged if merged else (None, None)

    def _watermark_host_path(self, watermark: int, lo: int, hi: int,
                             span: int, pos_pad: np.ndarray,
                             valid: np.ndarray) -> None:
        """Exact host-side merge scan (the fused path's fallback for >M
        emissions per key per scan and for gap/span sizes beyond int32)."""
        g, S, K = self.g, self.S, self.K
        import jax.numpy as jnp

        pos_d = jnp.asarray(pos_pad)
        # pull only the resident span's columns (one gather + two transfers
        # instead of the full [K, S] state); padding columns are sliced off
        # host-side
        take = _build_take(len(self._vfields))

        ints_d, flds_d = take(self._cnt, self._mn, self._mx, self._fields,
                              pos_d)
        ints = np.asarray(ints_d)
        cnt = ints[0][:, :span]
        mn = ints[1][:, :span].astype(np.int64)
        mx = ints[2][:, :span].astype(np.int64)
        fields = [np.asarray(f)[:, :span] for f in flds_d]
        pos_arr = pos_pad[:span]

        # vectorized gap-merge scan over the resident slice span
        cur_open = np.zeros(K, dtype=bool)
        cur_min = np.zeros(K, dtype=np.int64)
        cur_max = np.zeros(K, dtype=np.int64)
        cur_cnt = np.zeros(K, dtype=np.int64)
        cur_fld = [np.full(K, ident) for ident in self._idents]
        cells = np.zeros((K, span), dtype=bool)   # current session's cells
        purge = np.zeros((K, span), dtype=bool)   # cells of emitted sessions
        emitted: List[Tuple[int, int, int, int, list]] = []  # per emit row

        def emit(mask: np.ndarray) -> None:
            for k in np.flatnonzero(mask):
                emitted.append((
                    int(cur_min[k]), int(cur_max[k]), k, int(cur_cnt[k]),
                    [f[k] for f in cur_fld],
                ))
            purge[mask] |= cells[mask]
            cells[mask] = False
            cur_open[mask] = False

        for i, s in enumerate(range(lo, hi + 1)):
            frag = cnt[:, i] > 0
            if not frag.any():
                continue
            fmn = s * g + mn[:, i]
            fmx = s * g + mx[:, i]
            # touching windows merge: [a, b) and [b, b+g) intersect per the
            # reference's TimeWindow.intersects ("just after or before"),
            # so the merge condition is gap <= g, strict only beyond it
            joins = cur_open & frag & (fmn - cur_max <= g)
            breaks = cur_open & frag & ~joins
            # a later fragment with gap >= g proves the session closed
            emit(breaks)
            starts = frag & ~joins
            cur_min[starts] = fmn[starts]
            cur_cnt[starts] = 0
            for cf, ident in zip(cur_fld, self._idents):
                cf[starts] = ident
            cur_open |= frag
            cur_max[frag] = fmx[frag]
            cur_cnt[frag] += cnt[:, i][frag]
            for cf, f, (_n, _dt, scatter) in zip(cur_fld, fields, self._vfields):
                cf[frag] = _NP_COMBINE[scatter](cf[frag], f[:, i][frag])
            cells[frag, i] = True

        # sessions whose gap the watermark itself proves
        emit(cur_open & (cur_max + g - 1 <= watermark))

        if emitted:
            # fire order: by merged-window end then key id (deterministic,
            # matching the oracle's timer ordering)
            emitted.sort(key=lambda e: (e[1] + g, e[2]))
            names = [n for n, _dt, _s in self._vfields]
            one_names = [
                f.name for f in self.agg.fields if f.source != VALUE
            ]
            tracker = self.emission_tracker
            for mn_ts, mx_ts, k, c, fvals in emitted:
                window = TimeWindow(mn_ts, mx_ts + g)
                if tracker is not None:
                    tracker.record_fire(window.end)
                fdict = dict(zip(names, fvals))
                for n in one_names:  # ONE-source fields carry the count
                    fdict[n] = c
                result = self.agg.extract(fdict)
                self.output.append(
                    (self._key_of(k), window,
                     np.asarray(result).item(), window.max_timestamp())
                )
            # scatter the span purge back to ring coordinates (span <= S so
            # each position appears once)
            keep_full = np.ones((K, S), dtype=bool)
            keep_full[:, pos_arr] = ~purge
            run = _build_purge(
                self.K, S, len(self._vfields), self._idents,
                tuple(dt for _n, dt, _s in self._vfields), g,
            )
            self._cnt, self._mn, self._mx, self._fields = run(
                self._cnt, self._mn, self._mx, self._fields, keep_full,
            )
            cnt = np.where(purge, 0, cnt)

        # advance the resident span to the surviving fragments
        live_cols = cnt.any(axis=0)
        alive_abs = [s for i, s in enumerate(range(lo, hi + 1)) if live_cols[i]]
        if alive_abs:
            self.ring_lo = min(alive_abs)
            self.max_used = max(alive_abs)
        else:
            self.ring_lo = None
            self.max_used = None
        self._drain_future()

    def _drain_future(self) -> None:
        if not self._future:
            return
        lo = self.ring_lo
        pending, self._future = self._future, []
        ready_k, ready_v, ready_t = [], [], []
        for k, v, t in pending:
            s = t // self.g
            if lo is None or s < lo + self.S:
                ready_k.append(k)
                ready_v.append(v)
                ready_t.append(t)
                if lo is None:
                    lo = s
            else:
                self._future.append((k, v, t))
        if ready_k:
            self.process_batch(
                np.asarray(ready_k), np.asarray(ready_v, dtype=np.float32),
                np.asarray(ready_t, dtype=np.int64),
            )

    def advance_processing_time(self, time: int) -> None:  # pragma: no cover
        raise NotImplementedError("event-time only")

    def drain_output(self) -> List[Tuple[Any, Any, Any, int]]:
        if self._pending:
            self._resolve_pending()
        out = self.output
        self.output = []
        return out

    # -- observability gauges ------------------------------------------
    def state_bytes(self) -> int:
        n = sum(int(getattr(a, "nbytes", 0))
                for a in (self._cnt, self._mn, self._mx))
        n += sum(int(getattr(f, "nbytes", 0)) for f in self._fields)
        return n

    def state_key_count(self) -> int:
        return len(self.keydict)

    # ------------------------------------------------------------------
    def snapshot(self) -> dict:
        if self._pending:
            self._resolve_pending()
        return {
            "cnt": np.asarray(self._cnt),
            "mn": np.asarray(self._mn),
            "mx": np.asarray(self._mx),
            "fields": [np.asarray(f) for f in self._fields],
            "keys": self.keydict.snapshot(),
            "watermark": self.current_watermark,
            "ring_lo": self.ring_lo,
            "max_used": self.max_used,
            "future": [(k, float(v), int(t)) for k, v, t in self._future],
            "num_late_dropped": self.num_late_records_dropped,
            "dense": getattr(self, "_dense", False),
        }

    def restore(self, snap: dict) -> None:
        import jax.numpy as jnp

        self._cnt = jnp.asarray(snap["cnt"])
        self._mn = jnp.asarray(snap["mn"])
        self._mx = jnp.asarray(snap["mx"])
        self._fields = tuple(jnp.asarray(f) for f in snap["fields"])
        self.K = int(self._cnt.shape[0])
        self.keydict = KeyDictionary.restore(snap["keys"])
        self.current_watermark = snap["watermark"]
        self.ring_lo = snap["ring_lo"]
        self.max_used = snap["max_used"]
        self._future = list(snap["future"])
        self.num_late_records_dropped = snap["num_late_dropped"]
        self._dense = snap.get("dense", False)
        # in-flight deferred scans belong to the pre-restore timeline:
        # resolving them against restored state would replay emissions and
        # corrupt the restored ring bounds
        self._pending = []
        self._since_dispatch = None
